"""Device-resident columnar vectors and batches.

TPU-native analogue of the reference's columnar data layer
(sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java and
cuDF's column model): a column is one or more flat device buffers plus a
validity mask. The decisive architectural difference from cuDF is that XLA
wants **static shapes**, so every batch here carries a static ``capacity``
and a (possibly traced) ``num_rows`` scalar:

- rows ``[0, num_rows)`` are live; rows beyond are dead padding,
- all kernels compute over the full capacity and mask with
  ``live_mask(capacity, num_rows)`` where results would otherwise leak,
- operations that change cardinality (filter, join, aggregate) keep the
  same capacity and only move ``num_rows`` — no recompilation, and XLA
  sees one fixed program per capacity bucket.

Strings use the Arrow/cuDF layout: ``offsets:int32[capacity+1]`` into a
flat ``chars:uint8[char_capacity]`` buffer.

ColumnVector / StringColumn / ColumnarBatch are registered as JAX pytrees so
whole batches flow through ``jax.jit`` / ``shard_map`` untouched.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt


def live_mask(capacity: int, num_rows) -> jax.Array:
    """bool[capacity] mask of live rows."""
    return jnp.arange(capacity, dtype=jnp.int32) < num_rows


def rows_from_offsets(starts: jax.Array, lens: jax.Array,
                      out_size: int) -> jax.Array:
    """Owning row per flat element position.

    Row r owns positions [starts[r], starts[r]+lens[r]); spans are
    contiguous and ascending (the Arrow offsets invariant). Returns
    int32[out_size] with positions past the last span mapping to the
    last row (callers mask with a total-length check). Implemented as
    scatter-max + cummax — two linear passes, replacing the
    searchsorted formulation whose log-factor passes dominated every
    string repack at batch scale."""
    n = starts.shape[0]
    # only rows that own at least one byte mark their start; at a shared
    # start position the non-empty row is the max index by construction
    mark = jnp.full(out_size, -1, jnp.int32).at[
        jnp.where(lens > 0, starts, out_size)].max(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    row = jax.lax.cummax(mark)
    return jnp.clip(row, 0, n - 1)


def compaction_indices(keep: jax.Array) -> jax.Array:
    """Stable-compaction gather map: entry j (for j < sum(keep)) is the
    position of the j-th kept row; tail entries are 0 (callers mask dead
    output rows, so the duplicated row-0 gather is harmless). cumsum +
    scatter — replaces ``argsort(~keep)`` whose full sort cost dominated
    every filter/compact on batches at capacity scale."""
    cap = keep.shape[0]
    slot = jnp.cumsum(keep.astype(jnp.int32)) - 1
    return jnp.zeros(cap, jnp.int32).at[
        jnp.where(keep, slot, cap)].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")


class ColumnVector:
    """A flat primitive column: data buffer + validity mask.

    ``validity[i] == True`` means row i is non-null. Dead rows (beyond the
    owning batch's num_rows) must have ``validity == False``; data there is
    zeroed so reductions can use data*validity without masking twice.
    """

    __slots__ = ("data", "validity", "dtype")

    def __init__(self, data: jax.Array, validity: jax.Array, dtype: dt.DType):
        self.data = data
        self.validity = validity
        self.dtype = dtype

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def with_validity(self, validity: jax.Array) -> "ColumnVector":
        return ColumnVector(self.data, validity, self.dtype)

    def gather(self, indices: jax.Array, valid: Optional[jax.Array] = None) -> "ColumnVector":
        """Gather rows; out-of-range/invalid gather slots become null.

        Mirrors cuDF ``Table.gather`` + GatherMap semantics used throughout
        the reference's join/sort paths (JoinGatherer.scala).
        """
        safe = jnp.clip(indices, 0, self.capacity - 1)
        data = jnp.take(self.data, safe, axis=0)
        validity = jnp.take(self.validity, safe, axis=0)
        if valid is not None:
            validity = validity & valid
            data = jnp.where(valid, data, jnp.zeros_like(data))
        return ColumnVector(data, validity, self.dtype)

    def to_numpy(self, num_rows: Optional[int] = None):
        """Host copy of live values as a (values, mask) pair."""
        n = self.capacity if num_rows is None else int(num_rows)
        return np.asarray(self.data)[:n], np.asarray(self.validity)[:n]

    def __repr__(self):
        return f"ColumnVector({self.dtype}, capacity={self.capacity})"


class StringColumn:
    """Variable-length UTF-8 column: offsets into a flat byte buffer.

    Arrow/cuDF string layout. ``offsets`` has capacity+1 entries; row i's
    bytes are chars[offsets[i]:offsets[i+1]]. Dead/null rows have
    zero-length extents so kernels never touch garbage bytes.

    ``pad_bucket`` is a static power-of-two upper bound on the longest
    string in the column. Column-to-column comparison, sorting, and
    hashing lower strings to a (capacity, pad_bucket) fixed-width view;
    keeping the bound static+bucketed bounds XLA recompiles.
    """

    __slots__ = ("offsets", "chars", "validity", "dtype", "pad_bucket")

    def __init__(self, offsets: jax.Array, chars: jax.Array, validity: jax.Array,
                 pad_bucket: int = 64):
        self.offsets = offsets
        self.chars = chars
        self.validity = validity
        self.dtype = dt.STRING
        self.pad_bucket = pad_bucket

    @property
    def capacity(self) -> int:
        return self.offsets.shape[0] - 1

    @property
    def char_capacity(self) -> int:
        return self.chars.shape[0]

    def lengths(self) -> jax.Array:
        return self.offsets[1:] - self.offsets[:-1]

    def with_validity(self, validity: jax.Array) -> "StringColumn":
        return StringColumn(self.offsets, self.chars, validity, self.pad_bucket)

    def padded(self) -> jax.Array:
        """(capacity, pad_bucket) uint8 fixed-width view, zero padded.

        The workhorse lowering for string compare/sort/hash kernels —
        zero never appears inside UTF-8 text, so byte-wise lexicographic
        order on the padded view equals string order.
        """
        cap = self.capacity
        starts = self.offsets[:-1]
        lens = self.lengths()
        k = jnp.arange(self.pad_bucket, dtype=jnp.int32)
        idx = starts[:, None] + k[None, :]
        take = jnp.take(self.chars, jnp.clip(idx, 0, self.char_capacity - 1))
        return jnp.where(k[None, :] < lens[:, None], take, jnp.zeros((), jnp.uint8))

    def gather(self, indices: jax.Array, valid: Optional[jax.Array] = None,
               out_char_capacity: Optional[int] = None,
               unique: bool = False) -> "StringColumn":
        """Gather string rows, repacking bytes into a new flat buffer.

        The output has ``len(indices)`` rows. The default output byte
        buffer is ``len(indices) * pad_bucket`` rounded to a power of
        two — a hard upper bound (every row is at most pad_bucket
        bytes), so duplicating gathers (joins with repeated keys,
        cross-pair replication) can never overflow-truncate.
        ``unique=True`` (permutations/compactions: each source row used
        at most once) keeps the tight source-sized buffer instead —
        total gathered bytes can't exceed the source total.
        """
        src_cap = self.capacity
        out_cap = indices.shape[0]
        if out_char_capacity is not None:
            nbytes_cap = out_char_capacity
        elif unique:
            nbytes_cap = self.char_capacity
        else:
            nbytes_cap = round_pow2(max(out_cap * self.pad_bucket, 128))
        safe = jnp.clip(indices, 0, src_cap - 1)
        starts = jnp.take(self.offsets[:-1], safe)
        lens = jnp.take(self.lengths(), safe)
        validity = jnp.take(self.validity, safe)
        if valid is not None:
            validity = validity & valid
            lens = jnp.where(valid, lens, 0)
        # Truncate rows that would start past the output buffer: they
        # become empty rather than corrupting neighbours.
        ends = jnp.cumsum(lens, dtype=jnp.int32)
        lens = jnp.where(ends <= nbytes_cap, lens, 0)
        new_offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        # Repack: for each output byte position find its row (linear
        # scatter+cummax scan), then index into the source chars buffer.
        pos = jnp.arange(nbytes_cap, dtype=jnp.int32)
        row_c = rows_from_offsets(new_offsets[:-1], lens, nbytes_cap)
        within = pos - jnp.take(new_offsets, row_c)
        src = jnp.take(starts, row_c) + within
        total = new_offsets[out_cap]
        new_chars = jnp.where(
            pos < total,
            jnp.take(self.chars, jnp.clip(src, 0, self.char_capacity - 1)),
            jnp.zeros((), jnp.uint8))
        return StringColumn(new_offsets, new_chars, validity, self.pad_bucket)

    def to_numpy(self, num_rows: Optional[int] = None):
        n = self.capacity if num_rows is None else int(num_rows)
        offs = np.asarray(self.offsets)
        chars = np.asarray(self.chars).tobytes()
        vals = np.array(
            [chars[offs[i]:offs[i + 1]].decode("utf-8", errors="replace") for i in range(n)],
            dtype=object)
        return vals, np.asarray(self.validity)[:n]

    def __repr__(self):
        return f"StringColumn(capacity={self.capacity}, char_capacity={self.char_capacity})"


Column = Union[ColumnVector, StringColumn]


class ColumnarBatch:
    """A batch of named columns with static capacity and dynamic num_rows.

    The unit that flows through the operator pipeline — the analogue of
    Spark's ColumnarBatch of GpuColumnVectors (RDD[ColumnarBatch] in the
    reference, SURVEY §1 L2). ``num_rows`` may be a Python int (host side)
    or a traced int32 scalar (inside jit).
    """

    __slots__ = ("columns", "names", "num_rows")

    def __init__(self, columns: Sequence[Column], names: Sequence[str], num_rows):
        assert len(columns) == len(names)
        self.columns = list(columns)
        self.names = list(names)
        self.num_rows = num_rows

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def live_mask(self) -> jax.Array:
        return live_mask(self.capacity, self.num_rows)

    def with_columns(self, columns: Sequence[Column], names: Sequence[str]) -> "ColumnarBatch":
        return ColumnarBatch(columns, names, self.num_rows)

    def select(self, names: Sequence[str]) -> "ColumnarBatch":
        return ColumnarBatch([self.column(n) for n in names], list(names), self.num_rows)

    def gather(self, indices: jax.Array, new_num_rows,
               unique: bool = False) -> "ColumnarBatch":
        """Gather rows by index; indices beyond new_num_rows produce dead
        rows. ``unique=True`` = permutation/compaction (no source row
        duplicated): string columns keep their tight byte buffers."""
        cap = indices.shape[0]
        valid = live_mask(cap, new_num_rows)

        def g(c):
            from .nested import ListColumn
            if isinstance(c, (StringColumn, ListColumn)):
                return c.gather(indices, valid, unique=unique)
            return c.gather(indices, valid)
        cols = [g(c) for c in self.columns]
        return ColumnarBatch(cols, self.names, new_num_rows)

    def schema(self):
        return [(n, c.dtype) for n, c in zip(self.names, self.columns)]

    def __repr__(self):
        cols = ", ".join(f"{n}:{c.dtype}" for n, c in zip(self.names, self.columns))
        return f"ColumnarBatch[{cols}](capacity={self.capacity}, num_rows={self.num_rows})"


# ---------------------------------------------------------------------------
# pytree registrations: batches flow through jit/shard_map as containers.
# ---------------------------------------------------------------------------

def _cv_flatten(v: ColumnVector):
    return (v.data, v.validity), v.dtype


def _cv_unflatten(dtype, children):
    data, validity = children
    return ColumnVector(data, validity, dtype)


jax.tree_util.register_pytree_node(ColumnVector, _cv_flatten, _cv_unflatten)


def _sc_flatten(v: StringColumn):
    return (v.offsets, v.chars, v.validity), v.pad_bucket


def _sc_unflatten(pad_bucket, children):
    return StringColumn(*children, pad_bucket=pad_bucket)


jax.tree_util.register_pytree_node(StringColumn, _sc_flatten, _sc_unflatten)


def _cb_flatten(b: ColumnarBatch):
    return (tuple(b.columns), b.num_rows), tuple(b.names)


def _cb_unflatten(names, children):
    columns, num_rows = children
    return ColumnarBatch(list(columns), list(names), num_rows)


jax.tree_util.register_pytree_node(ColumnarBatch, _cb_flatten, _cb_unflatten)


# ---------------------------------------------------------------------------
# Host <-> device construction
# ---------------------------------------------------------------------------

def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def round_pow2(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (>= minimum). THE bucketing helper:
    capacities and string pad buckets all come from here so the XLA
    recompile behavior stays consistent across construction paths."""
    cap = max(minimum, 1)
    while cap < n:
        cap *= 2
    return cap


def choose_capacity(n: int, minimum: int = 8) -> int:
    """Bucket row counts to powers of two so XLA compiles once per bucket.

    This is the static-shape answer to cuDF's fully dynamic batch sizes
    (SURVEY §7 hard-part #1): a handful of capacity buckets means a handful
    of compiled programs, amortized across the whole query.
    """
    return round_pow2(n, minimum)


def _encode_strings(values, valid: np.ndarray, n: int):
    """utf-8 encode a host string column -> (lengths[int32], bytes).
    Invalid/None slots encode as zero-length. The hot path hands the
    whole column to pyarrow (C-speed layout) instead of per-row Python
    encode; anything pyarrow rejects (mixed/str-coercible objects)
    falls back to the per-row loop."""
    import pyarrow as pa
    vals = values.tolist() if isinstance(values, np.ndarray) else list(values)
    if not valid.all():
        vals = [v if (m and v is not None) else None
                for v, m in zip(vals, valid)]
    try:
        arr = pa.array(vals, type=pa.string(), from_pandas=True)
    except (pa.lib.ArrowInvalid, pa.lib.ArrowTypeError):
        encoded = [b"" if not valid[i] or vals[i] is None
                   else str(vals[i]).encode("utf-8") for i in range(n)]
        lens = np.fromiter((len(e) for e in encoded), dtype=np.int32,
                           count=n)
        return lens, np.frombuffer(b"".join(encoded), dtype=np.uint8)
    off_buf, data_buf = arr.buffers()[1], arr.buffers()[2]
    off = np.frombuffer(off_buf, dtype=np.int32)[
        arr.offset:arr.offset + n + 1]
    lens = np.diff(off)
    data = (np.frombuffer(data_buf, dtype=np.uint8)[off[0]:off[n]]
            if data_buf is not None and n else np.empty(0, np.uint8))
    # null slots in an arrow array built from python lists carry
    # zero-length extents already, matching the engine invariant
    return lens.astype(np.int32), data


def column_from_numpy(values: np.ndarray, capacity: int,
                      dtype: Optional[dt.DType] = None,
                      mask: Optional[np.ndarray] = None) -> Column:
    """Build a device column from host values (+ optional null mask)."""
    n = len(values)
    assert capacity >= n
    if dtype is None:
        dtype = dt.from_numpy_dtype(values.dtype)
    valid = np.ones(n, dtype=bool) if mask is None else np.asarray(mask, dtype=bool)

    if dtype == dt.STRING:
        lens, data = _encode_strings(values, valid, n)
        offsets = np.zeros(capacity + 1, dtype=np.int32)
        offsets[1:n + 1] = np.cumsum(lens)
        offsets[n + 1:] = offsets[n]
        total = int(offsets[n])
        char_cap = max(_round_up(total, 128), 128)
        chars = np.zeros(char_cap, dtype=np.uint8)
        if total:
            chars[:total] = data[:total]
        validity = np.zeros(capacity, dtype=bool)
        validity[:n] = valid
        max_len = int(lens.max()) if n else 0
        return StringColumn(jnp.asarray(offsets), jnp.asarray(chars), jnp.asarray(validity),
                            pad_bucket=round_pow2(max_len))

    if isinstance(dtype, dt.DecimalType) and dtype.is_wide:
        from .decimal128 import from_unscaled_ints
        unscaled = [None if not valid[i] or values[i] is None
                    else _to_physical(values[i], dtype) for i in range(n)]
        return from_unscaled_ints(unscaled, capacity, dtype, mask=valid)

    phys = np.dtype(dtype.physical)
    data = np.zeros(capacity, dtype=phys)
    vals = np.asarray(values)
    if vals.dtype == object:
        vals = np.array([0 if (v is None) else _to_physical(v, dtype) for v in vals],
                        dtype=phys)
    data[:n] = np.where(valid, vals.astype(phys, copy=False), np.zeros(1, dtype=phys))
    validity = np.zeros(capacity, dtype=bool)
    validity[:n] = valid
    return ColumnVector(jnp.asarray(data), jnp.asarray(validity), dtype)


def _to_physical(v, dtype: dt.DType):
    """Convert one Python value to the physical lane representation."""
    import datetime
    import decimal
    if isinstance(dtype, dt.TimestampType):
        if isinstance(v, datetime.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            return int(v.timestamp() * 1_000_000)
        return int(v)
    if isinstance(dtype, dt.DateType):
        if isinstance(v, datetime.date) and not isinstance(v, datetime.datetime):
            return (v - datetime.date(1970, 1, 1)).days
        return int(v)
    if isinstance(dtype, dt.DecimalType):
        if isinstance(v, decimal.Decimal):
            return int(v.scaleb(dtype.scale).to_integral_value())
        if isinstance(v, float):
            return int(round(v * 10 ** dtype.scale))
        return int(v) * 10 ** dtype.scale
    return v


def batch_from_pydict(data: dict, capacity: Optional[int] = None,
                      schema: Optional[List] = None) -> ColumnarBatch:
    """Build a ColumnarBatch from {name: list/ndarray}; None entries are null."""
    names = list(data.keys())
    n = len(next(iter(data.values()))) if data else 0
    cap = capacity or choose_capacity(n)
    cols = []
    for i, name in enumerate(names):
        values = data[name]
        dtype = None
        if schema is not None:
            dtype = dict(schema).get(name)
        arr = np.asarray(values, dtype=object)
        mask = np.array([v is not None for v in arr], dtype=bool)
        if dtype is None:
            sample = next((v for v in arr if v is not None), None)
            if isinstance(sample, str):
                dtype = dt.STRING
            elif isinstance(sample, bool):
                dtype = dt.BOOL
            elif isinstance(sample, (int, np.integer)):
                dtype = dt.INT64
            elif isinstance(sample, (float, np.floating)):
                dtype = dt.FLOAT64
            else:
                dtype = dt.INT64
        cols.append(column_from_numpy(arr, cap, dtype=dtype, mask=mask))
    return ColumnarBatch(cols, names, n)


def from_physical(v, dtype: dt.DType):
    """Convert one physical lane value back to its Python representation."""
    import datetime
    import decimal
    if hasattr(v, "item"):
        v = v.item()
    if isinstance(dtype, dt.DateType):
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
    if isinstance(dtype, dt.TimestampType):
        return datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc) + \
            datetime.timedelta(microseconds=int(v))
    if isinstance(dtype, dt.DecimalType):
        return decimal.Decimal(int(v)).scaleb(-dtype.scale)
    return v


def batch_to_pydict(batch: ColumnarBatch) -> dict:
    """Host copy of live rows; nulls become None."""
    n = int(batch.num_rows)
    out = {}
    for name, col in zip(batch.names, batch.columns):
        vals, mask = col.to_numpy(n)
        out[name] = [from_physical(vals[i], col.dtype) if mask[i] else None
                     for i in range(n)]
    return out
