"""Fused filter+aggregate lowering onto the pallas tile_reduce kernel.

A global (no grouping keys) HashAggregateExec whose aggregates — and,
when its child is a FilterExec, the filter predicate too — are simple
numeric expressions executes here as ONE pallas pass per input batch:
predicate, projections, and partial reduction all evaluate in VMEM, so
each input column crosses HBM exactly once and no filtered intermediate
batch is ever materialized. This is the TPU counterpart of the
reference's fused cuDF reduction path for q6-shaped queries
(GpuAggregateExec.scala AggHelper update pass over a filtered iterator).

Numerics: on TPU the kernel computes in float32 (float64 inputs and
float64 literals are demoted before tracing — Mosaic has no f64), with
per-tile partials combined in emulated float64 outside the kernel; on
CPU (pallas interpret mode, used by the test lane) everything stays
float64, so differential tests check the exact Spark semantics. The
float32 tile arithmetic on TPU is the same class of deviation the
reference ships behind spark.rapids.sql.variableFloatAgg.enabled.

The gate is static and conservative: unsupported aggregate/expression
shapes simply keep the stock XLA path. A one-time warmup compile on a
tiny synthetic batch guards against Mosaic lowering gaps at runtime —
if it fails, the exec permanently falls back before consuming its child.
"""

from __future__ import annotations

import copy
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnVector, ColumnarBatch
from ..expr import aggregates as Agg
from ..expr import arithmetic as A
from ..expr import core as E
from ..expr import predicates as Pr
from ..expr.cast import Cast
from ..ops import pallas_kernels as PK

_SAFE_NODES = (
    E.ColumnRef, E.Literal, E.Alias, Cast,
    A.Add, A.Subtract, A.Multiply, A.Divide, A.UnaryMinus,
    A.UnaryPositive, A.Abs, A.Least, A.Greatest,
    Pr.EqualTo, Pr.LessThan, Pr.GreaterThan, Pr.LessThanOrEqual,
    Pr.GreaterThanOrEqual, Pr.EqualNullSafe, Pr.And, Pr.Or, Pr.Not,
    Pr.IsNull, Pr.IsNotNull, Pr.IsNaN, Pr.InSet,
)
_SAFE_DTYPES = (dt.BOOL, dt.INT8, dt.INT16, dt.INT32, dt.DATE,
                dt.FLOAT32, dt.FLOAT64)
_FLOATY = (dt.FLOAT32, dt.FLOAT64)
# min/max must be exact in a float32 lane on TPU: floats are closed
# under min/max, DATE/INT16/INT8 values are < 2^24
_MINMAX_DTYPES = (dt.FLOAT32, dt.FLOAT64, dt.DATE, dt.INT8, dt.INT16)


class _PaddedStrPred(E.Expression):
    """Kernel-side string predicate over the padded byte-lane view —
    the string-predicate kernel family (reference: cuDF string
    comparison kernels feeding filtered reductions). The referenced
    column's (tile, W) char block + lengths + validity ride the kernel
    batch's ``str_lanes``; comparison is pure VPU byte arithmetic in
    VMEM, so dim-filter predicates like cd_gender='M' fuse into the
    single-pass reduction."""

    def __init__(self, name: str, choices: Sequence[bytes],
                 prefix: bool = False):
        super().__init__()
        self.name = name
        self.choices = [bytes(c) for c in choices]
        self.prefix = prefix

    def data_type(self, schema) -> dt.DType:
        return dt.BOOL

    def references(self) -> set:
        return {self.name}

    def eval(self, batch) -> ColumnVector:
        chars, lens, valid = batch.str_lanes[self.name]
        tile, w = chars.shape
        hit = jnp.zeros(tile, jnp.bool_)
        for lit in self.choices:
            m = len(lit)
            if m > w:
                continue  # longer than any string in this batch
            eq = jnp.ones(tile, jnp.bool_)
            for j in range(m):  # m is tiny (literal length)
                # python-int scalars: array constants can't be
                # captured inside a pallas kernel trace
                eq = eq & (chars[:, j].astype(jnp.int32) == lit[j])
            if self.prefix:
                eq = eq & (lens >= m)
            else:
                eq = eq & (lens == m)
            hit = hit | eq
        return ColumnVector(hit, valid, dt.BOOL)

    def __repr__(self):
        op = "startswith" if self.prefix else "in"
        return f"{self.name} {op} {self.choices!r}"


class _PaddedStrNull(E.Expression):
    """IS [NOT] NULL over a kernel-batch string column."""

    def __init__(self, name: str, negated: bool):
        super().__init__()
        self.name = name
        self.negated = negated

    def data_type(self, schema) -> dt.DType:
        return dt.BOOL

    def references(self) -> set:
        return {self.name}

    def eval(self, batch) -> ColumnVector:
        _, _, valid = batch.str_lanes[self.name]
        data = valid if self.negated else ~valid
        return ColumnVector(data, jnp.ones_like(valid), dt.BOOL)


def _rewrite_string_preds(pred: E.Expression, schema):
    """Replace eligible string predicate subtrees (col = 'lit',
    col IN ('a','b'), startswith, IS [NOT] NULL) with kernel-lane
    nodes; returns (rewritten, {string column names}) — or (pred,
    set()) unchanged when nothing matched."""
    from ..expr import strings as S
    schema_d = dict(schema)
    found: set = set()

    def is_str_ref(e):
        return isinstance(e, E.ColumnRef) and \
            schema_d.get(e.name) == dt.STRING

    def rw(e: E.Expression):
        if isinstance(e, Pr.EqualTo):
            l, r = e.children
            if is_str_ref(l) and isinstance(r, E.Literal) and \
                    isinstance(r.value, str):
                found.add(l.name)
                return _PaddedStrPred(l.name, [r.value.encode()])
            if is_str_ref(r) and isinstance(l, E.Literal) and \
                    isinstance(l.value, str):
                found.add(r.name)
                return _PaddedStrPred(r.name, [l.value.encode()])
        if isinstance(e, Pr.InSet) and is_str_ref(e.children[0]) and \
                all(isinstance(v, str) for v in e.values):
            found.add(e.children[0].name)
            return _PaddedStrPred(e.children[0].name,
                                  [v.encode() for v in e.values])
        if isinstance(e, S.StartsWith) and is_str_ref(e.children[0]):
            found.add(e.children[0].name)
            return _PaddedStrPred(e.children[0].name,
                                  [e.prefix.encode()], prefix=True)
        if isinstance(e, (Pr.IsNull, Pr.IsNotNull)) and \
                is_str_ref(e.children[0]):
            found.add(e.children[0].name)
            return _PaddedStrNull(e.children[0].name,
                                  isinstance(e, Pr.IsNotNull))
        if not e.children:
            return e
        out = copy.copy(e)
        out.children = [rw(c) for c in e.children]
        return out

    return rw(pred), found


def _expr_safe(expr: E.Expression, schema, no_f64: bool = False) -> bool:
    """``schema`` is the Schema list ([(name, dtype)]) data_type wants.
    ``no_f64`` additionally rejects any float64-typed subexpression —
    used for TPU filter predicates, where demoting to float32 would
    change which ROWS pass (not just low-order sum bits, the only
    deviation srt.sql.pallas.enabled's contract covers)."""
    if isinstance(expr, (_PaddedStrPred, _PaddedStrNull)):
        return True  # pure byte-lane VPU arithmetic, exact
    if not isinstance(expr, _SAFE_NODES):
        return False
    if isinstance(expr, E.Literal) and expr.value is None:
        return False
    try:
        t = expr.data_type(schema)
        if t not in _SAFE_DTYPES or (no_f64 and t == dt.FLOAT64):
            return False
    except Exception:
        return False
    return all(_expr_safe(c, schema, no_f64) for c in expr.children)


def _demote_f64(expr: E.Expression) -> E.Expression:
    """float64 -> float32 rewrite for the TPU kernel trace (Mosaic has
    no f64). Column data itself is cast outside the kernel; this fixes
    the literals/casts inside the tree so no f64 op is ever traced."""
    if isinstance(expr, E.Literal) and expr.dtype == dt.FLOAT64:
        return E.Literal(float(np.float32(expr.value)), dt.FLOAT32)
    if isinstance(expr, Cast) and expr.to == dt.FLOAT64:
        return Cast(_demote_f64(expr.children[0]), dt.FLOAT32, expr.ansi)
    kids = [_demote_f64(c) for c in expr.children]
    if all(a is b for a, b in zip(kids, expr.children)):
        return expr
    clone = copy.copy(expr)
    clone.children = kids
    return clone


def _collect_refs(exprs, names: set) -> None:
    for e in exprs:
        if isinstance(e, E.ColumnRef):
            names.add(e.name)
        _collect_refs(e.children, names)


class _KernelBatch(ColumnarBatch):
    """Shim batch for tracing expressions inside the kernel: live_mask
    comes from a block input instead of an iota (Mosaic-unfriendly)."""

    def __init__(self, columns, names, num_rows, live):
        super().__init__(columns, names, num_rows)
        self._live = live

    def live_mask(self):
        return self._live


class PallasAggPlan:
    """Static lowering of (pred, agg_exprs) onto tile_reduce outputs."""

    def __init__(self, agg_exprs, input_schema, pred: Optional[E.Expression]):
        self.input_schema = input_schema
        schema = list(input_schema)
        self.str_names: List[str] = []
        if pred is not None:
            pred, snames = _rewrite_string_preds(pred, schema)
            self.str_names = sorted(snames)
        self.pred = pred
        demote = PK.on_tpu()
        self._prep = _demote_f64 if demote else (lambda e: e)
        self.kinds: List[str] = []
        # per agg: list of (state_name, slot_index, state_dtype)
        self.agg_slots: List[List[Tuple[str, int, dt.DType]]] = []
        self._builders: List[Callable] = []
        refs: set = set()
        if pred is not None:
            _collect_refs([pred], refs)
            refs -= set(self.str_names)  # ride str_lanes, not columns
        for fn, _name in agg_exprs:
            in_t = (fn.children[0].data_type(schema)
                    if fn.children else None)
            slots = []
            if isinstance(fn, (Agg.Sum, Agg.Average)):
                slots.append(("sum", self._slot(PK.SUM), dt.FLOAT64))
                slots.append(("count", self._slot(PK.SUM), dt.INT64))
                self._builders.append(self._masked_sum(fn))
            elif isinstance(fn, Agg.CountStar):
                slots.append(("count", self._slot(PK.SUM), dt.INT64))
                self._builders.append(self._count_star())
            elif isinstance(fn, Agg.Count):
                slots.append(("count", self._slot(PK.SUM), dt.INT64))
                self._builders.append(self._count(fn))
            elif isinstance(fn, (Agg.Min, Agg.Max)):
                kind = PK.MAX if fn.largest else PK.MIN
                slots.append((fn._key, self._slot(kind), in_t))
                slots.append(("seen", self._slot(PK.SUM), dt.BOOL))
                is_float = in_t in (dt.FLOAT32, dt.FLOAT64)
                if is_float:
                    # Spark float order puts NaN GREATEST: the kernel
                    # reduces non-NaN lanes only and this count
                    # restores NaN afterwards (any-NaN => max is NaN;
                    # all-NaN => min is NaN) — mirrors
                    # _MinMaxBase._float_reduce
                    slots.append(("_nan", self._slot(PK.SUM),
                                  dt.FLOAT64))
                self._builders.append(self._minmax(fn, kind,
                                                   with_nan=is_float))
            else:
                raise AssertionError(type(fn))
            self.agg_slots.append(slots)
        _collect_refs([fn for fn, _ in agg_exprs], refs)
        self.ref_names = sorted(refs)

    def _slot(self, kind: str) -> int:
        self.kinds.append(kind)
        return len(self.kinds) - 1

    # --- per-aggregate value builders (traced inside the kernel) ---
    def _masked_sum(self, fn):
        expr = self._prep(fn.children[0])

        def build(batch, mask):
            c = expr.eval(batch)
            m = mask & c.validity
            zero = jnp.zeros((), c.data.dtype)
            return [jnp.where(m, c.data, zero), m.astype(jnp.float32)]
        return build

    def _count_star(self):
        def build(batch, mask):
            return [mask.astype(jnp.float32)]
        return build

    def _count(self, fn):
        expr = self._prep(fn.children[0])

        def build(batch, mask):
            c = expr.eval(batch)
            return [(mask & c.validity).astype(jnp.float32)]
        return build

    def _minmax(self, fn, kind, with_nan: bool):
        expr = self._prep(fn.children[0])

        def build(batch, mask):
            c = expr.eval(batch)
            m = mask & c.validity
            fill = jnp.asarray(PK.reduce_identity(kind, c.data.dtype),
                               c.data.dtype)
            if not with_nan:
                return [jnp.where(m, c.data, fill),
                        m.astype(jnp.float32)]
            nan = jnp.isnan(c.data)
            return [jnp.where(m & ~nan, c.data, fill),
                    m.astype(jnp.float32),
                    (m & nan).astype(jnp.float32)]
        return build

    # --- the fused per-batch function (jit this) ---
    def batch_fn(self):
        schema_d = dict(self.input_schema)  # name -> dtype lookup
        names = self.ref_names
        demote = PK.on_tpu()
        pred = self._prep(self.pred) if self.pred is not None else None
        builders = self._builders
        kinds = self.kinds

        def shim_dtype(t: dt.DType) -> dt.DType:
            return dt.FLOAT32 if demote and t == dt.FLOAT64 else t

        col_dtypes = [shim_dtype(schema_d[n]) for n in names]

        str_names = self.str_names

        def run(batch: ColumnarBatch):
            arrays = []
            for n, st in zip(names, col_dtypes):
                c = batch.column(n)
                data = c.data
                if demote and data.dtype == jnp.float64:
                    data = data.astype(jnp.float32)
                arrays.append(data)
                arrays.append(c.validity.astype(jnp.uint8))
            n_scalar = len(arrays)
            for sn in str_names:
                sc = batch.column(sn)
                arrays.append(sc.padded())              # (cap, W) u8
                arrays.append(sc.lengths().astype(jnp.int32))
                arrays.append(sc.validity.astype(jnp.uint8))
            arrays.append(batch.live_mask().astype(jnp.uint8))

            def row_fn(blocks):
                tile = blocks[-1].shape[0]
                cols = []
                for i, (n, st) in enumerate(zip(names, col_dtypes)):
                    cols.append(ColumnVector(blocks[2 * i],
                                             blocks[2 * i + 1] != 0, st))
                live = blocks[-1] != 0
                kb = _KernelBatch(cols, list(names), tile, live)
                kb.str_lanes = {}
                for k, sn in enumerate(str_names):
                    chars = blocks[n_scalar + 3 * k]
                    lens = blocks[n_scalar + 3 * k + 1]
                    valid = blocks[n_scalar + 3 * k + 2] != 0
                    kb.str_lanes[sn] = (chars, lens, valid)
                mask = live
                if pred is not None:
                    pc = pred.eval(kb)
                    mask = mask & pc.data & pc.validity
                vals = []
                for b in builders:
                    vals.extend(b(kb, mask))
                return vals

            from ..conf import PALLAS_TILE_ROWS, active_conf
            return PK.tile_reduce(arrays, row_fn, kinds,
                                  tile_rows=active_conf()
                                  .get(PALLAS_TILE_ROWS))
        return run

    # --- host-side accumulation -> packed agg states ---
    def init_totals(self) -> List[float]:
        return [PK.reduce_identity(k, jnp.float64) if k != PK.SUM else 0.0
                for k in self.kinds]

    def combine(self, totals: List[float], partials) -> None:
        for i, (k, p) in enumerate(zip(self.kinds, partials)):
            v = float(p)
            if k == PK.SUM:
                totals[i] += v
            elif np.isnan(v) or np.isnan(totals[i]):
                # builders exclude NaN lanes from min/max slots, so a
                # NaN here can only be a true sum overflow artifact —
                # keep the propagate-NaN guard for safety
                totals[i] = float("nan")
            elif k == PK.MIN:
                totals[i] = min(totals[i], v)
            else:
                totals[i] = max(totals[i], v)

    def states(self, totals: List[float], cap: int = 8) -> List[dict]:
        """Accumulated scalars -> per-aggregate state dicts shaped for
        HashAggregateExec._pack (cap-length arrays, group 0 live)."""
        out = []
        for slots in self.agg_slots:
            d = {}
            aux = {sname: totals[idx] for sname, idx, _ in slots}
            if "_nan" in aux:
                # Spark NaN-greatest ordering, deferred from the kernel
                key_name, key_idx, _t = slots[0]
                kkind = self.kinds[key_idx]
                nan_ct, seen_ct = aux["_nan"], aux["seen"]
                if kkind == PK.MAX and nan_ct > 0:
                    totals[key_idx] = float("nan")
                elif kkind == PK.MIN and nan_ct > 0 and \
                        seen_ct - nan_ct <= 0:
                    totals[key_idx] = float("nan")
            for sname, idx, stype in slots:
                if sname == "_nan":
                    continue  # consumed above; not part of the state
                v = totals[idx]
                phys = stype.physical
                if stype == dt.BOOL:
                    arr = np.zeros(cap, bool)
                    arr[0] = v > 0
                else:
                    arr = np.zeros(cap, phys)
                    if np.issubdtype(phys, np.integer) and \
                            not np.isfinite(v):
                        # zero-row min/max of a float-lane reduction:
                        # the +/-inf identity can't enter an int buffer,
                        # and seen=False keeps it from escaping anyway
                        pass
                    else:
                        # real inf/NaN totals must flow through — the
                        # XLA lane returns inf for sum(col with inf)
                        arr[0] = np.asarray(v).astype(phys)
                d[sname] = jnp.asarray(arr)
            out.append(d)
        return out


def grouped_eligible(agg_exec) -> bool:
    """Static gate for the grouped MXU lane (VERDICT r4 #2 — the
    reference's device groupby is THE aggregate path,
    GpuAggregateExec.scala:175): grouping keys present and every
    aggregate sum-decomposable — Sum/Average over floats, Count,
    CountStar. The per-batch <= 1024-group bound is traced (the
    hash-claim prelude's num_groups), so the decision between the
    one-hot matmul and the XLA scatter path is a lax.cond inside one
    compiled program (ops/kernels.py group_aggregate_pallas)."""
    if not agg_exec.group_exprs or agg_exec.mode == "final":
        return False
    schema = list(agg_exec.input_schema)
    for fn, _name in agg_exec.agg_exprs:
        if type(fn) in (Agg.CountStar, Agg.Count):
            continue
        if type(fn) not in (Agg.Sum, Agg.Average):
            return False
        try:
            if fn.children[0].data_type(schema) not in _FLOATY:
                return False
        except Exception:
            return False
    return True


def grouped_lane_on() -> bool:
    """The grouped kernel runs where it is fast: the real chip. The CPU
    interpret lane exists for differential tests (force with
    SRT_PALLAS_GROUPED_FORCE=1) but costs Python dispatch per tile."""
    import os
    return PK.on_tpu() or os.environ.get("SRT_PALLAS_GROUPED_FORCE") == "1"


_GROUPED_WARMUP: dict = {}


def grouped_kernel_ok() -> bool:
    """One-time Mosaic-lowering probe for tile_group_reduce (the same
    guard-then-permanently-fallback contract as the global lane's
    warmup): a failure on the real chip must degrade to the XLA path,
    never crash a query."""
    if "ok" not in _GROUPED_WARMUP:
        try:
            gid = jnp.zeros(16, jnp.int32)
            vals = [jnp.ones(16, jnp.float32)]
            out = PK.tile_group_reduce(gid, vals, num_buckets=8,
                                       tile_rows=8)
            _GROUPED_WARMUP["ok"] = float(out[0][0]) == 16.0
        except Exception:
            _GROUPED_WARMUP["ok"] = False
    return _GROUPED_WARMUP["ok"]


def pallas_eligible(agg_exec) -> bool:
    """The static gate; False keeps the stock XLA path. (The actual
    PallasAggPlan is built lazily at execute time via build_plan, once
    the fused-or-not predicate is resolved.)"""
    if agg_exec.group_exprs:
        return False
    schema = list(agg_exec.input_schema)
    for fn, _name in agg_exec.agg_exprs:
        try:
            if isinstance(fn, (Agg.Sum, Agg.Average)):
                if fn.children[0].data_type(schema) not in _FLOATY:
                    return False
            elif isinstance(fn, (Agg.Min, Agg.Max)):
                if fn.children[0].data_type(schema) not in _MINMAX_DTYPES:
                    return False
            elif isinstance(fn, (Agg.CountStar, Agg.Count)):
                pass
            else:
                return False
        except Exception:
            return False
        if not all(_expr_safe(c, schema) for c in fn.children):
            return False
    return True


def build_plan(agg_exec, pred: Optional[E.Expression]) -> PallasAggPlan:
    return PallasAggPlan(agg_exec.agg_exprs, agg_exec.input_schema, pred)


def pred_safe(pred: E.Expression, input_schema) -> bool:
    """Filter predicates must keep exact row selection: on TPU (where
    the kernel would demote f64 to f32) any float64 subexpression keeps
    the filter un-fused — the aggregate still runs in pallas over the
    FilterExec's output. String predicate subtrees are judged AFTER
    their byte-lane rewrite (the string-predicate kernel family)."""
    rewritten, _ = _rewrite_string_preds(pred, list(input_schema))
    return _expr_safe(rewritten, list(input_schema),
                      no_f64=PK.on_tpu())
