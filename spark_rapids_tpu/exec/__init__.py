"""Physical operators — the GpuExec layer (SURVEY §1 L4, §2.4).

Each exec is an iterator-of-ColumnarBatch over its children, evaluating
jit-compiled kernels on device. Operators acquire the device semaphore
before submitting work, register big intermediates as spillable, and run
allocation-prone sections under the retry framework — the same runtime
discipline as the reference's operators (GpuExec.scala:197,
doExecuteColumnar:348).
"""

from .base import ExecContext, Metric, TpuExec, TpuSemaphore
from .basic import (BatchScanExec, CoalesceBatchesExec, ExpandExec,
                    FilterExec, LocalLimitExec, ProjectExec, RangeExec,
                    UnionExec)
from .aggregate import HashAggregateExec
from .fused import FusedHashJoinExec, FusedPipelineExec
from .pipeline import PrefetchExec, PrefetchIterator
from .sort import SortExec, SortOrder, TopNExec
from .join import BroadcastHashJoinExec, ShuffledHashJoinExec

__all__ = [
    "ExecContext", "Metric", "TpuExec", "TpuSemaphore",
    "BatchScanExec", "CoalesceBatchesExec", "ExpandExec", "FilterExec",
    "LocalLimitExec", "ProjectExec", "RangeExec", "UnionExec",
    "HashAggregateExec", "FusedHashJoinExec", "FusedPipelineExec",
    "PrefetchExec",
    "PrefetchIterator",
    "SortExec", "SortOrder", "TopNExec",
    "BroadcastHashJoinExec", "ShuffledHashJoinExec",
]
