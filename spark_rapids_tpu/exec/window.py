"""Window exec: sort-based segmented-scan window computation.

Rebuild of GpuWindowExec.scala (SURVEY §2.4, 2108 LoC). cuDF exposes
rolling/scan window kernels; the TPU formulation sorts the whole input
by (partition keys, order keys) once, derives segment boundaries, and
lowers every window function to vectorized segmented scans / gathers:

  row_number   idx - segment_start + 1
  rank         cummax of order-run starts within the segment
  dense_rank   segmented cumsum of order-run starts
  ntile        closed-form bucket from row_number and partition size
  lead/lag     index-shifted gather masked to the segment
  running agg  segmented associative_scan (sum/min/max/count/avg)
  whole-part.  segment reduce + gather
  sliding ROWS prefix-sum differences (sum/count/avg) or O(w) masked
               min/max for static window widths

Everything runs in ONE jit per (capacity, plan) — there is no per-
function kernel launch loop. Results scatter back to input order, so
the node is order-preserving (stronger than Spark's contract).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               StringColumn, choose_capacity)
from ..expr.aggregates import (AggregateFunction, Average, Count, CountStar,
                               Max, Min, Sum)
from ..expr.core import Expression, make_result
from ..jit_registry import shared_method_jit
from ..expr.window import (Lag, Lead, DenseRank, NTile, PercentRank, Rank,
                           RowNumber, WindowExpression, WindowFrame)
from ..ops import kernels as K
from .base import ExecContext, Schema, TpuExec


# ---------------------------------------------------------------------------
# segmented primitives (all length-N over the sorted layout)
# ---------------------------------------------------------------------------

def _seg_scan(op, vals, new_seg):
    """Inclusive segmented scan: op-accumulate, restarting where
    new_seg[i] is True (classic segmented-scan monoid lift)."""
    def combine(a, b):
        af, av = a
        bf, bv = b
        return (af | bf, jnp.where(bf, bv, op(av, bv)))
    flags, out = jax.lax.associative_scan(combine, (new_seg, vals))
    return out


def _seg_start_idx(new_seg):
    """For each row, index of its segment's first row (via cummax)."""
    n = new_seg.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = jnp.where(new_seg, idx, 0)
    return jax.lax.associative_scan(jnp.maximum, starts)


def _seg_counts(gid, num_rows, cap):
    """Per-row count of live rows in the row's segment."""
    ones = (jnp.arange(cap) < num_rows).astype(jnp.int64)
    totals = jnp.zeros(cap, jnp.int64).at[gid].add(ones)
    return totals[gid]


def _prev_differs(cols: Sequence[Column]) -> jnp.ndarray:
    """True where row i's keys differ from row i-1 (row 0 = True;
    K._adjacent_equal already yields eq[0] = False)."""
    eq = K._adjacent_equal(cols[0])
    for c in cols[1:]:
        eq = eq & K._adjacent_equal(c)
    return ~eq


def _seg_lower_bound(keys, seg_start, seg_end, query):
    """Per-row first index j in [seg_start_i, seg_end_i] with
    keys[j] >= query_i (vectorized binary search, ~log2(cap) steps)."""
    cap = keys.shape[0]
    lo = seg_start
    hi = seg_end + 1  # exclusive
    steps = max(cap.bit_length(), 1)
    for _ in range(steps):
        mid = (lo + hi) // 2
        ge = jnp.take(keys, jnp.clip(mid, 0, cap - 1)) >= query
        go_left = ge & (lo < hi)
        hi = jnp.where(go_left, mid, hi)
        lo = jnp.where(~ge & (lo < hi), mid + 1, lo)
    return lo


def _seg_upper_bound(keys, seg_start, seg_end, query):
    """Per-row last index j in [seg_start_i, seg_end_i] with
    keys[j] <= query_i (hi_i = lower_bound(> query) - 1)."""
    cap = keys.shape[0]
    lo = seg_start
    hi = seg_end + 1
    steps = max(cap.bit_length(), 1)
    for _ in range(steps):
        mid = (lo + hi) // 2
        gt = jnp.take(keys, jnp.clip(mid, 0, cap - 1)) > query
        go_left = gt & (lo < hi)
        hi = jnp.where(go_left, mid, hi)
        lo = jnp.where(~gt & (lo < hi), mid + 1, lo)
    return lo - 1


def _range_sum(vals, lo_i, hi_i, cap, width_empty):
    """Frame sums via prefix-sum differences, IEEE-safe for floats: a
    +/-inf or NaN anywhere in the partition must only poison frames
    that actually CONTAIN it (a naive cumsum difference yields inf-inf
    = NaN for every frame after the value)."""
    def diff(ps, zero):
        top = ps[jnp.clip(hi_i, 0, cap - 1)]
        bot = jnp.where(lo_i > 0, ps[jnp.clip(lo_i - 1, 0, cap - 1)], zero)
        return top - bot

    if not jnp.issubdtype(vals.dtype, jnp.floating):
        out = diff(jnp.cumsum(vals), jnp.zeros((), vals.dtype))
        return jnp.where(width_empty, 0, out)
    finite = jnp.isfinite(vals)
    base = diff(jnp.cumsum(jnp.where(finite, vals, 0.0)),
                jnp.zeros((), vals.dtype))

    def present(mask):
        return diff(jnp.cumsum(mask.astype(jnp.int32)), 0) > 0
    pos_inf = present(vals == jnp.inf)
    neg_inf = present(vals == -jnp.inf)
    has_nan = present(jnp.isnan(vals))
    out = jnp.where(pos_inf & ~neg_inf, jnp.inf,
                    jnp.where(neg_inf & ~pos_inf, -jnp.inf, base))
    out = jnp.where(has_nan | (pos_inf & neg_inf), jnp.nan, out)
    return jnp.where(width_empty, 0.0, out)


def _range_count(cnt_vals, lo_i, hi_i, cap, width_empty):
    ccnt = jnp.cumsum(cnt_vals)
    top = ccnt[jnp.clip(hi_i, 0, cap - 1)]
    bot = jnp.where(lo_i > 0, ccnt[jnp.clip(lo_i - 1, 0, cap - 1)], 0)
    return jnp.where(width_empty, 0, top - bot)


def _rmq(vals, lo_i, hi_i, cap, op, out_t):
    """Range min/max query via a doubling sparse table: O(cap log cap)
    build, two gathers per query."""
    fill = dt.max_value(out_t) if op is jnp.minimum else dt.min_value(out_t)
    levels = [vals]
    span = 1
    while span < cap:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[span:], jnp.full((span,), fill, prev.dtype)])
        levels.append(op(prev, shifted))
        span *= 2
    table = jnp.stack(levels)                       # (L, cap)
    w = jnp.maximum(hi_i - lo_i + 1, 1)
    kk = jnp.floor(jnp.log2(w.astype(jnp.float64))).astype(jnp.int32)
    pow2 = jnp.left_shift(jnp.int32(1), kk)
    flat = table.reshape(-1)
    a = jnp.take(flat, jnp.clip(kk * cap + lo_i, 0, flat.shape[0] - 1))
    b = jnp.take(flat, jnp.clip(kk * cap + hi_i - pow2 + 1, 0,
                                flat.shape[0] - 1))
    out = op(a, b)
    return jnp.where(hi_i < lo_i, jnp.asarray(fill, vals.dtype), out)


class WindowExec(TpuExec):
    """Computes window columns for expressions sharing one
    (partition_by, order_by) spec; appends them to the child schema."""

    def __init__(self, child: TpuExec,
                 window_exprs: Sequence[Tuple[WindowExpression, str]]):
        super().__init__(child)
        self.window_exprs = list(window_exprs)
        spec = window_exprs[0][0].spec
        self.partition_by = spec.partition_by
        self.order_by = spec.order_fields
        for we, _ in window_exprs[1:]:
            if (repr(we.spec.partition_by) != repr(self.partition_by)
                    or repr(we.spec.order_fields) != repr(self.order_by)):
                raise ValueError(
                    "one WindowExec handles one (partition, order) spec; "
                    "the planner must split differing specs")
        in_schema = child.output_schema
        self._schema = list(in_schema) + [
            (name, we.data_type(in_schema))
            for we, name in self.window_exprs]
        from ..expr.misc import contains_eager
        self._jit = self._compute if contains_eager(
            [we for we, _ in self.window_exprs] + list(self.partition_by)
            + [o.expr for o in self.order_by]) \
            else shared_method_jit(
                self, "_compute",
                ("window_exprs", "partition_by", "order_by", "_schema"))

    @property
    def output_schema(self) -> Schema:
        return self._schema

    # --- the one big kernel ---
    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        cap = batch.capacity
        n = batch.num_rows
        live = batch.live_mask()
        part_cols = [e.eval(batch) for e in self.partition_by]
        order_cols = [o.expr.eval(batch) for o in self.order_by]

        # sort by (partition, order); dead rows sort last
        asc = [True] * len(part_cols) + [o.ascending for o in self.order_by]
        nf = [True] * len(part_cols) + [o.nulls_first for o in self.order_by]
        perm = K.sort_indices(part_cols + order_cols, asc, nf, live)
        sorted_batch = batch.gather(perm, n, unique=True)
        s_part = [c.gather(perm) for c in part_cols]
        s_order = [c.gather(perm) for c in order_cols]

        idx = jnp.arange(cap, dtype=jnp.int32)
        s_live = idx < n
        new_part = _prev_differs(s_part) if s_part else \
            (idx == 0)
        new_part = new_part | (idx == 0)
        gid = jnp.cumsum(new_part.astype(jnp.int32)) - 1
        seg_start = _seg_start_idx(new_part)
        counts = _seg_counts(gid, n, cap)
        new_order = new_part | (_prev_differs(s_order)
                                if s_order else jnp.zeros(cap, jnp.bool_))
        # last row index of each order-key run (RANGE peer semantics):
        # next run's start - 1, via reversed inclusive cummin of starts
        starts_only = jnp.where(new_order, idx, jnp.int32(cap))
        incl_next = jax.lax.associative_scan(
            jnp.minimum, starts_only[::-1])[::-1]
        next_start = jnp.concatenate(
            [incl_next[1:], jnp.full(1, cap, jnp.int32)])
        run_end = jnp.clip(next_start - 1, 0, cap - 1)

        out_cols: List[Column] = []
        for we, _name in self.window_exprs:
            out_cols.append(self._one_function(
                we, sorted_batch, idx, s_live, new_part, new_order, gid,
                seg_start, counts, run_end, cap, n))

        # scatter results back to input order
        inv = jnp.zeros(cap, jnp.int32).at[perm].set(idx)
        restored = [c.gather(inv) for c in out_cols]
        return ColumnarBatch(
            list(batch.columns) + restored,
            [nm for nm, _ in self._schema], n)

    def _one_function(self, we: WindowExpression, sorted_batch, idx,
                      s_live, new_part, new_order, gid, seg_start, counts,
                      run_end, cap, n) -> Column:
        fn = we.func
        live_valid = s_live
        rn = idx - seg_start + 1  # row_number, 1-based

        if isinstance(fn, RowNumber):
            return make_result(rn.astype(jnp.int32), live_valid, dt.INT32)
        if isinstance(fn, (Rank, DenseRank, PercentRank)):
            run_start = jnp.where(new_order, idx, 0)
            rank_idx = jax.lax.associative_scan(jnp.maximum, run_start)
            rank = (rank_idx - seg_start + 1).astype(jnp.int32)
            if isinstance(fn, Rank):
                return make_result(rank, live_valid, dt.INT32)
            if isinstance(fn, DenseRank):
                dr = _seg_scan(jnp.add, new_order.astype(jnp.int32),
                               new_part).astype(jnp.int32)
                return make_result(dr, live_valid, dt.INT32)
            denom = jnp.maximum(counts - 1, 1).astype(jnp.float64)
            pr = jnp.where(counts > 1, (rank - 1).astype(jnp.float64)
                           / denom, 0.0)
            return make_result(pr, live_valid, dt.FLOAT64)
        if isinstance(fn, NTile):
            nt = jnp.int64(fn.n)
            cnt = counts
            q = cnt // nt
            r = cnt % nt
            i0 = (rn - 1).astype(jnp.int64)
            big_span = r * (q + 1)
            in_big = i0 < big_span
            bucket = jnp.where(
                in_big, i0 // jnp.maximum(q + 1, 1),
                r + jnp.where(q > 0, (i0 - big_span) // jnp.maximum(q, 1),
                              i0 - big_span))
            return make_result((bucket + 1).astype(jnp.int32), live_valid,
                               dt.INT32)
        if isinstance(fn, (Lead, Lag)):
            col = fn.children[0].eval(sorted_batch)
            k = fn.offset if isinstance(fn, Lead) and not isinstance(fn, Lag) \
                else -fn.offset
            target = idx + k
            seg_end = seg_start + counts.astype(jnp.int32) - 1
            in_seg = (target >= seg_start) & (target <= seg_end) & \
                (target >= 0) & (target < cap)
            got = col.gather(jnp.clip(target, 0, cap - 1))
            if fn.default is not None:
                from ..expr.core import Literal
                d = Literal(fn.default).eval(sorted_batch)
                if isinstance(got, StringColumn):
                    from ..expr.conditional import _select_strings
                    out = _select_strings(in_seg, got, d)
                    return out.with_validity(
                        jnp.where(in_seg, got.validity, d.validity) & s_live)
                data = jnp.where(in_seg, got.data, d.data.astype(got.data.dtype))
                valid = jnp.where(in_seg, got.validity, d.validity) & s_live
                return make_result(data, valid, got.dtype)
            valid = got.validity & in_seg & s_live
            if isinstance(got, StringColumn):
                return got.with_validity(valid)
            return make_result(got.data, valid, got.dtype)
        if isinstance(fn, AggregateFunction):
            return self._window_aggregate(fn, we.spec.frame, sorted_batch,
                                          idx, s_live, new_part, gid,
                                          seg_start, counts, run_end, cap,
                                          spec=we.spec)
        raise NotImplementedError(type(fn).__name__)

    def _window_aggregate(self, fn: AggregateFunction, frame: WindowFrame,
                          sorted_batch, idx, s_live, new_part, gid,
                          seg_start, counts, run_end, cap,
                          spec=None) -> Column:
        in_schema = sorted_batch.schema()
        if isinstance(fn, CountStar):
            vals = s_live.astype(jnp.int64)
            valid_in = s_live
            out_t = dt.INT64
        else:
            col = fn.children[0].eval(sorted_batch)
            out_t = fn.data_type(in_schema)
            valid_in = col.validity
            if isinstance(fn, (Sum, Average, Count)):
                phys = jnp.float64 if isinstance(fn, Average) or \
                    (isinstance(fn, Sum) and out_t == dt.FLOAT64) else \
                    out_t.physical
                vals = col.data.astype(jnp.float64
                                       if isinstance(fn, Average)
                                       else phys)
                if isinstance(col.dtype, dt.DecimalType) and \
                        isinstance(fn, Average):
                    vals = vals / (10.0 ** col.dtype.scale)
            else:
                vals = col.data

        cnt_vals = valid_in.astype(jnp.int64)
        if isinstance(fn, Count) or isinstance(fn, CountStar):
            agg_vals = cnt_vals
            op = jnp.add
            zero_for_null = 0
        elif isinstance(fn, Sum) or isinstance(fn, Average):
            agg_vals = jnp.where(valid_in, vals, 0)
            op = jnp.add
            zero_for_null = 0
        elif isinstance(fn, Min):
            fill = dt.max_value(out_t)
            agg_vals = jnp.where(valid_in, vals,
                                 jnp.asarray(fill, vals.dtype))
            op = jnp.minimum
        elif isinstance(fn, Max):
            fill = dt.min_value(out_t)
            agg_vals = jnp.where(valid_in, vals,
                                 jnp.asarray(fill, vals.dtype))
            op = jnp.maximum
        else:
            raise NotImplementedError(
                f"window aggregate {type(fn).__name__}")

        if frame.is_unbounded:
            if op is jnp.add:
                total = jnp.zeros(cap, agg_vals.dtype).at[gid].add(agg_vals)
            elif op is jnp.minimum:
                total = jnp.full(cap, jnp.asarray(
                    dt.max_value(out_t), agg_vals.dtype)).at[gid].min(agg_vals)
            else:
                total = jnp.full(cap, jnp.asarray(
                    dt.min_value(out_t), agg_vals.dtype)).at[gid].max(agg_vals)
            acc = total[gid]
            ncnt = jnp.zeros(cap, jnp.int64).at[gid].add(cnt_vals)[gid]
        elif frame.is_running:
            acc = _seg_scan(op, agg_vals, new_part)
            ncnt = _seg_scan(jnp.add, cnt_vals, new_part)
            if not frame.row_based:
                # RANGE running: all peers of the current order key share
                # the value at their run's LAST row (SQL peer semantics)
                acc = jnp.take(acc, run_end)
                ncnt = jnp.take(ncnt, run_end)
        elif frame.row_based:
            return self._sliding(fn, frame, agg_vals, cnt_vals, idx,
                                 seg_start, counts, cap, out_t, op, s_live)
        else:
            return self._range_sliding(fn, frame, spec, sorted_batch,
                                       agg_vals, cnt_vals, seg_start,
                                       counts, cap, out_t, op, s_live)

        return self._finalize_agg(fn, acc, ncnt, s_live, out_t)

    def _range_sliding(self, fn, frame, spec, sorted_batch, agg_vals,
                       cnt_vals, seg_start, counts, cap, out_t, op,
                       s_live):
        """RANGE BETWEEN x PRECEDING AND y FOLLOWING with value offsets
        (GpuWindowExec bounded-range frames): per-row frame bounds are
        binary searches over the partition-sorted order key; add-monoids
        then use prefix-sum differences and min/max a doubling sparse
        table (O(log n) RMQ — the two-kernel trick cuDF's range windows
        use becomes searchsorted + gather here)."""
        of = spec.order_fields[0]
        key_col = of.expr.eval(sorted_batch)
        k = key_col.data.astype(jnp.float64)
        if isinstance(key_col.dtype, dt.DecimalType):
            # decimal lanes are scaled ints; frame offsets are logical
            # values — scale them to the same fixed-point basis
            factor = float(10 ** key_col.dtype.scale)
            frame = WindowFrame(
                None if frame.lo is None else frame.lo * factor,
                None if frame.hi is None else frame.hi * factor,
                row_based=False)
        if not of.ascending:
            k = -k
        # null order keys are their own peer group at the sort's null
        # end: map them to +/-inf so their frames cover exactly the run
        null_end = jnp.where(of.nulls_first, -jnp.inf, jnp.inf)
        k = jnp.where(key_col.validity, k, null_end)
        seg_end = seg_start + counts.astype(jnp.int32) - 1
        lo_val = k + frame.lo if frame.lo is not None else None
        hi_val = k + frame.hi if frame.hi is not None else None
        lo_i = seg_start if lo_val is None else _seg_lower_bound(
            k, seg_start, seg_end, lo_val)
        hi_i = seg_end if hi_val is None else _seg_upper_bound(
            k, seg_start, seg_end, hi_val)
        width_empty = hi_i < lo_i
        if op is jnp.add:
            acc = _range_sum(agg_vals, lo_i, hi_i, cap, width_empty)
        else:
            acc = _rmq(agg_vals, lo_i, hi_i, cap, op, out_t)
        ncnt = _range_count(cnt_vals, lo_i, hi_i, cap, width_empty)
        return self._finalize_agg(fn, acc, ncnt, s_live, out_t)

    def _sliding(self, fn, frame, agg_vals, cnt_vals, idx, seg_start,
                 counts, cap, out_t, op, s_live):
        """ROWS BETWEEN a AND b with integer bounds: prefix-sum
        differences for add-monoids, O(width) masked scan otherwise."""
        lo = frame.lo
        hi = frame.hi
        seg_end = seg_start + counts.astype(jnp.int32) - 1
        lo_i = seg_start if lo is None else \
            jnp.maximum(idx + lo, seg_start)
        hi_i = seg_end if hi is None else \
            jnp.minimum(idx + hi, seg_end)
        width_empty = hi_i < lo_i
        if op is jnp.add:
            acc = _range_sum(agg_vals, lo_i, hi_i, cap, width_empty)
            ncnt = _range_count(cnt_vals, lo_i, hi_i, cap, width_empty)
        else:
            if lo is None or hi is None:
                raise NotImplementedError(
                    "min/max sliding frames need bounded ROWS offsets")
            width = hi - lo + 1
            acc = jnp.take(agg_vals, jnp.clip(lo_i, 0, cap - 1))
            ncnt = jnp.zeros(cap, jnp.int64)
            for off in range(width):
                j = lo_i + off
                ok = (j <= hi_i)
                v = jnp.take(agg_vals, jnp.clip(j, 0, cap - 1))
                acc = jnp.where(ok, op(acc, v), acc)
                ncnt = ncnt + jnp.where(
                    ok, jnp.take(cnt_vals, jnp.clip(j, 0, cap - 1)), 0)
        return self._finalize_agg(fn, acc, ncnt, s_live, out_t)

    def _finalize_agg(self, fn, acc, ncnt, s_live, out_t) -> ColumnVector:
        if isinstance(fn, (Count, CountStar)):
            return make_result(acc.astype(jnp.int64), s_live, dt.INT64)
        has_vals = ncnt > 0
        if isinstance(fn, Average):
            out = acc / jnp.where(has_vals, ncnt, 1).astype(jnp.float64)
            return make_result(out, has_vals & s_live, dt.FLOAT64)
        if isinstance(fn, Sum):
            phys = out_t.physical
            return make_result(acc.astype(phys), has_vals & s_live, out_t)
        return make_result(acc, has_vals & s_live, out_t)

    def required_child_distributions(self):
        """Partitioned windows cluster by the partition keys
        (GpuWindowExec requiredChildDistribution): each reduce
        partition holds whole window partitions, so the exec
        materializes one PARTITION at a time instead of the whole
        input — and the same clustering is what the mesh lowering
        rides."""
        from ..plan.distribution import (ClusteredDistribution,
                                         UnspecifiedDistribution)
        if self.partition_by:
            return [ClusteredDistribution(self.partition_by)]
        return [UnspecifiedDistribution()]

    @property
    def output_partitioning(self):
        return self.children[0].output_partitioning

    # --- streaming shell: one materialization per child partition ---
    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        for part in self.children[0].execute_partitioned(ctx):
            yield from self._window_partition(ctx, part)

    def _window_partition(self, ctx: ExecContext,
                          stream) -> Iterator[ColumnarBatch]:
        from ..memory.retry import with_retry_no_split
        from ..memory.spill import SpillableBatch, SpillPriority
        runs: List[SpillableBatch] = []
        total = 0
        try:
            for b in stream:
                if int(b.num_rows) == 0:
                    continue
                total += int(b.num_rows)
                runs.append(with_retry_no_split(
                    lambda x=b: SpillableBatch(
                        x, SpillPriority.ACTIVE_ON_DECK)))
            if not runs:
                return
            cap = choose_capacity(total)

            def compute():
                batches = [sb.get() for sb in runs]
                with ctx.semaphore:
                    merged = (batches[0] if len(batches) == 1
                              else K.concat_batches(batches, cap))
                    return self._jit(merged)
            # RetryOOM: spill + re-run (pure over the held spillables)
            yield with_retry_no_split(compute)
        finally:
            for sb in runs:
                sb.close()

    def node_description(self) -> str:
        fns = ", ".join(type(we.func).__name__
                        for we, _ in self.window_exprs)
        return f"Window[{fns}]"


# ---------------------------------------------------------------------------
# batched running windows (GpuRunningWindowExec + BatchedRunningWindowFixer,
# GpuWindowExec.scala:236-292)
# ---------------------------------------------------------------------------

def running_compatible(window_exprs, in_schema) -> bool:
    """True when every expression can stream batch-at-a-time over a
    (partition, order)-sorted child with carried state: rank family, or
    ROWS running (unbounded-preceding..current-row) sum/min/max/count/
    avg over plain numeric inputs. RANGE running is excluded — its peer
    rows share the value at the run's LAST row, which can live in the
    next batch (needs lookahead); decimal inputs carry two-limb states
    the scalar fixer cannot hold."""
    for we, _name in window_exprs:
        fn = we.func
        if isinstance(fn, (RowNumber, Rank, DenseRank)):
            continue
        frame = we.spec.frame
        if isinstance(fn, (Sum, Count, CountStar, Min, Max, Average)) \
                and frame is not None and frame.is_running \
                and frame.row_based:
            if fn.children:
                t = fn.children[0].data_type(in_schema)
                if isinstance(t, dt.DecimalType) or t == dt.STRING \
                        or t.is_nested:
                    return False
            continue
        return False
    return True


class BatchedRunningWindowExec(TpuExec):
    """Running-frame windows over an already (partition, order)-sorted
    stream in O(batch) memory: each batch computes its within-batch
    segmented scans, then the FIRST partition-run is fixed up with
    state carried from the previous batch (rank/row-number bases,
    running accumulator and count), exactly the reference's
    BatchedRunningWindowFixer contract. The planner places a SortExec
    below; output rows stream in sorted order (Spark's window makes no
    ordering promise, and this matches the reference's running path)."""

    def __init__(self, child: TpuExec,
                 window_exprs: Sequence[Tuple[WindowExpression, str]]):
        super().__init__(child)
        self.window_exprs = list(window_exprs)
        spec = window_exprs[0][0].spec
        self.partition_by = spec.partition_by
        self.order_by = spec.order_fields
        in_schema = child.output_schema
        self._schema = list(in_schema) + [
            (name, we.data_type(in_schema))
            for we, name in self.window_exprs]
        self._in_schema = in_schema
        from ..expr.misc import contains_eager
        self._jit = self._compute if contains_eager(
            [we for we, _ in self.window_exprs] + list(self.partition_by)
            + [o.expr for o in self.order_by]) \
            else shared_method_jit(
                self, "_compute",
                ("window_exprs", "partition_by", "order_by", "_schema",
                 "_in_schema"))

    @property
    def output_schema(self) -> Schema:
        return self._schema

    # --- carried state -----------------------------------------------
    def _agg_acc_dtype(self, fn):
        if isinstance(fn, (Count, CountStar)):
            return jnp.int64
        if isinstance(fn, Average):
            return jnp.float64
        t = fn.data_type(self._in_schema)
        return t.physical

    def _zero_state(self):
        """Structure-stable pytree: 1-row tail key columns + per-fn
        scalars. has_tail gates every fixup."""
        def zero_col(e):
            t = e.data_type(self._in_schema)
            if t == dt.STRING:
                return StringColumn(jnp.zeros(2, jnp.int32),
                                    jnp.zeros(8, jnp.uint8),
                                    jnp.zeros(1, jnp.bool_), pad_bucket=8)
            return ColumnVector(jnp.zeros(1, t.physical),
                                jnp.zeros(1, jnp.bool_), t)
        fns = []
        for we, _ in self.window_exprs:
            fn = we.func
            fns.append({
                "acc": jnp.zeros((), self._agg_acc_dtype(fn))
                if isinstance(fn, (Sum, Count, CountStar, Min, Max,
                                   Average)) else jnp.zeros((), jnp.int64),
                "cnt": jnp.zeros((), jnp.int64),
                "rank": jnp.zeros((), jnp.int64),
                "dense": jnp.zeros((), jnp.int64),
            })
        return {
            "has_tail": jnp.zeros((), jnp.bool_),
            "rows": jnp.zeros((), jnp.int64),  # rows so far in partition
            "tail_part": [zero_col(e) for e in self.partition_by],
            "tail_order": [zero_col(o.expr) for o in self.order_by],
            "fns": fns,
        }

    # --- the per-batch kernel ----------------------------------------
    def _compute(self, batch: ColumnarBatch, state):
        cap = batch.capacity
        n = batch.num_rows
        idx = jnp.arange(cap, dtype=jnp.int32)
        s_live = idx < n
        part_cols = [e.eval(batch) for e in self.partition_by]
        order_cols = [o.expr.eval(batch) for o in self.order_by]

        new_part = (_prev_differs(part_cols) if part_cols
                    else jnp.zeros(cap, jnp.bool_)) | (idx == 0)
        gid = jnp.cumsum(new_part.astype(jnp.int32)) - 1
        seg_start = _seg_start_idx(new_part)
        new_order = new_part | (_prev_differs(order_cols)
                                if order_cols else jnp.zeros(cap, jnp.bool_))
        run_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(new_order, idx, 0))
        rn = (idx - seg_start + 1).astype(jnp.int64)

        zero_i = jnp.zeros(1, jnp.int32)
        def row0_equal(cols, tails):
            if not cols:
                return jnp.ones((), jnp.bool_)
            # grouping equality: a NULL partition key continues the
            # NULL partition (join-style null!=null broke carried
            # state exactly for null keys)
            return K._keys_equal(cols, zero_i, tails, zero_i,
                                 null_safe=True)[0]
        cont = state["has_tail"] & (n > 0) & \
            row0_equal(part_cols, state["tail_part"])
        cont_order = cont & row0_equal(order_cols, state["tail_order"])
        in_seg0 = (gid == 0) & s_live
        prev_rows = jnp.where(cont, state["rows"], 0)

        out_cols: List[Column] = []
        new_fns = []
        last = jnp.clip(n - 1, 0, cap - 1)

        for (we, _name), fst in zip(self.window_exprs, state["fns"]):
            fn = we.func
            if isinstance(fn, RowNumber):
                out = jnp.where(in_seg0, rn + prev_rows, rn)
                out_cols.append(make_result(out.astype(jnp.int32),
                                            s_live, dt.INT32))
                nf = dict(fst)
                new_fns.append(nf)
                continue
            if isinstance(fn, (Rank, DenseRank)):
                if isinstance(fn, Rank):
                    rank = (run_start - seg_start + 1).astype(jnp.int64)
                    # rows continuing the tail's ORDER run keep its rank;
                    # later runs of the continued partition shift by the
                    # carried partition row count
                    in_first_run = run_start == 0
                    fixed = jnp.where(in_first_run & cont_order,
                                      fst["rank"], rank + prev_rows)
                    out = jnp.where(in_seg0 & cont, fixed, rank)
                    out_cols.append(make_result(out.astype(jnp.int32),
                                                s_live, dt.INT32))
                    nf = dict(fst)
                    nf["rank"] = jnp.take(out, last)
                    new_fns.append(nf)
                else:
                    dr = _seg_scan(jnp.add, new_order.astype(jnp.int64),
                                   new_part)
                    fixed = dr + fst["dense"] - \
                        jnp.where(cont_order, 1, 0)
                    out = jnp.where(in_seg0 & cont, fixed, dr)
                    out_cols.append(make_result(out.astype(jnp.int32),
                                                s_live, dt.INT32))
                    nf = dict(fst)
                    nf["dense"] = jnp.take(out, last)
                    new_fns.append(nf)
                continue
            # running aggregates
            out_t = fn.data_type(self._in_schema) \
                if not isinstance(fn, CountStar) else dt.INT64
            if isinstance(fn, CountStar):
                valid_in = s_live
                vals = s_live.astype(jnp.int64)
            else:
                col = fn.children[0].eval(batch)
                valid_in = col.validity
                vals = col.data
            acc_t = self._agg_acc_dtype(fn)
            cnt_vals = (valid_in & s_live).astype(jnp.int64)
            if isinstance(fn, (Count, CountStar)):
                agg_vals = cnt_vals
                op = jnp.add
            elif isinstance(fn, Min):
                op = jnp.minimum
                fill = dt.max_value(out_t)
                agg_vals = jnp.where(valid_in & s_live, vals.astype(acc_t),
                                     jnp.asarray(fill, acc_t))
            elif isinstance(fn, Max):
                op = jnp.maximum
                fill = dt.min_value(out_t)
                agg_vals = jnp.where(valid_in & s_live, vals.astype(acc_t),
                                     jnp.asarray(fill, acc_t))
            else:  # Sum / Average
                op = jnp.add
                agg_vals = jnp.where(valid_in & s_live,
                                     vals.astype(acc_t),
                                     jnp.zeros((), acc_t))
            acc = _seg_scan(op, agg_vals, new_part)
            ncnt = _seg_scan(jnp.add, cnt_vals, new_part)
            prev_acc = fst["acc"]
            prev_cnt = jnp.where(cont, fst["cnt"], 0)
            if op is jnp.add:
                fix = acc + jnp.where(cont, prev_acc,
                                      jnp.zeros((), acc.dtype))
            else:
                fix = jnp.where(cont & (prev_cnt > 0),
                                op(acc, prev_acc), acc)
            acc = jnp.where(in_seg0, fix, acc)
            ncnt = jnp.where(in_seg0, ncnt + prev_cnt, ncnt)
            has_vals = ncnt > 0
            if isinstance(fn, (Count, CountStar)):
                out_cols.append(make_result(acc.astype(jnp.int64),
                                            s_live, dt.INT64))
            elif isinstance(fn, Average):
                avg = acc / jnp.where(has_vals, ncnt, 1).astype(jnp.float64)
                out_cols.append(make_result(avg, has_vals & s_live,
                                            dt.FLOAT64))
            else:
                out_cols.append(make_result(acc.astype(out_t.physical),
                                            has_vals & s_live, out_t))
            nf = dict(fst)
            nf["acc"] = jnp.take(acc, last).astype(acc_t)
            nf["cnt"] = jnp.take(ncnt, last)
            new_fns.append(nf)

        # carried tail = last live row's keys + its row_number
        one_valid = jnp.asarray([True])
        last_arr = jnp.asarray([0], jnp.int32) + last
        new_state = {
            "has_tail": state["has_tail"] | (n > 0),
            "rows": jnp.where(
                n > 0,
                jnp.take(jnp.where(in_seg0, rn + prev_rows, rn), last),
                state["rows"]),
            "tail_part": [c.gather(last_arr, one_valid)
                          for c in part_cols],
            "tail_order": [c.gather(last_arr, one_valid)
                           for c in order_cols],
            "fns": new_fns,
        }
        out = ColumnarBatch(list(batch.columns) + out_cols,
                            [nm for nm, _ in self._schema], n)
        return out, new_state

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        state = self._zero_state()
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            with ctx.semaphore:
                out, state = self._jit(batch, state)
            yield out

    def node_description(self) -> str:
        fns = ", ".join(type(we.func).__name__
                        for we, _ in self.window_exprs)
        return f"BatchedRunningWindow[{fns}]"
