"""Sort exec: in-core full sort + spillable out-of-core merge.

Rebuild of GpuSortExec.scala (:86, out-of-core iterator :242) and
SortUtils.scala. Each input batch is sorted on device; if more than one
batch arrives the sorted runs are concatenated and re-sorted at full
size (a single argsort chain is the XLA-friendly formulation — the
pairwise merge tree of the reference exists to bound GPU memory, which
here is the spill framework's job: runs wait on the spill tier until
the final pass).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax

from ..columnar.vector import ColumnarBatch, choose_capacity
from ..expr.core import Expression
from ..ops import kernels as K
from .base import ExecContext, Schema, TpuExec


class SortOrder:
    """(expr, ascending, nulls_first) — Catalyst SortOrder."""

    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.expr = expr
        self.ascending = ascending
        # Spark default: NULLS FIRST for ASC, NULLS LAST for DESC
        self.nulls_first = ascending if nulls_first is None else nulls_first


class SortExec(TpuExec):
    def __init__(self, child: TpuExec, order: Sequence[SortOrder],
                 global_sort: bool = True):
        super().__init__(child)
        self.order = list(order)
        self.global_sort = global_sort
        self._jit_sort = jax.jit(self._sort_one)

    def _sort_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = [o.expr.eval(batch) for o in self.order]
        return K.sort_batch(batch, key_cols,
                            [o.ascending for o in self.order],
                            [o.nulls_first for o in self.order])

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def required_child_distributions(self):
        from ..plan.distribution import (OrderedDistribution,
                                         UnspecifiedDistribution)
        if self.global_sort:
            return [OrderedDistribution(self.order)]
        return [UnspecifiedDistribution()]

    @property
    def output_partitioning(self):
        from ..plan.distribution import RangePartitioning
        if self.global_sort:
            child = self.children[0].output_partitioning
            return RangePartitioning(self.order, child.num_partitions)
        return self.children[0].output_partitioning

    def _sort_partition(self, ctx: ExecContext,
                        stream) -> Iterator[ColumnarBatch]:
        """Buffer one partition (spillable), concat, sort — the
        out-of-core shape of GpuSortExec.scala:242 with the spill tier
        holding the runs."""
        from ..memory.spill import SpillableBatch, SpillPriority
        runs: List[SpillableBatch] = []
        total = 0
        try:
            for batch in stream:
                if int(batch.num_rows) == 0:
                    continue
                total += int(batch.num_rows)
                runs.append(SpillableBatch(batch,
                                           SpillPriority.ACTIVE_ON_DECK))
            if not runs:
                return
            cap = choose_capacity(total)
            batches = [sb.get() for sb in runs]
            with ctx.semaphore:
                merged = (batches[0] if len(batches) == 1
                          else K.concat_batches(batches, cap))
                yield self._jit_sort(merged)
        finally:
            for sb in runs:
                sb.close()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        if not self.global_sort:
            for batch in self.children[0].execute(ctx):
                if int(batch.num_rows) == 0:
                    continue
                with ctx.semaphore:
                    yield self._jit_sort(batch)
            return
        # Global sort over a range-partitioned child: sorting each
        # partition and emitting in partition order is globally sorted
        # (partition i's rows all precede partition i+1's).
        for part in self.children[0].execute_partitioned(ctx):
            yield from self._sort_partition(ctx, part)

    def node_description(self) -> str:
        keys = ", ".join(
            f"{o.expr!r} {'ASC' if o.ascending else 'DESC'}"
            for o in self.order)
        return f"Sort[{keys}]{'' if self.global_sort else ' (local)'}"


class TopNExec(TpuExec):
    """ORDER BY + LIMIT n fused (GpuTopN, limit.scala): keeps only the
    top n rows per batch, then a final n-way selection — bounds memory
    without the full-sort concat."""

    def __init__(self, child: TpuExec, order: Sequence[SortOrder], limit: int):
        super().__init__(child)
        self.order = list(order)
        self.limit = limit
        self._jit_topn = jax.jit(self._topn)
        self._jit_shrink = jax.jit(
            lambda b: K.slice_batch(b, 0, b.num_rows,
                                    choose_capacity(self.limit)))

    def _topn(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = [o.expr.eval(batch) for o in self.order]
        sorted_b = K.sort_batch(batch, key_cols,
                                [o.ascending for o in self.order],
                                [o.nulls_first for o in self.order])
        return K.local_limit(sorted_b, self.limit)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        partials: List[ColumnarBatch] = []
        total = 0
        # Each partial holds <= limit live rows; compact it down to the
        # limit's capacity bucket so retained memory is O(batches*limit),
        # not O(batches*input_capacity).
        part_cap = choose_capacity(self.limit)
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            with ctx.semaphore:
                part = self._jit_topn(batch)
                if part.capacity > part_cap:
                    part = self._jit_shrink(part)
            partials.append(part)
            total += int(part.num_rows)
        if not partials:
            return
        cap = choose_capacity(max(total, self.limit))
        with ctx.semaphore:
            merged = (partials[0] if len(partials) == 1
                      else K.concat_batches(partials, cap))
            yield self._jit_topn(merged)

    def node_description(self) -> str:
        return f"TopN[{self.limit}]"
