"""Sort exec: in-core full sort + true out-of-core k-way chunk merge.

Rebuild of GpuSortExec.scala (:86, out-of-core iterator :242) and
SortUtils.scala. Each input batch is sorted on device into a run. A
partition whose total rows fit ``srt.sql.sort.oocRowBudget`` merges
with one concat + argsort (the XLA-friendly fast path). Bigger
partitions run the out-of-core iterator: runs are split into spilled
C-row chunks, and a host-driven loop repeatedly loads the chunk whose
first row is globally smallest (device-ordered head comparison), sorts
it against the bounded carry, and emits every row that can no longer
be preceded by an unloaded row (rows ordered <= the minimum pending
chunk head — the same bound logic as the reference's out-of-core merge
pending/sorted queues). Device residency stays O(budget): one chunk +
the carry, with runs parked in the spill tier.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch,
                               choose_capacity, live_mask)
from ..expr.core import Expression
from ..jit_registry import shared_fn_jit, shared_method_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, Schema, TpuExec


def _concat_sort_builder(order, cap):
    """MODULE-LEVEL builder for shared_fn_jit (fusion v2, sort-prefix
    fusion): concat + key extraction + sort as ONE program — the
    out-of-core merge step (carry + chunk) and the pending-head pick
    both otherwise pay an eager concat that round-trips HBM before the
    sort launch reads it back."""
    def run(*batches):
        b = batches[0] if len(batches) == 1 \
            else K.concat_batches(list(batches), cap)
        keys = [o.expr.eval(b) for o in order]
        return K.sort_batch(b, keys,
                            [o.ascending for o in order],
                            [o.nulls_first for o in order])
    return run


def _chunk_head_builder(length, cap):
    """MODULE-LEVEL builder for shared_fn_jit: slice one C-row chunk
    out of a sorted run AND capture its head-row token (8-cap batch
    with the __run tag column) in the same program."""
    def run(run_b, start):
        piece = K.slice_batch(run_b, start, length, cap)
        head = K.slice_batch(piece, 0, 1, 8)
        tag = ColumnVector(jnp.zeros(8, jnp.int32),
                           live_mask(8, head.num_rows), dt.INT32)
        head8 = ColumnarBatch(head.columns + [tag],
                              head.names + ["__run"], head.num_rows)
        return piece, head8
    return run


def _bound_prefix_builder(order):
    """MODULE-LEVEL builder for shared_fn_jit: bound-row slice + safe-
    prefix count in one program (the fused form of
    _safe_prefix_builder — takes the sorted pending-heads batch and
    slices its first row as the bound internally)."""
    from ..parallel.partition import range_partition_ids

    def run(mb, hs):
        bb = K.slice_batch(hs, 0, 1, 8)
        keys = [o.expr.eval(mb) for o in order]
        bkeys = [o.expr.eval(bb) for o in order]
        bkeys = [c.gather(jnp.zeros(1, jnp.int32),
                          live_mask(1, bb.num_rows))
                 if hasattr(c, "chars") else
                 type(c)(c.data[:1], c.validity[:1], c.dtype)
                 for c in bkeys]
        pid = range_partition_ids(
            keys, bkeys, [o.ascending for o in order],
            [o.nulls_first for o in order])
        return jnp.sum((pid == 0) & mb.live_mask()).astype(jnp.int32)
    return run


def _safe_prefix_builder(order):
    from ..parallel.partition import range_partition_ids

    def run(mb, bb):
        keys = [o.expr.eval(mb) for o in order]
        bkeys = [o.expr.eval(bb) for o in order]
        bkeys = [c.gather(jnp.zeros(1, jnp.int32),
                          live_mask(1, bb.num_rows))
                 if hasattr(c, "chars") else
                 type(c)(c.data[:1], c.validity[:1], c.dtype)
                 for c in bkeys]
        pid = range_partition_ids(
            keys, bkeys, [o.ascending for o in order],
            [o.nulls_first for o in order])
        return jnp.sum((pid == 0) & mb.live_mask()).astype(jnp.int32)
    return run


class SortOrder:
    """(expr, ascending, nulls_first) — Catalyst SortOrder."""

    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.expr = expr
        self.ascending = ascending
        # Spark default: NULLS FIRST for ASC, NULLS LAST for DESC
        self.nulls_first = ascending if nulls_first is None else nulls_first


class SortExec(TpuExec):
    def __init__(self, child: TpuExec, order: Sequence[SortOrder],
                 global_sort: bool = True):
        super().__init__(child)
        self.order = list(order)
        self.global_sort = global_sort
        from ..expr.misc import contains_eager
        # eager sort keys (ANSI guards) evaluate outside jit
        self._eager_keys = contains_eager([o.expr for o in self.order])
        self._jit_sort = self._sort_one if self._eager_keys \
            else shared_method_jit(self, "_sort_one", ("order",))
        self._fused_cache = {}

    # --- sort-prefix fusion (fusion v2) ---

    def _sort_fusion_on(self, ctx: ExecContext) -> bool:
        from ..conf import FUSION_ENABLED, FUSION_SORT
        return (not self._eager_keys
                and ctx.conf.get(FUSION_ENABLED)
                and ctx.conf.get(FUSION_SORT))

    def _fused_concat_sort(self, cap: int):
        """One-program concat+key-extraction+sort at ``cap`` slots."""
        key = ("concat_sort", cap)
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = shared_fn_jit(_concat_sort_builder, self.order, cap)
            from ..jit_registry import annotate
            annotate(fn, "fused-sort:concat+sort[" + ", ".join(
                repr(o.expr) for o in self.order) + "]")
            from .fused import FUSION_STATS
            FUSION_STATS["sorts"] += 1
            self._fused_cache[key] = fn
        return fn

    def _fused_chunk_head(self, length: int, cap: int):
        key = ("chunk_head", length, cap)
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = shared_fn_jit(_chunk_head_builder, length, cap)
            from ..jit_registry import annotate
            annotate(fn, "fused-sort:chunk+head")
            self._fused_cache[key] = fn
        return fn

    def _fused_bound_prefix(self):
        key = "bound_prefix"
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = shared_fn_jit(_bound_prefix_builder, self.order)
            from ..jit_registry import annotate
            annotate(fn, "fused-sort:safe-prefix[" + ", ".join(
                repr(o.expr) for o in self.order) + "]")
            self._fused_cache[key] = fn
        return fn

    def _sort_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = [o.expr.eval(batch) for o in self.order]
        return K.sort_batch(batch, key_cols,
                            [o.ascending for o in self.order],
                            [o.nulls_first for o in self.order])

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def required_child_distributions(self):
        from ..plan.distribution import (OrderedDistribution,
                                         UnspecifiedDistribution)
        if self.global_sort:
            return [OrderedDistribution(self.order)]
        return [UnspecifiedDistribution()]

    @property
    def output_partitioning(self):
        from ..plan.distribution import RangePartitioning
        if self.global_sort:
            child = self.children[0].output_partitioning
            return RangePartitioning(self.order, child.num_partitions)
        return self.children[0].output_partitioning

    def _sort_partition(self, ctx: ExecContext,
                        stream) -> Iterator[ColumnarBatch]:
        """Buffer one partition (spillable) and sort it: one concat +
        sort when it fits the in-core budget, the out-of-core chunk
        merge (GpuSortExec.scala:242) when it does not."""
        from ..conf import SORT_OOC_ROWS
        from ..memory.spill import SpillableBatch, SpillPriority
        runs: List[SpillableBatch] = []
        total = 0
        max_run = 0
        try:
            from ..memory.retry import with_retry_no_split
            for batch in stream:
                if int(batch.num_rows) == 0:
                    continue
                total += int(batch.num_rows)
                max_run = max(max_run, batch.capacity)
                runs.append(with_retry_no_split(
                    lambda b=batch: SpillableBatch(
                        b, SpillPriority.ACTIVE_ON_DECK)))
            if not runs:
                return
            budget = max(ctx.conf.get(SORT_OOC_ROWS), max_run)
            if total <= budget:
                cap = choose_capacity(total)
                batches = [sb.get() for sb in runs]
                with ctx.semaphore:
                    if self._sort_fusion_on(ctx) and 1 < len(batches) <= 16:
                        # concat + key extraction + sort as one program
                        # (each batch count is its own signature, so
                        # bound the fan-in; bigger sets concat eagerly,
                        # and a lone batch reuses the shared sort program)
                        yield self._fused_concat_sort(cap)(*batches)
                    else:
                        merged = (batches[0] if len(batches) == 1
                                  else K.concat_batches(batches, cap))
                        yield self._jit_sort(merged)
                return
            yield from self._ooc_merge(ctx, runs, budget)
        finally:
            for sb in runs:
                sb.close()

    # --- out-of-core merge ------------------------------------------------

    def _head_row(self, batch: ColumnarBatch, run_idx: int
                  ) -> ColumnarBatch:
        """First row of a (sorted) device batch + a __run tag column,
        in an 8-capacity batch — the merge loop's pending-head token."""
        head = K.slice_batch(batch, 0, 1, 8)
        tag = ColumnVector(jnp.full(8, run_idx, jnp.int32),
                           live_mask(8, head.num_rows), dt.INT32)
        return ColumnarBatch(head.columns + [tag],
                             head.names + ["__run"], head.num_rows)

    def _dead_head(self, like: ColumnarBatch) -> ColumnarBatch:
        z = K.slice_batch(like, 0, 0, 8)
        return ColumnarBatch(z.columns, z.names, jnp.int32(0))

    def _ooc_merge(self, ctx: ExecContext, runs, budget: int
                   ) -> Iterator[ColumnarBatch]:
        """Bounded-memory k-way merge of spilled sorted runs.

        Each run is sorted and split into spilled C-row chunks with
        C = budget // (2*k); every chunk's HEAD ROW is captured at
        split time (tiny, stays device-resident). When k is too large
        for the bound (C would hit its floor), runs cascade: groups of
        runs merge into longer spilled runs first, so the final pass
        always satisfies carry <= k*C <= budget/2."""
        from ..memory.retry import with_retry_no_split
        from ..memory.spill import SpillableBatch, SpillPriority
        k = len(runs)
        floor_c = 256
        max_k = max(2, budget // (2 * floor_c))
        # 1. sort + split every input run
        split: List[Tuple[List, List]] = []   # (chunk sbs, chunk heads)
        for sb in runs:
            with ctx.semaphore:
                run = with_retry_no_split(
                    lambda sb=sb: self._jit_sort(sb.get()))
            sb.close()
            split.append(self._split_run(ctx, run, budget,
                                         max(min(k, max_k), 2)))
        # 2. cascade while too many runs for the residency bound:
        # groups merge into one longer run whose emitted pieces are
        # re-split to C-row chunks (pieces can be up to budget-sized)
        while len(split) > max_k:
            group, split = split[:max_k], split[max_k:]
            combined_chunks: List = []
            combined_heads: List = []
            for piece in self._merge_chunklists(ctx, group, budget):
                parts, hlist = self._split_run(ctx, piece, budget,
                                               max_k)
                combined_chunks.extend(parts)
                combined_heads.extend(hlist)
            split.append((combined_chunks, combined_heads))
        yield from self._merge_chunklists(ctx, split, budget)

    def _split_run(self, ctx: ExecContext, run: ColumnarBatch,
                   budget: int, k: int):
        """Split a sorted device run into spilled C-row chunks plus
        their (device-resident, 8-cap) head rows."""
        from ..memory.retry import with_retry_no_split
        from ..memory.spill import SpillableBatch, SpillPriority
        C = max(256, budget // (2 * k))
        chunk_cap = choose_capacity(C)
        n = int(run.num_rows)
        parts, part_heads = [], []
        fused = self._fused_chunk_head(C, chunk_cap) \
            if self._sort_fusion_on(ctx) else None
        for start in range(0, max(n, 1), C):
            with ctx.semaphore:
                if fused is not None:
                    # chunk slice + head-row token in one program
                    piece, head = fused(run, jnp.int32(start))
                    part_heads.append(head)
                else:
                    piece = K.slice_batch(run, start, jnp.int32(C),
                                          chunk_cap)
                    part_heads.append(self._head_row(piece, 0))
            parts.append(with_retry_no_split(
                lambda p=piece: SpillableBatch(
                    p, SpillPriority.ACTIVE_ON_DECK)))
        return parts, part_heads

    def _merge_chunklists(self, ctx: ExecContext, split, budget: int
                          ) -> Iterator[ColumnarBatch]:
        """Merge k chunklists ((spilled chunks, head rows) per run).

        Loop invariant: every emitted row orders <= the first row of
        every unloaded chunk, so the concatenation of emitted batches
        is globally sorted. The carry holds rows that may still be
        preceded by unloaded rows; per run at most one chunk of rows
        can be parked there, so carry <= k*C <= budget/2 and device
        residency stays O(budget)."""
        from ..memory.retry import with_retry_no_split
        m = ctx.metrics_for(self.exec_id)
        peak_m = m.setdefault("sortOocPeakRows",
                              Metric("sortOocPeakRows", Metric.DEBUG))
        k = len(split)
        chunks = [parts for parts, _ in split]
        all_heads = []
        for ri, (_, hlist) in enumerate(split):
            # re-tag heads with this merge's run index
            all_heads.append([
                ColumnarBatch(h.columns[:-1] + [ColumnVector(
                    jnp.full(8, ri, jnp.int32), h.columns[-1].validity,
                    dt.INT32)], h.names, h.num_rows) for h in hlist])
        next_chunk = [0] * k
        heads: List[Optional[ColumnarBatch]] = [
            hl[0] if hl else None for hl in all_heads]
        schema_like = next(h for h in heads if h is not None)
        carry: Optional[ColumnarBatch] = None

        def pending() -> List[ColumnarBatch]:
            return [h if h is not None else self._dead_head(schema_like)
                    for h in heads]

        fuse = self._sort_fusion_on(ctx)

        def pick_heads() -> ColumnarBatch:
            """Sorted pending-heads batch — fused concat+sort when on
            (one program), eager concat + sort launch otherwise."""
            with ctx.semaphore:
                if fuse:
                    return self._fused_concat_sort(8 * k)(*pending())
                hb = K.concat_batches(pending(), 8 * k)
                return self._jit_sort_heads(hb)

        try:
            while True:
                live_heads = [h for h in heads if h is not None]
                if not live_heads:
                    if carry is not None and int(carry.num_rows) > 0:
                        yield carry
                    return
                # pick the run whose pending chunk head is smallest
                # (device comparison — exact sort semantics)
                hs = pick_heads()
                r = int(hs.column("__run").data[0])
                i = next_chunk[r]
                chunk = with_retry_no_split(chunks[r][i].get)
                chunks[r][i].close()
                next_chunk[r] += 1
                heads[r] = all_heads[r][next_chunk[r]] \
                    if next_chunk[r] < len(chunks[r]) else None
                # merge the chunk into the carry and emit the safe
                # prefix (rows ordered <= every pending head); pure
                # compute over already-held batches, so RetryOOM just
                # re-runs it after a synchronous spill

                def merge_step(carry=carry, chunk=chunk):
                    with ctx.semaphore:
                        if carry is None:
                            return self._jit_sort(chunk)
                        cap = choose_capacity(
                            int(carry.num_rows) + int(chunk.num_rows))
                        if fuse:
                            return self._fused_concat_sort(cap)(
                                carry, chunk)
                        return self._jit_sort(K.concat_batches(
                            [carry, chunk], cap))
                merged = with_retry_no_split(merge_step)
                peak_m.set(max(peak_m.value, int(merged.num_rows)))
                live_heads = [h for h in heads if h is not None]
                if not live_heads:
                    carry = merged
                    continue
                hs = pick_heads()
                with ctx.semaphore:
                    if fuse:
                        # bound-row slice + prefix count, one program
                        n_le = self._fused_bound_prefix()(merged, hs)
                    else:
                        bound = K.slice_batch(hs, 0, 1, 8)
                        n_le = self._jit_safe_prefix(merged, bound)
                n = int(n_le)
                if n > 0:
                    with ctx.semaphore:
                        out = K.slice_batch(merged, 0, jnp.int32(n),
                                            choose_capacity(n))
                        rest = int(merged.num_rows) - n
                        carry = K.slice_batch(
                            merged, jnp.int32(n),
                            jnp.int32(max(rest, 0)),
                            choose_capacity(max(rest, 1)))
                    yield out
                else:
                    carry = merged
        finally:
            for parts in chunks:
                for p in parts:
                    p.close()

    def _jit_sort_heads(self, hb: ColumnarBatch) -> ColumnarBatch:
        # same registry key as _jit_sort (identical program; the trace
        # cache keys on the head batch's own structure)
        return self._jit_sort(hb)

    def _jit_safe_prefix(self, merged: ColumnarBatch,
                         bound: ColumnarBatch):
        """Count of merged rows ordering <= the bound row (they form a
        prefix of the sorted batch; range_partition_ids shares the sort
        comparator exactly, so 'strictly after bound' == unsafe)."""
        if not hasattr(self, "_safe_prefix_fn"):
            from ..expr.misc import contains_eager
            if contains_eager([o.expr for o in self.order]):
                self._safe_prefix_fn = _safe_prefix_builder(self.order)
            else:
                self._safe_prefix_fn = shared_fn_jit(
                    _safe_prefix_builder, self.order)
        return self._safe_prefix_fn(merged, bound)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        if not self.global_sort:
            for batch in self.children[0].execute(ctx):
                if int(batch.num_rows) == 0:
                    continue
                with ctx.semaphore:
                    yield self._jit_sort(batch)
            return
        # Global sort over a range-partitioned child: sorting each
        # partition and emitting in partition order is globally sorted
        # (partition i's rows all precede partition i+1's).
        for part in self.children[0].execute_partitioned(ctx):
            yield from self._sort_partition(ctx, part)

    def node_description(self) -> str:
        keys = ", ".join(
            f"{o.expr!r} {'ASC' if o.ascending else 'DESC'}"
            for o in self.order)
        return f"Sort[{keys}]{'' if self.global_sort else ' (local)'}"


class TopNExec(TpuExec):
    """ORDER BY + LIMIT n fused (GpuTopN, limit.scala): keeps only the
    top n rows per batch, then a final n-way selection — bounds memory
    without the full-sort concat."""

    def __init__(self, child: TpuExec, order: Sequence[SortOrder], limit: int):
        super().__init__(child)
        self.order = list(order)
        self.limit = limit
        from ..expr.misc import contains_eager
        self._jit_topn = self._topn \
            if contains_eager([o.expr for o in self.order]) \
            else shared_method_jit(self, "_topn", ("order", "limit"))
        shrink_cap = choose_capacity(self.limit)
        self._jit_shrink = lambda b: K.repack_to(b, shrink_cap)

    def _topn(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = [o.expr.eval(batch) for o in self.order]
        sorted_b = K.sort_batch(batch, key_cols,
                                [o.ascending for o in self.order],
                                [o.nulls_first for o in self.order])
        return K.local_limit(sorted_b, self.limit)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        partials: List[ColumnarBatch] = []
        total = 0
        # Each partial holds <= limit live rows; compact it down to the
        # limit's capacity bucket so retained memory is O(batches*limit),
        # not O(batches*input_capacity).
        part_cap = choose_capacity(self.limit)
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            with ctx.semaphore:
                part = self._jit_topn(batch)
                if part.capacity > part_cap:
                    part = self._jit_shrink(part)
            partials.append(part)
            total += int(part.num_rows)
        if not partials:
            return
        cap = choose_capacity(max(total, self.limit))
        with ctx.semaphore:
            merged = (partials[0] if len(partials) == 1
                      else K.concat_batches(partials, cap))
            yield self._jit_topn(merged)

    def node_description(self) -> str:
        return f"TopN[{self.limit}]"
