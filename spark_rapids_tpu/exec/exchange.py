"""Exchange execs: shuffle (hash/round-robin/range) and broadcast.

Rebuild of GpuShuffleExchangeExecBase.scala (:167,
prepareBatchShuffleDependency :277) + GpuHashPartitioningBase /
GpuRangePartitioner + GpuBroadcastExchangeExec.scala:352 (SURVEY §2.7):
each incoming batch is split on-device into the target partitions
(parallel/partition.py — the cudf Table.partition equivalent), the
per-partition slices become shuffle blocks via the manager
(device-cached or serialized host blocks), and the read side streams one
reduce partition's blocks back.

These nodes are *planned*: overrides.ensure_distribution inserts them
wherever a parent operator's required distribution (aggregate merge
clustering, join co-partitioning, global-sort ordering) is not satisfied
by its child — Spark's EnsureRequirements over our exec tree.

Under a device mesh the same partitioning feeds the all-to-all
collective instead (parallel/shuffle.py shuffle_exchange) — that path
compiles into the SPMD program and never touches this manager
(plan/mesh_executor.py lowers these nodes to collectives).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity)
from ..conf import SHUFFLE_PARTITIONS
from ..expr.core import Expression
from ..jit_registry import shared_fn_jit
from ..ops import kernels as K
from ..parallel.partition import (PartitionedBatch, hash_partition_ids,
                                  partition_batch, range_partition_ids,
                                  round_robin_partition_ids,
                                  string_from_padded)
from ..parallel.shuffle_manager import ShuffleManager, shuffle_manager
from .base import ExecContext, Metric, NvtxTimer, Schema, TpuExec

_SHUFFLE_IDS = itertools.count(1)
_IDS_LOCK = threading.Lock()


def next_shuffle_id() -> int:
    with _IDS_LOCK:
        return next(_SHUFFLE_IDS)


def seed_shuffle_ids(base: int) -> None:
    """Restart the local shuffle-id counter at ``base``.

    Shuffle ids are allocated during per-worker plan translation, so
    peers agree on them only if their counters start from the same
    point. A process-lifetime counter breaks the moment membership is
    elastic: a worker that joins (or REjoins) mid-session has built
    fewer exchanges than the veterans, its ids lag theirs, and the
    cluster deadlocks with every worker waiting at a differently-keyed
    stage barrier. The driver therefore ships a fresh ``sid_base``
    with every attempt and workers re-seed before translating."""
    global _SHUFFLE_IDS
    with _IDS_LOCK:
        _SHUFFLE_IDS = itertools.count(base)


def partition_slice(pb: PartitionedBatch, i: int) -> ColumnarBatch:
    """Extract partition i of a PartitionedBatch as a standalone batch."""
    S = pb.slot_capacity
    cols = []
    for spec, dtype in zip(pb.columns, pb.dtypes):
        if isinstance(dtype, dt.ArrayType):
            from ..parallel.partition import list_from_packed
            lens, valid, cdata, cok, e_counts = spec
            cols.append(list_from_packed(lens[i], valid[i], cdata[i],
                                         cok[i], e_counts[i],
                                         dtype.element_type))
        elif dtype == dt.STRING:
            padded, lens, valid = spec
            cols.append(string_from_padded(padded[i], lens[i], valid[i]))
        elif isinstance(dtype, dt.DecimalType) and dtype.is_wide:
            from ..columnar.decimal128 import Decimal128Column
            hi, lo, valid = spec
            cols.append(Decimal128Column(hi[i], lo[i], valid[i], dtype))
        else:
            data, valid = spec
            cols.append(ColumnVector(data[i], valid[i], dtype))
    return ColumnarBatch(cols, pb.names, pb.counts[i])


def _partition_slices(pb: PartitionedBatch, num_parts: int):
    return [partition_slice(pb, i) for i in range(num_parts)]


def _range_partition_builder(orders, num_parts):
    def run(batch: ColumnarBatch, bnds):
        keys = [o.expr.eval(batch) for o in orders]
        pids = range_partition_ids(
            keys, bnds, [o.ascending for o in orders],
            [o.nulls_first for o in orders])
        return _partition_slices(partition_batch(batch, pids, num_parts),
                                 num_parts)
    return run


def _hash_partition_builder(key_exprs, num_parts):
    def run(batch: ColumnarBatch):
        keys = [e.eval(batch) for e in key_exprs]
        pids = hash_partition_ids(keys, num_parts)
        return _partition_slices(partition_batch(batch, pids, num_parts),
                                 num_parts)
    return run


def _rr_partition_builder(num_parts):
    def run(batch: ColumnarBatch):
        pids = round_robin_partition_ids(batch.capacity, num_parts)
        return _partition_slices(partition_batch(batch, pids, num_parts),
                                 num_parts)
    return run


class ShuffleExchangeExec(TpuExec):
    """Repartitioning through the ShuffleManager.

    ``key_exprs`` non-empty -> hash partitioning; empty + ``sort_orders``
    -> range partitioning (sample child, compute bounds, partition by
    bound search); both empty -> round-robin (or a single-partition
    concentrator when num_partitions == 1).
    """

    def __init__(self, child: TpuExec,
                 key_exprs: Sequence[Expression],
                 num_partitions: Optional[int] = None,
                 manager: Optional[ShuffleManager] = None,
                 sort_orders: Optional[Sequence] = None):
        super().__init__(child)
        self.key_exprs = list(key_exprs)
        self.sort_orders = list(sort_orders) if sort_orders else []
        if self.key_exprs and self.sort_orders:
            raise ValueError("hash keys and range orders are exclusive")
        self.num_partitions = num_partitions
        self.manager = manager
        self.shuffle_id = next_shuffle_id()
        self._written = False
        self._jit_cache = {}
        self._global_counts = None
        self._global_stats = None
        #: speculation outcome from the driver barrier: None, or
        #: {"allowed": {worker_id: (map_ids...)}} restricting which
        #: peer blocks readers may consume (first-result-wins dedup)
        self._winners = None
        self._barrier_done = False
        self._own_map_ids: List[int] = []

    def reset_for_rerun(self) -> None:
        super().reset_for_rerun()
        # fresh shuffle id: the previous run's blocks are owned by the
        # old id (and may already be cleaned up)
        self.shuffle_id = next_shuffle_id()
        self._written = False
        self._global_counts = None
        self._global_stats = None
        self._winners = None
        self._barrier_done = False
        self._own_map_ids = []

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    @property
    def output_partitioning(self):
        from ..plan.distribution import (HashPartitioning, RangePartitioning,
                                         SinglePartition, UnknownPartitioning)
        n = self.num_partitions or 1
        if self.sort_orders:
            return RangePartitioning(self.sort_orders, n)
        if self.key_exprs:
            return HashPartitioning(self.key_exprs, n)
        if n == 1:
            return SinglePartition()
        return UnknownPartitioning(n)

    def _effective_parts(self, ctx: ExecContext) -> int:
        return self.num_partitions or ctx.conf.get(SHUFFLE_PARTITIONS)

    def _partition_fn(self, num_parts: int, bounds=None):
        """Jitted batch -> [partition batches]. The slice-out of every
        partition lives INSIDE the jit: partitioning plus N slices is
        one XLA program per batch structure instead of hundreds of
        eager dispatches per map batch. Shared process-wide via the jit
        registry: every exchange over the same keys/orders and fan-out
        reuses one traced fn."""
        key = (num_parts, bounds is not None)
        if key not in self._jit_cache:
            from ..expr.misc import contains_eager
            eager = contains_eager(
                list(self.key_exprs)
                + [o.expr for o in self.sort_orders])
            if self.sort_orders:
                self._jit_cache[key] = _range_partition_builder(
                    self.sort_orders, num_parts) if eager else \
                    shared_fn_jit(_range_partition_builder,
                                  self.sort_orders, num_parts)
            elif self.key_exprs:
                self._jit_cache[key] = _hash_partition_builder(
                    self.key_exprs, num_parts) if eager else \
                    shared_fn_jit(_hash_partition_builder,
                                  self.key_exprs, num_parts)
            else:
                self._jit_cache[key] = shared_fn_jit(
                    _rr_partition_builder, num_parts)
        return self._jit_cache[key]

    # --- range bounds (GpuRangePartitioner.sketch: sample to the
    # driver, sort, take quantile bounds) ---
    def _sample_rows(self, ctx: ExecContext,
                     batches: List[ColumnarBatch],
                     num_parts: int) -> List[tuple]:
        """Host-side sample row tuples of the sort keys."""
        from ..conf import RANGE_SAMPLE_SIZE
        orders = self.sort_orders
        per_part = ctx.conf.get(RANGE_SAMPLE_SIZE)
        per_batch = max(1, (num_parts * per_part)
                        // max(len(batches), 1))
        samples: List[tuple] = []  # row tuples of physical values
        for b in batches:
            n = int(b.num_rows)
            take = min(n, per_batch)
            if take == 0:
                continue
            with ctx.semaphore:
                keys = [o.expr.eval(b) for o in orders]
            # host copies of the first `take` live rows
            cols = []
            for kc in keys:
                vals, mask = kc.to_numpy(take)
                cols.append((vals, mask))
            for i in range(take):
                samples.append(tuple(
                    (None if not cols[k][1][i] else cols[k][0][i])
                    for k in range(len(orders))))
        return samples

    def _compute_bounds(self, ctx: ExecContext,
                        batches: List[ColumnarBatch], num_parts: int):
        """Sample the buffered child, return per-key bound Columns with
        (num_parts - 1) rows, device-resident. Under a cluster context
        the local sketch all-gathers through the driver first
        (GpuRangePartitioner.sketch sends samples to the driver), so
        every worker derives IDENTICAL bounds and range partitions stay
        globally consistent."""
        orders = self.sort_orders
        pre = (ctx.cluster.bounds_for(self.shuffle_id)
               if ctx.cluster is not None else None)
        if pre is not None:
            # stage-level retry of a REUSED range exchange: the renamed
            # blocks were cut with the previous attempt's bounds, so the
            # freshly re-executed shards must use the SAME bounds — and
            # every worker takes this shortcut consistently (skipping
            # the sample gather without deadlock), because the driver
            # only marks a position reusable after verifying every
            # survivor holds the job's record.
            bounds_rows = [tuple(r) for r in pre]
            ctx.cluster.record_bounds(self.shuffle_id, bounds_rows)
            return self._bounds_device_cols(bounds_rows)
        samples = self._sample_rows(ctx, batches, num_parts)
        if ctx.cluster is not None:
            gathered = ctx.cluster.gather(("bounds", self.shuffle_id),
                                          samples)
            samples = [t for lst in gathered if lst for t in lst]
        if not samples:
            samples = [tuple(None for _ in orders)]

        def sort_key(row):
            parts = []
            for v, o in zip(row, orders):
                null_rank = 0 if o.nulls_first else 2
                if v is None:
                    parts.append((null_rank if o.ascending else 2 - null_rank,
                                  0))
                else:
                    key = str(v) if isinstance(v, (bytes, str)) else v
                    # `-key` is not defined for str/bool/date/Decimal
                    # sample values; flip comparisons instead
                    parts.append((1, key if o.ascending
                                  else _InvertedKey(key)))
            return parts
        samples.sort(key=sort_key)
        # quantile bounds: num_parts-1 cut rows
        bounds_rows = []
        m = len(samples)
        for i in range(1, num_parts):
            bounds_rows.append(samples[min(m - 1, (i * m) // num_parts)])
        if ctx.cluster is not None:
            # remember the cut rows: a stage-level retry that reuses
            # this exchange's blocks must partition with the same bounds
            ctx.cluster.record_bounds(self.shuffle_id, bounds_rows)
        return self._bounds_device_cols(bounds_rows)

    def _bounds_device_cols(self, bounds_rows):
        orders = self.sort_orders
        # build device columns for the bounds; capacity == bound count
        # exactly (range_partition_ids treats every slot as a bound).
        # Sampled non-string values are already physical lanes (the
        # to_numpy copy is raw), so primitive bounds are built directly
        # rather than through column_from_numpy's python-value coercion.
        in_schema = self.children[0].output_schema
        bound_cols = []
        cap = len(bounds_rows)
        from ..columnar.vector import column_from_numpy
        for k, o in enumerate(orders):
            ktype = o.expr.data_type(in_schema)
            mask = np.array([r[k] is not None for r in bounds_rows],
                            dtype=bool)
            if ktype == dt.STRING:
                values = np.array([r[k] for r in bounds_rows], dtype=object)
                bound_cols.append(column_from_numpy(values, cap,
                                                    dtype=ktype, mask=mask))
            else:
                phys = np.dtype(ktype.physical)
                data = np.array([0 if r[k] is None else r[k]
                                 for r in bounds_rows], dtype=phys)
                bound_cols.append(ColumnVector(jnp.asarray(data),
                                               jnp.asarray(mask), ktype))
        return bound_cols, len(bounds_rows)

    def _write(self, ctx: ExecContext) -> None:
        """Map phase: drain the child, write all blocks. Idempotent."""
        if self._written:
            return
        self._written = True
        # per-run drain budget: the planner counts how many tree edges
        # drain this exchange (a subtree shared by the two halves of a
        # full-outer union drains twice); blocks free on the LAST drain
        self._consumers = getattr(self, "_planned_consumers", 1)
        mgr = self.manager or shuffle_manager()
        n_parts = self._effective_parts(ctx)
        mgr.register_shuffle(self.shuffle_id, n_parts)
        m = ctx.metrics_for(self.exec_id)
        part_time = m.setdefault("partitionTime",
                                 Metric("partitionTime", Metric.MODERATE,
                                        "ns"))
        write_rows = m.setdefault("shuffleWriteRows",
                                  Metric("shuffleWriteRows",
                                         Metric.ESSENTIAL))
        write_bytes = m.setdefault("shuffleBytesWritten",
                                   Metric("shuffleBytesWritten",
                                          Metric.ESSENTIAL, "B"))
        # per-attempt map-id namespace: a stage retry renames the prior
        # attempt's surviving blocks into this shuffle id, so freshly
        # re-executed shards must not collide with their map ids
        map_id = ctx.cluster.map_id_base if ctx.cluster is not None else 0
        push_route = self._push_route(ctx, mgr, n_parts)
        buddy = self._buddy_endpoint(ctx)
        bypassed_before = getattr(mgr, "bypassed_bytes", 0)
        if self.sort_orders:
            # buffer spillable, sample bounds, then partition
            from ..memory.spill import SpillableBatch, SpillPriority
            held = []
            try:
                from ..memory.retry import with_retry_no_split
                for batch in self.children[0].execute(ctx):
                    if int(batch.num_rows) == 0:
                        continue
                    held.append(with_retry_no_split(
                        lambda b=batch: SpillableBatch(
                            K.compact_for_transfer(b),
                            SpillPriority.ACTIVE_ON_DECK)))
                batches = with_retry_no_split(
                    lambda: [sb.get() for sb in held])
                bounds, n_bounds = self._compute_bounds(ctx, batches,
                                                        n_parts)
                fn = self._partition_fn(n_parts, bounds=True)
                for batch in batches:
                    t0 = time.perf_counter_ns()

                    def write_one(batch=batch, map_id=map_id,
                                  bounds=bounds):
                        # replay-safe: block writes overwrite by
                        # (shuffle, map, reduce)
                        with ctx.semaphore:
                            # per-slice compaction: each slice carries
                            # the full input capacity (static
                            # worst-case skew bound) but typically
                            # holds ~1/P of the rows
                            parts = [K.compact_for_transfer(p)
                                     for p in fn(batch, bounds)]
                        return mgr.write_map_output(
                            self.shuffle_id, map_id, parts,
                            local_ok=ctx.cluster is None)
                    write_bytes.add(with_retry_no_split(write_one))
                    part_time.add(time.perf_counter_ns() - t0)
                    write_rows.add(int(batch.num_rows))
                    if push_route is not None:
                        mgr.push_map_output(self.shuffle_id, map_id,
                                            push_route,
                                            who=self._push_who(ctx))
                    if buddy is not None:
                        mgr.replicate_map_output(self.shuffle_id,
                                                 map_id, buddy,
                                                 who=self._push_who(ctx))
                    self._own_map_ids.append(map_id)
                    map_id += 1
            finally:
                for sb in held:
                    sb.close()
            self._finish_write(ctx, mgr, push_route, bypassed_before,
                               buddy=buddy)
            return
        self._own_map_ids.extend(
            self._run_map_loop(ctx, mgr, n_parts, map_id,
                               self.children[0], push_route=push_route,
                               buddy=buddy))
        self._finish_write(ctx, mgr, push_route, bypassed_before,
                           buddy=buddy)

    def _push_route(self, ctx: ExecContext, mgr,
                    n_parts: int) -> Optional[dict]:
        """reduce partition -> owning endpoint, when push-based shuffle
        applies to this exchange: cluster mode, the manager's push path
        on, and the planner's ``_push_ok`` tag present (overrides tags
        every planned shuffle exchange; hand-built plans opt in
        explicitly). Routing is BEST-EFFORT — AQE may later coalesce or
        skew-split partitions across different readers, in which case a
        mispredicted push just idles in a segment nobody reads and the
        pull path serves the real reader."""
        if (ctx.cluster is None
                or not getattr(mgr, "push_enabled", False)
                or not getattr(self, "_push_ok", False)):
            return None
        try:
            return ctx.cluster.partition_owners(n_parts)
        except Exception:
            return None  # no assignment info: pull covers everything

    @staticmethod
    def _push_who(ctx: ExecContext) -> str:
        """Stable sender label for the ``push.send`` fault site, so a
        chaos plan can address exactly one worker's push path (ports
        are random; worker ids are not)."""
        return (f"w={ctx.cluster.worker_id}"
                if ctx.cluster is not None else "w=local")

    def _buddy_endpoint(self, ctx: ExecContext) -> Optional[str]:
        """Replication target for this worker's completed map output
        under k=2 shuffle durability: the next peer in ring order.
        None when replication is off, local mode, or there is no
        distinct peer to hold the copy."""
        from ..conf import SHUFFLE_REPLICATION_FACTOR
        if (ctx.cluster is None
                or ctx.conf.get(SHUFFLE_REPLICATION_FACTOR) < 2):
            return None
        peers = ctx.cluster.peers
        if len(peers) < 2:
            return None
        return peers[(ctx.cluster.worker_id + 1) % len(peers)]

    @staticmethod
    def _replica_targets(ctx: ExecContext) -> Optional[dict]:
        """origin endpoint -> its ring buddy, handed to the fetch path
        as a last-resort fallback. Always populated in multi-worker
        clusters — with replication off (or an incomplete replica set)
        the buddy answers "no coverage" and the reader falls back to
        the normal stage-retry path, so the only cost is one extra
        round-trip on an already-failing fetch."""
        if ctx.cluster is None:
            return None
        peers = ctx.cluster.peers
        n = len(peers)
        if n < 2:
            return None
        return {peers[i]: peers[(i + 1) % n] for i in range(n)}

    def _finish_write(self, ctx: ExecContext, mgr, push_route,
                      bypassed_before: int, buddy=None) -> None:
        """Map phase epilogue: drain in-flight pushes BEFORE the stage
        barrier can release readers, and report bytes that took the
        zero-copy local channel. With a replication buddy, the replica
        manifest publishes AFTER the drain (so it only ever vouches for
        blocks that actually landed) and BEFORE the barrier report (so
        any map id a reader can learn about is covered)."""
        if push_route is not None or buddy is not None:
            mgr.drain_pushes()
        if buddy is not None:
            mgr.publish_replica_manifest(self.shuffle_id, buddy)
        bypassed = getattr(mgr, "bypassed_bytes", 0) - bypassed_before
        if bypassed > 0:
            m = ctx.metrics_for(self.exec_id)
            m.setdefault("shuffleBytesBypassed",
                         Metric("shuffleBytesBypassed",
                                Metric.ESSENTIAL, "B")).add(bypassed)

    def record_mesh_exchange(self, ctx: ExecContext, nbytes: int,
                             resident: bool) -> None:
        """Mesh-lane byte accounting for this exchange's stage boundary.

        On the SPMD stage path nothing is serialized: the child stage's
        output is handed to the consumer program device-resident, so
        every boundary byte lands in ``shuffleBytesBypassed`` (it
        bypassed the serialized shuffle write path this class's
        ``_write`` implements — ``shuffleBytesWritten`` stays 0 on mesh
        runs, which is exactly the "device-resident stages dominate"
        signal the bench gate checks). Bytes that additionally rode an
        in-program collective (a true repartition: non-resident hash /
        range / round-robin all_to_all, single-partition all_gather)
        are ALSO counted as ``shuffleBytesWire`` — ICI traffic, not a
        write. A resident exchange contributes bypassed bytes only.
        """
        if nbytes <= 0:
            return
        m = ctx.metrics_for(self.exec_id)
        m.setdefault("shuffleBytesBypassed",
                     Metric("shuffleBytesBypassed",
                            Metric.ESSENTIAL, "B")).add(nbytes)
        if not resident:
            m.setdefault("shuffleBytesWire",
                         Metric("shuffleBytesWire",
                                Metric.ESSENTIAL, "B")).add(nbytes)

    def _run_map_loop(self, ctx: ExecContext, mgr, n_parts: int,
                      map_id: int, child: TpuExec,
                      push_route: Optional[dict] = None,
                      buddy: Optional[str] = None) -> List[int]:
        """Drain ``child``, partition every batch, write blocks under
        ascending map ids from ``map_id``; returns the ids written.
        Shared by the normal (non-range) map phase and speculative
        re-execution of a straggler's shard, which runs a re-sharded
        clone of the stage subtree under a disjoint map-id namespace."""
        m = ctx.metrics_for(self.exec_id)
        part_time = m.setdefault("partitionTime",
                                 Metric("partitionTime", Metric.MODERATE,
                                        "ns"))
        write_rows = m.setdefault("shuffleWriteRows",
                                  Metric("shuffleWriteRows",
                                         Metric.ESSENTIAL))
        write_bytes = m.setdefault("shuffleBytesWritten",
                                   Metric("shuffleBytesWritten",
                                          Metric.ESSENTIAL, "B"))
        from ..memory.retry import with_retry_no_split
        written: List[int] = []
        for batch in child.execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            t0 = time.perf_counter_ns()

            def write_one(batch=batch, map_id=map_id):
                # partition + block write re-runs cleanly on RetryOOM:
                # blocks are keyed (shuffle, map, reduce) so a replay
                # overwrites, never duplicates
                with ctx.semaphore:
                    b = K.compact_for_transfer(batch)
                    fn = self._partition_fn(n_parts)
                    parts = [K.compact_for_transfer(p)
                             for p in fn(b)]
                wrote = mgr.write_map_output(
                    self.shuffle_id, map_id, parts,
                    local_ok=ctx.cluster is None)
                return int(b.num_rows), wrote
            rows_written, bytes_written = with_retry_no_split(write_one)
            part_time.add(time.perf_counter_ns() - t0)
            write_rows.add(rows_written)
            write_bytes.add(bytes_written)
            if push_route is not None:
                # eager push at map completion: this map's blocks start
                # uploading to their reducers while the next batch is
                # still computing
                mgr.push_map_output(self.shuffle_id, map_id, push_route,
                                    who=self._push_who(ctx))
            if buddy is not None:
                mgr.replicate_map_output(self.shuffle_id, map_id, buddy,
                                         who=self._push_who(ctx))
            written.append(map_id)
            map_id += 1
        return written

    def run_speculative_maps(self, ctx: ExecContext,
                             map_id_base: int) -> List[int]:
        """Speculative map execution entry: run THIS exchange's map
        phase under an explicit map-id namespace, bypassing the
        ``_written`` idempotence latch and the barrier. The cluster's
        speculate callback invokes it on a clone of the stage subtree
        re-sharded to the straggler's logical ids, with ``shuffle_id``
        pointed at the live shuffle — blocks land in this worker's
        store and win or lose at the driver's first-result-wins
        commit."""
        if self.sort_orders:
            raise RuntimeError(
                "range exchanges are not speculation-eligible")
        mgr = self.manager or shuffle_manager()
        n_parts = self._effective_parts(ctx)
        mgr.register_shuffle(self.shuffle_id, n_parts)
        push_route = self._push_route(ctx, mgr, n_parts)
        buddy = self._buddy_endpoint(ctx)
        written = self._run_map_loop(ctx, mgr, n_parts, map_id_base,
                                     self.children[0],
                                     push_route=push_route, buddy=buddy)
        if push_route is not None or buddy is not None:
            # speculative pushes drain before the result reports: the
            # winners filter applies at segment-index granularity, so a
            # losing worker's pushed entries are simply never consumed
            mgr.drain_pushes()
        if buddy is not None:
            # re-publish: the manifest must cover the speculative maps
            # before their ids can reach the driver's commit
            mgr.publish_replica_manifest(self.shuffle_id, buddy)
        return written

    def _release(self, mgr) -> None:
        """One consumer finished a full drain. Shared subtrees (the two
        halves of a full-outer union both reference this instance) mean
        multiple drains per run; only the last one frees the blocks —
        an eager unregister would break the sibling's re-read (the
        round-4 FULL OUTER JOIN + AQE KeyError)."""
        self._consumers = getattr(self, "_consumers", 1) - 1
        if self._consumers <= 0:
            mgr.unregister_shuffle(self.shuffle_id)

    # kept for existing callers/tests
    def write(self, ctx: ExecContext) -> None:
        self._write(ctx)

    def read_partition(self, ctx: ExecContext,
                       reduce_id: int) -> Iterator[ColumnarBatch]:
        mgr = self.manager or shuffle_manager()
        self._write(ctx)
        yield from mgr.read_partition(self.shuffle_id, reduce_id)

    # --- AQE surface (GpuCustomShuffleReaderExec analogue) ---
    def _cluster_barrier(self, ctx: ExecContext):
        """Speculation-aware driver barrier, once per run: reports this
        worker's own map ids and exact per-(map, reduce) sizes, may run
        speculative work for a straggler inside the call, and caches
        the winners verdict that filters every subsequent read and
        stats gather (first-result-wins dedup). With speculation off
        the driver keeps its plain all-or-nothing barrier and the
        verdict is None (no filtering)."""
        if self._barrier_done:
            return self._winners
        mgr = self.manager or shuffle_manager()
        detail = mgr.map_output_statistics(
            self.shuffle_id, map_ids=set(self._own_map_ids)).detail
        def leaf_stage(node) -> bool:
            return all(not isinstance(c, ShuffleExchangeExec)
                       and leaf_stage(c) for c in node.children)

        # only leaf map stages are speculation-eligible: a re-run of a
        # subtree with its own exchange would need a nested barrier,
        # and range exchanges gather bounds cooperatively
        self._winners = ctx.cluster.barrier(
            self.shuffle_id, getattr(self, "_cluster_pos", -1),
            detail=detail,
            spec_ok=not self.sort_orders and leaf_stage(self))
        self._barrier_done = True
        return self._winners

    def _allowed_by_endpoint(self, ctx: ExecContext):
        """Winners verdict -> per-peer-endpoint allowed map-id sets for
        the fetch filter. None when no speculation verdict exists (all
        blocks are authoritative)."""
        winners = self._winners
        if not winners or winners.get("allowed") is None:
            return None
        peers = ctx.cluster.peers
        allowed = winners["allowed"]
        return {peers[w]: set(allowed.get(w, ()))
                for w in range(len(peers))}

    def materialized_stats(self, ctx: ExecContext):
        """Write the map side (idempotent) and return
        ``(rows, bytes)`` lists per reduce partition — the
        MapOutputStatistics AQE decisions read.

        Cluster mode: a speculation-aware barrier resolves which maps
        won, then each worker's WINNING local stats all-gather through
        the driver and sum, so every worker computes IDENTICAL global
        statistics (the fix for round-2's divergent-coalescing bug —
        decisions must be a pure function of global state, never of
        local map outputs)."""
        mgr = self.manager or shuffle_manager()
        self._write(ctx)
        if ctx.cluster is None:
            st = mgr.map_output_statistics(self.shuffle_id)
            return st.rows_by_reduce, st.bytes_by_reduce
        if self._global_stats is not None:
            return self._global_stats
        winners = self._cluster_barrier(ctx)
        mine: Optional[set] = set(self._own_map_ids)
        if winners and winners.get("allowed") is not None:
            mine = set(winners["allowed"].get(
                ctx.cluster.worker_id, ()))
        st = mgr.map_output_statistics(self.shuffle_id, map_ids=mine)
        gathered = ctx.cluster.gather(
            ("aqe_stats", self.shuffle_id),
            (st.rows_by_reduce, st.bytes_by_reduce))
        n = st.num_partitions
        rows = [sum(g[0][i] for g in gathered if g) for i in range(n)]
        nbytes = [sum(g[1][i] for g in gathered if g) for i in range(n)]
        self._global_stats = (rows, nbytes)
        self._global_counts = rows
        return self._global_stats

    def materialized_row_counts(self, ctx: ExecContext) -> List[int]:
        """Rows per reduce partition (the byte-blind legacy accessor;
        kept for existing callers — materialized_stats is the AQE
        surface)."""
        return self.materialized_stats(ctx)[0]

    @staticmethod
    def coalesce_groups(counts: List[int], min_rows: int,
                        byte_counts: Optional[List[int]] = None,
                        target_bytes: int = 0) -> List[List[int]]:
        """Greedy adjacent grouping: each group closes on reaching
        min_rows OR, when measured byte sizes are supplied,
        target_bytes — whichever lands first (the last group may reach
        neither). CoalesceShufflePartitions' strategy generalized from
        rows to measured bytes."""
        groups: List[List[int]] = []
        cur: List[int] = []
        acc = 0
        acc_b = 0
        for i, c in enumerate(counts):
            cur.append(i)
            acc += c
            if byte_counts is not None and i < len(byte_counts):
                acc_b += byte_counts[i]
            if acc >= min_rows or (target_bytes > 0
                                   and byte_counts is not None
                                   and acc_b >= target_bytes):
                groups.append(cur)
                cur, acc, acc_b = [], 0, 0
        if cur:
            if groups:
                groups[-1].extend(cur)
            else:
                groups.append(cur)
        return groups

    def _fetch_metrics_cb(self, ctx: ExecContext):
        """Per-source read attribution: segment (pushed + consolidated
        locally), local (self-endpoint short-circuit, no socket), or
        remote (pulled over the wire)."""
        m = ctx.metrics_for(self.exec_id)
        counters = {
            kind: m.setdefault(name, Metric(name, Metric.MODERATE))
            for kind, name in (("segment", "shuffleSegmentBlocksRead"),
                               ("local", "shuffleLocalBlocksRead"),
                               ("remote", "shuffleRemoteBlocksRead"))}
        fetched = m.setdefault("shuffleBytesFetched",
                               Metric("shuffleBytesFetched",
                                      Metric.MODERATE, "B"))

        def on_block(kind: str, nbytes: int) -> None:
            counters[kind].add(1)
            if kind == "remote":
                fetched.add(nbytes)
        return on_block

    def _maybe_prefetch(self, ctx: ExecContext, factory, name: str):
        """Read-side pipelining (RapidsShuffleIterator fetch-ahead
        role): pull one reduce partition's block stream — fetch,
        checksum verify, deserialize — on a background producer so it
        overlaps the consumer's reduce compute. Gated on the conf AND
        the planner's ``_pipeline_ok`` safety tag; off = the plain
        synchronous generator. The producer for partition i starts only
        when the consumer requests partition i, so ``ctx.partition_id``
        advances strictly behind the consumer."""
        from .pipeline import pipeline_enabled, prefetch_batches
        if not pipeline_enabled(ctx, self):
            return factory()
        mgr = self.manager or shuffle_manager()
        # locality bypass may hand LIVE manager-owned batches through
        # this stream — don't re-wrap them as spillables (double
        # memory accounting; a queue discard would close a batch the
        # manager still serves to replays)
        stage = not (ctx.cluster is None
                     and getattr(mgr, "push_enabled", False)
                     and getattr(mgr, "local_bypass", False))
        return prefetch_batches(ctx, self, factory, name=name, stage=stage)

    def execute_partition_groups(self, ctx: ExecContext,
                                 groups: List[List[int]],
                                 map_mod: Optional[dict] = None):
        """One iterator per partition GROUP (a disjoint union of hash
        partitions keeps keys clustered, so group-wise consumers stay
        correct). ``map_mod``: {group_index: (s, S)} restricts that
        group's reads to map outputs with map_id % S == s — the skew
        split primitive (GpuCustomShuffleReaderExec's skewed partition
        specs slice a reduce partition by map ranges the same way).

        Cluster mode: ``groups`` must be identical on every worker (a
        pure function of the gathered global stats); this worker then
        streams only its contiguous block of GROUPS, fetching each
        partition from all peers."""
        mgr = self.manager or shuffle_manager()
        self._write(ctx)
        m = ctx.metrics_for(self.exec_id)
        m.setdefault("adaptiveCoalescedPartitions",
                     Metric("adaptiveCoalescedPartitions",
                            Metric.MODERATE)).add(
            max(mgr.num_partitions(self.shuffle_id) - len(groups), 0))
        if ctx.cluster is not None:
            from ..parallel.transport import fetch_all_partitions
            self._cluster_barrier(ctx)
            allowed = self._allowed_by_endpoint(ctx)
            peers = ctx.cluster.peers
            resolver = ctx.cluster.resolve_endpoint
            dsid = getattr(self, "_downstream_sid", None)
            on_block = self._fetch_metrics_cb(ctx)

            def remote_group(gi, g):
                mm = (map_mod or {}).get(gi)
                for reduce_id in g:
                    ctx.partition_id = reduce_id
                    yield from fetch_all_partitions(
                        peers, self.shuffle_id, reduce_id, map_mod=mm,
                        endpoint_resolver=resolver, allowed=allowed,
                        manager=mgr, metrics_cb=on_block,
                        replicas=self._replica_targets(ctx))
            for gi in ctx.cluster.assigned(len(groups), dsid):
                yield self._maybe_prefetch(
                    ctx, lambda _gi=gi: remote_group(_gi, groups[_gi]),
                    f"shuffle-g{gi}")
            return

        def read_group(gi, g):
            mm = (map_mod or {}).get(gi)
            for reduce_id in g:
                ctx.partition_id = reduce_id
                yield from mgr.read_partition(self.shuffle_id,
                                              reduce_id, map_mod=mm)
        try:
            for gi, g in enumerate(groups):
                yield self._maybe_prefetch(
                    ctx, lambda _gi=gi, _g=g: read_group(_gi, _g),
                    f"shuffle-g{gi}")
        finally:
            self._release(mgr)

    def execute_partitioned(self, ctx: ExecContext):
        """One iterator per reduce partition, in partition order.
        AQE coalescing is CONSUMER-driven (execute_partition_groups):
        a consumer with two partitioned inputs must apply the SAME
        grouping to both, so the exchange never groups on its own.

        Under a cluster context (parallel/cluster.py), the map side
        writes LOCAL blocks, a driver barrier makes every worker's maps
        visible, and only this worker's contiguous block of reduce
        partitions streams back — each partition fetched from ALL peers
        over the shuffle transport (RapidsShuffleIterator role)."""
        mgr = self.manager or shuffle_manager()
        self._write(ctx)
        n_parts = mgr.num_partitions(self.shuffle_id)
        if ctx.cluster is not None:
            from ..parallel.transport import fetch_all_partitions
            self._cluster_barrier(ctx)
            allowed = self._allowed_by_endpoint(ctx)
            peers = ctx.cluster.peers
            resolver = ctx.cluster.resolve_endpoint
            dsid = getattr(self, "_downstream_sid", None)
            on_block = self._fetch_metrics_cb(ctx)

            def remote_read(reduce_id):
                ctx.partition_id = reduce_id
                yield from fetch_all_partitions(
                    peers, self.shuffle_id, reduce_id,
                    endpoint_resolver=resolver, allowed=allowed,
                    manager=mgr, metrics_cb=on_block,
                    replicas=self._replica_targets(ctx))
            for reduce_id in ctx.cluster.assigned(n_parts, dsid):
                yield self._maybe_prefetch(
                    ctx, lambda rid=reduce_id: remote_read(rid),
                    f"shuffle-p{reduce_id}")
            # no unregister here: PEERS fetch this worker's blocks until
            # the whole job completes — the driver's post-job reset (or
            # failure-path reset) frees them (cluster.py _run_once)
            return

        def local_read(reduce_id):
            ctx.partition_id = reduce_id
            yield from mgr.read_partition(self.shuffle_id, reduce_id)
        try:
            for reduce_id in range(n_parts):
                yield self._maybe_prefetch(
                    ctx, lambda rid=reduce_id: local_read(rid),
                    f"shuffle-p{reduce_id}")
        finally:
            self._release(mgr)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        """Single-stream execution: write all map outputs, then stream
        partitions in order (partition boundaries preserved for
        downstream partition-wise operators)."""
        for part in self.execute_partitioned(ctx):
            yield from part

    def node_description(self) -> str:
        if self.sort_orders:
            keys = "range: " + ", ".join(repr(o.expr)
                                         for o in self.sort_orders)
        else:
            keys = ", ".join(repr(e) for e in self.key_exprs) or "round-robin"
        n = self.num_partitions or "conf"
        return f"ShuffleExchange[{keys}, parts={n}]"


class _InvertedKey:
    """Order-reversing wrapper for any comparable host sample value
    (bool/date/Decimal have no unary minus; numpy bools raise on it)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


class BroadcastExchangeExec(TpuExec):
    """Materialize the child into one batch replicated to every consumer
    (GpuBroadcastExchangeExec.scala:352 doExecuteBroadcast:467). In
    single-process execution this is a concat; under a mesh it lowers to
    an all_gather (parallel/shuffle.py all_gather_batch)."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._materialized: Optional[ColumnarBatch] = None

    def reset_for_rerun(self) -> None:
        super().reset_for_rerun()
        self._materialized = None

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    @property
    def output_partitioning(self):
        from ..plan.distribution import BroadcastPartitioning
        return BroadcastPartitioning()

    def materialize(self, ctx: ExecContext) -> Optional[ColumnarBatch]:
        if self._materialized is None:
            m = ctx.metrics_for(self.exec_id)
            bt = m.setdefault("broadcastTime",
                              Metric("broadcastTime", Metric.MODERATE, "ns"))
            from .pipeline import pipeline_enabled, prefetch_batches
            if pipeline_enabled(ctx, self):
                # drain the child through a background producer: decode
                # and upload of batch N+1 overlap the consumer's
                # accumulation of batch N
                stream = prefetch_batches(
                    ctx, self, lambda: self.children[0].execute(ctx),
                    name="broadcast")
            else:
                stream = self.children[0].execute(ctx)
            with NvtxTimer(bt, "broadcast.build"):
                batches = [b for b in stream
                           if int(b.num_rows) > 0]
                if not batches:
                    return None
                total = sum(int(b.num_rows) for b in batches)
                with ctx.semaphore:
                    self._materialized = (
                        batches[0] if len(batches) == 1
                        else K.concat_batches(batches,
                                              choose_capacity(total)))
        return self._materialized

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        out = self.materialize(ctx)
        if out is not None:
            yield out

    def node_description(self) -> str:
        return "BroadcastExchange"
