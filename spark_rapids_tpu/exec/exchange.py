"""Shuffle exchange exec: hash-partition the child stream through the
ShuffleManager.

Rebuild of GpuShuffleExchangeExecBase.scala (:167,
prepareBatchShuffleDependency :277) + GpuHashPartitioningBase (SURVEY
§2.7): each incoming batch is split on-device into the target
partitions (parallel/partition.py — the cudf Table.partition
equivalent), the per-partition slices become shuffle blocks via the
manager (device-cached or serialized host blocks), and the read side
streams one reduce partition's blocks back (GpuShuffleCoalesceExec is
the downstream CoalesceBatchesExec).

Under a device mesh the same partitioning feeds the all-to-all
collective instead (parallel/shuffle.py shuffle_exchange) — that path
compiles into the SPMD program and never touches this manager.
"""

from __future__ import annotations

import itertools
import threading
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity)
from ..conf import SHUFFLE_PARTITIONS
from ..expr.core import Expression
from ..parallel.partition import (PartitionedBatch, hash_partition_ids,
                                  partition_batch, round_robin_partition_ids,
                                  string_from_padded)
from ..parallel.shuffle_manager import ShuffleManager, shuffle_manager
from .base import ExecContext, Metric, Schema, TpuExec

_SHUFFLE_IDS = itertools.count(1)
_IDS_LOCK = threading.Lock()


def next_shuffle_id() -> int:
    with _IDS_LOCK:
        return next(_SHUFFLE_IDS)


def partition_slice(pb: PartitionedBatch, i: int) -> ColumnarBatch:
    """Extract partition i of a PartitionedBatch as a standalone batch."""
    S = pb.slot_capacity
    cols = []
    for spec, dtype in zip(pb.columns, pb.dtypes):
        if dtype == dt.STRING:
            padded, lens, valid = spec
            cols.append(string_from_padded(padded[i], lens[i], valid[i]))
        else:
            data, valid = spec
            cols.append(ColumnVector(data[i], valid[i], dtype))
    return ColumnarBatch(cols, pb.names, pb.counts[i])


class ShuffleExchangeExec(TpuExec):
    """Hash (or round-robin) repartitioning through the ShuffleManager."""

    def __init__(self, child: TpuExec,
                 key_exprs: Sequence[Expression],
                 num_partitions: Optional[int] = None,
                 manager: Optional[ShuffleManager] = None):
        super().__init__(child)
        self.key_exprs = list(key_exprs)
        self.num_partitions = num_partitions
        self.manager = manager
        self.shuffle_id = next_shuffle_id()
        self._jit_cache = {}

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def _partition_fn(self, num_parts: int):
        if num_parts not in self._jit_cache:
            def run(batch: ColumnarBatch) -> PartitionedBatch:
                if self.key_exprs:
                    keys = [e.eval(batch) for e in self.key_exprs]
                    pids = hash_partition_ids(keys, num_parts)
                else:
                    pids = round_robin_partition_ids(batch.capacity,
                                                     num_parts)
                return partition_batch(batch, pids, num_parts)
            self._jit_cache[num_parts] = jax.jit(run)
        return self._jit_cache[num_parts]

    def write(self, ctx: ExecContext) -> int:
        """Map phase: drain the child, write all blocks. Returns the
        number of map tasks (batches) written."""
        mgr = self.manager or shuffle_manager()
        n_parts = self.num_partitions or ctx.conf.get(SHUFFLE_PARTITIONS)
        mgr.register_shuffle(self.shuffle_id, n_parts)
        m = ctx.metrics_for(self.exec_id)
        part_time = m.setdefault("partitionTime",
                                 Metric("partitionTime", Metric.MODERATE,
                                        "ns"))
        map_id = 0
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            import time
            t0 = time.perf_counter_ns()
            with ctx.semaphore:
                pb = self._partition_fn(n_parts)(batch)
                parts = [partition_slice(pb, i) for i in range(n_parts)]
            part_time.add(time.perf_counter_ns() - t0)
            mgr.write_map_output(self.shuffle_id, map_id, parts)
            map_id += 1
        return map_id

    def read_partition(self, ctx: ExecContext,
                       reduce_id: int) -> Iterator[ColumnarBatch]:
        mgr = self.manager or shuffle_manager()
        yield from mgr.read_partition(self.shuffle_id, reduce_id)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        """Single-process execution: write all map outputs, then stream
        partitions in order (partition boundaries preserved for
        downstream partition-wise operators)."""
        mgr = self.manager or shuffle_manager()
        self.write(ctx)
        n_parts = mgr.num_partitions(self.shuffle_id)
        try:
            for reduce_id in range(n_parts):
                yield from self.read_partition(ctx, reduce_id)
        finally:
            mgr.unregister_shuffle(self.shuffle_id)

    def node_description(self) -> str:
        keys = ", ".join(repr(e) for e in self.key_exprs) or "round-robin"
        return f"ShuffleExchange[{keys}]"
