"""Asynchronous pipelined execution: overlap I/O with device compute.

The engine is a pull-based iterator chain, so every scan decode,
shuffle fetch, checksum verify, and host->device transfer stalls the
consumer (and therefore the TPU) for its full duration. The reference
plugin hides these latencies with a multithreaded reader and the
RapidsShuffleIterator's fetch-ahead window; this module is the common
primitive behind both: ``PrefetchIterator`` runs a producer iterator on
a background thread behind a bounded queue with byte-budget
backpressure, and ``prefetch_batches`` specializes it for
ColumnarBatch streams — every in-flight batch registers with the spill
catalog as ACTIVE_ON_DECK so memory pressure can reclaim it, and with
``srt.exec.pipeline.depth`` >= 2 the producer's upload of batch N+1
overlaps the consumer's compute on batch N (double buffering; JAX's
async dispatch makes the device transfer itself non-blocking on the
producer).

Insertion points (see plan/overrides.py ``_insert_pipeline``):
  * ``PrefetchExec`` wraps ``FileSourceScanExec`` output — decode
    overlaps compute,
  * the read side of ``ShuffleExchangeExec`` wraps each reduce
    partition's block stream — fetch/verify/deserialize overlap reduce
    compute,
  * ``BroadcastExchangeExec.materialize`` drains its child through a
    prefetcher while concat-staging runs on the consumer.

Correctness contract:
  * items arrive in producer order (single producer, FIFO deque);
  * a producer-side exception is re-raised on the CONSUMING thread —
    the original exception object, after all items produced before it
    have been drained — so ``FetchFailed`` / ``DataCorruption`` /
    injected faults surface at the same plan node and with the same
    type as in synchronous mode, and stage-retry / whole-job-retry
    isinstance checks keep firing;
  * the producer thread inherits the query conf (``set_active_conf``)
    and, when a fault plan is armed, the wrapping operator's fault
    scope, so ``~op=`` site matches behave as if the work ran inline;
  * ``close()`` is idempotent, joins the producer, and discards (via
    ``on_discard``) anything still queued, so an abandoned consumer
    (LocalLimit, error unwind) leaks neither threads nor spill-catalog
    registrations.

The SelfTimer disjointness invariant (obs: exclusive op-times on one
thread never overlap) holds because each thread pulls through its own
timer stack (ExecContext.timer_stack is thread-local): producer-side
operators attribute their op-time on the producer's stack, the
``PrefetchExec`` / exchange frames attribute only wait time on the
consumer's. tools/profile_report.py folds the two by treating
sum(op-time) > wall as pipeline overlap, not double-charging.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Callable, Iterable, Iterator, Optional

from ..conf import (PIPELINE_DEPTH, PIPELINE_ENABLED, PIPELINE_MAX_BYTES,
                    SrtConf, set_active_conf)
from .base import ExecContext, Metric, Schema, TpuExec

__all__ = ["PrefetchIterator", "PrefetchExec", "prefetch_batches",
           "pipeline_enabled", "prefetch_buffer_bytes",
           "prefetch_thread_leaks", "close_live_iterators"]

# Live iterators, for the resource sampler's prefetch-occupancy gauge.
# Weak so an abandoned iterator never outlives its consumer.
_LIVE: "weakref.WeakSet[PrefetchIterator]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()

#: producer threads that outlived close()'s join timeout — a stuck
#: source (hung socket, wedged decode). Chaos runs and the leak gate
#: fail loudly on a nonzero count instead of silently shipping a
#: daemon thread per wedged query.
_THREAD_LEAKS = [0]


def prefetch_thread_leaks() -> int:
    return _THREAD_LEAKS[0]


def prefetch_buffer_bytes() -> int:
    """Total bytes queued across all live prefetchers in this process
    (obs/resource.py sampler probe; racy reads are fine for a gauge)."""
    with _LIVE_LOCK:
        its = list(_LIVE)
    return sum(it._bytes for it in its)


def close_live_iterators(query=None, join_timeout: float = 10.0) -> int:
    """Close every live PrefetchIterator owned by ``query`` (a
    QueryContext, or a query-id string; None closes all).

    The serving tier's per-session teardown calls this after a client
    disconnect: a consumer abandoned mid-stream never reaches the
    iterator's normal close, and without this the producer thread
    would count as a leak once its queue backpressure wedged. Returns
    the number of iterators closed."""
    qid = getattr(query, "query_id", query)
    with _LIVE_LOCK:
        its = list(_LIVE)
    closed = 0
    for it in its:
        owner = it._query
        if qid is not None and (owner is None or owner.query_id != qid):
            continue
        it.close(join_timeout=join_timeout)
        closed += 1
    return closed


class PrefetchIterator:
    """Run ``source_factory()`` on a background thread; consume here.

    The factory (not a live iterator) crosses the thread boundary so
    the source generator is CREATED on the producer thread — generator
    bodies that capture thread-local state at first-next (conf, fault
    scopes, task context) see the producer's, which this class sets up
    to mirror the consumer's.

    Backpressure: the producer blocks while ``depth`` items are queued
    or queued bytes would exceed ``max_bytes``; an oversized single
    item is admitted only into an EMPTY queue (progress guarantee, the
    ByteBudget convention). ``nbytes`` sizes items; None = count-only.
    """

    def __init__(self, source_factory: Callable[[], Iterable],
                 depth: int = 2,
                 max_bytes: int = 0,
                 nbytes: Optional[Callable] = None,
                 conf: Optional[SrtConf] = None,
                 fault_tag: str = "",
                 on_discard: Optional[Callable] = None,
                 name: str = "prefetch",
                 wait_metric: Optional[Metric] = None,
                 depth_peak_metric: Optional[Metric] = None,
                 bytes_peak_metric: Optional[Metric] = None,
                 tracer=None,
                 parent_span_id: Optional[int] = None,
                 query=None,
                 leak_metric: Optional[Metric] = None):
        self._factory = source_factory
        #: cancellation token (robustness/admission.py QueryContext):
        #: the producer observes it between items and while blocked on
        #: backpressure, the consumer while blocked on an empty queue —
        #: a cancelled query drains and joins instead of wedging
        self._query = query
        self._leak_metric = leak_metric
        self._depth = max(int(depth), 1)
        self._max_bytes = max(int(max_bytes), 0)
        self._nbytes = nbytes
        self._conf = conf
        self._fault_tag = fault_tag
        self._on_discard = on_discard
        self._wait_metric = wait_metric
        self._depth_peak_metric = depth_peak_metric
        self._bytes_peak_metric = bytes_peak_metric
        self._name = name
        # span parenting across the thread boundary: the producer
        # thread's tracer stack starts empty, so without an explicit
        # parent captured at construction (on the CONSUMER thread,
        # where the enclosing operator span is live) every
        # producer-side span would orphan
        self._tracer = tracer
        self._parent_span_id = parent_span_id
        self._cv = threading.Condition()
        self._buf: deque = deque()  # (item, nbytes)
        self._bytes = 0
        self._depth_peak = 0
        self._bytes_peak = 0
        self._done = False
        self._stopped = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"srt-prefetch-{name}", daemon=True)
        with _LIVE_LOCK:
            _LIVE.add(self)
        self._thread.start()

    # --- producer side ---------------------------------------------------
    def _run(self) -> None:
        from ..robustness import faults
        from ..robustness.admission import set_current_query
        if self._conf is not None:
            set_active_conf(self._conf)
        # producer thread inherits the query identity the same way it
        # inherits the conf: spillable registrations it creates carry
        # the owning query's budget-slice tag, and retry/backoff sleeps
        # deep in the source (transport) become cancel-aware
        set_current_query(self._query)
        scope = (faults.op_scope(self._fault_tag)
                 if self._fault_tag and faults.armed() else None)
        # scoped producer span: pushed onto THIS thread's tracer stack,
        # so operator spans opened by the source (SelfTimer falls back
        # to tracer.current_id()) parent here instead of orphaning
        span_scope = (self._tracer.span(f"prefetch-{self._name}",
                                        kind="producer",
                                        parent=self._parent_span_id)
                      if self._tracer is not None else None)
        src = None
        try:
            if span_scope is not None:
                span_scope.__enter__()
            if scope is not None:
                scope.__enter__()
            try:
                src = iter(self._factory())
                for item in src:
                    if self._query is not None and (
                            self._query.is_cancelled()
                            or self._query.expired()):
                        # observe-and-drain: no error relay — the
                        # consumer raises the typed teardown itself
                        self._discard(item)
                        break
                    n = int(self._nbytes(item)) if self._nbytes else 0
                    if not self._admit(item, n):
                        break
            finally:
                if scope is not None:
                    scope.__exit__(None, None, None)
                if span_scope is not None:
                    span_scope.__exit__(None, None, None)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            with self._cv:
                self._error = e
                self._cv.notify_all()
        finally:
            # tear the source down on ITS OWN thread (generator finally
            # blocks may release locks/sockets owned by this thread)
            if src is not None and hasattr(src, "close"):
                try:
                    src.close()
                except Exception:
                    pass
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def _admit(self, item, n: int) -> bool:
        """Queue one item, honoring depth + byte backpressure. False =
        stopped: the item was discarded and the producer should quit."""
        with self._cv:
            while not self._stopped and self._buf and (
                    len(self._buf) >= self._depth
                    or (self._max_bytes
                        and self._bytes + n > self._max_bytes)):
                if self._query is not None:
                    if self._query.is_cancelled() or \
                            self._query.expired():
                        self._discard(item)
                        return False
                    # bounded wait so a cancel with a wedged consumer
                    # still unblocks the producer
                    self._cv.wait(timeout=0.25)
                else:
                    self._cv.wait()
            if self._stopped:
                self._discard(item)
                return False
            self._buf.append((item, n))
            self._bytes += n
            if len(self._buf) > self._depth_peak:
                self._depth_peak = len(self._buf)
            if self._bytes > self._bytes_peak:
                self._bytes_peak = self._bytes
            self._cv.notify_all()
            return True

    def _discard(self, item) -> None:
        if self._on_discard is not None:
            try:
                self._on_discard(item)
            except Exception:
                pass

    # --- consumer side ---------------------------------------------------
    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        with self._cv:
            waited = 0
            while True:
                if self._buf:
                    item, n = self._buf.popleft()
                    self._bytes -= n
                    self._cv.notify_all()
                    if waited and self._wait_metric is not None:
                        self._wait_metric.add(waited)
                    return item
                # buffered items drain before an error surfaces: the
                # consumer sees exactly the prefix the producer emitted
                # before failing, same as synchronous execution
                if self._error is not None:
                    err = self._error
                    self._stopped = True
                    self._cv.notify_all()
                    self._flush_peaks()
                    raise err
                if self._done:
                    # a producer that DRAINED on cancel/deadline looks
                    # exactly like clean end-of-stream — re-check the
                    # token before reporting exhaustion, or the query
                    # would return a silently truncated prefix
                    if self._query is not None:
                        self._query.check()
                    self._flush_peaks()
                    raise StopIteration
                t0 = time.perf_counter_ns()
                if self._query is not None:
                    # typed teardown even when the producer is wedged
                    # in a hung source: poll the token while waiting
                    self._query.check()
                    self._cv.wait(timeout=0.25)
                else:
                    self._cv.wait()
                waited += time.perf_counter_ns() - t0

    def _flush_peaks(self) -> None:
        # peaks fold across partitions sharing one metrics dict: keep
        # the query-wide max (single consuming thread, no set() race)
        if self._depth_peak_metric is not None:
            self._depth_peak_metric.set(
                max(self._depth_peak_metric.value, self._depth_peak))
        if self._bytes_peak_metric is not None:
            self._bytes_peak_metric.set(
                max(self._bytes_peak_metric.value, self._bytes_peak))

    def close(self, join_timeout: float = 30.0) -> None:
        """Stop the producer, join it, and discard queued items.

        A producer that outlives the join timeout is wedged inside its
        source (hung socket, stuck decode) — it leaks as a daemon
        thread. That must fail loudly, not silently: a warning event,
        the process-wide ``prefetch_thread_leaks`` counter, and the
        node's ``prefetchThreadLeaks`` metric all record it so chaos
        runs and the serving tier's health checks trip."""
        if self._closed:
            return
        self._closed = True
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            _THREAD_LEAKS[0] += 1
            if self._leak_metric is not None:
                self._leak_metric.add(1)
            from ..obs import events as _events
            _events.emit("PrefetchThreadLeak",
                         thread=self._thread.name,
                         join_timeout_s=join_timeout,
                         queued=len(self._buf))
            import logging
            logging.getLogger("spark_rapids_tpu.exec").warning(
                "prefetch producer %s leaked: still alive %.0fs after "
                "close()", self._thread.name, join_timeout)
        with self._cv:
            while self._buf:
                item, _ = self._buf.popleft()
                self._discard(item)
            self._bytes = 0
            self._flush_peaks()

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def pipeline_enabled(ctx: ExecContext, node=None) -> bool:
    """Runtime gate: the conf switch AND (for exchanges) the planner's
    safety tag. The planner withholds ``_pipeline_ok`` from plans with
    partition-context expressions (spark_partition_id() et al) whose
    values would race against a producer advancing ``ctx.partition_id``.
    """
    if not ctx.conf.get(PIPELINE_ENABLED):
        return False
    if node is not None and not getattr(node, "_pipeline_ok", False):
        return False
    return True


class _Unstaged:
    """Queue-slot shim matching SpillableBatch's get/close/nbytes
    surface WITHOUT taking a spill-catalog registration or ownership.
    Used for zero-copy shuffle-bypass streams: those batches are live
    objects the shuffle manager still owns (already spill-registered in
    its device catalog), so re-wrapping would double-account the bytes
    and a queue discard would close a batch other readers may replay.
    """

    __slots__ = ("_batch", "nbytes")

    def __init__(self, batch):
        self._batch = batch
        self.nbytes = int(getattr(batch, "nbytes", 0))

    def get(self):
        return self._batch

    def close(self) -> None:
        pass


def prefetch_batches(ctx: ExecContext, node: TpuExec,
                     source_factory: Callable[[], Iterable],
                     name: str = "", stage: bool = True) -> Iterator:
    """Pull a ColumnarBatch stream through a background prefetcher.

    Each produced batch registers with the spill catalog as an
    ACTIVE_ON_DECK SpillableBatch while it waits in the queue (memory
    pressure can push queued batches to host/disk instead of OOMing);
    the consumer re-materializes (usually a no-op: still on device) and
    releases the registration before yielding. Metrics land on
    ``node``: prefetchWaitTime (consumer blocked on an empty queue),
    prefetchQueueDepthPeak, prefetchBytesPeak.

    ``stage=False`` skips the SpillableBatch wrap — for streams that
    may hand through ALREADY-owned live batches (the shuffle locality
    bypass), where a second registration would double-count memory and
    discard-on-close would free somebody else's batch.
    """
    from ..memory.spill import SpillableBatch, SpillPriority
    m = ctx.metrics_for(node.exec_id)
    wait = m.setdefault("prefetchWaitTime",
                        Metric("prefetchWaitTime", Metric.MODERATE, "ns"))
    dpk = m.setdefault("prefetchQueueDepthPeak",
                       Metric("prefetchQueueDepthPeak", Metric.DEBUG))
    bpk = m.setdefault("prefetchBytesPeak",
                       Metric("prefetchBytesPeak", Metric.DEBUG))
    leaks = m.setdefault("prefetchThreadLeaks",
                         Metric("prefetchThreadLeaks", Metric.ESSENTIAL))

    def staged() -> Iterator:
        for batch in source_factory():
            yield SpillableBatch(batch, SpillPriority.ACTIVE_ON_DECK) \
                if stage else _Unstaged(batch)

    # capture the enclosing operator span NOW, on the consumer thread:
    # the nearest timed frame with a live span, else the thread's open
    # scope (query/task span) — the producer thread can't see either
    parent_span_id = None
    if ctx.tracer is not None:
        for frame in reversed(ctx.timer_stack):
            sp = getattr(frame, "_span", None)
            if sp is not None:
                parent_span_id = sp.span_id
                break
        if parent_span_id is None:
            parent_span_id = ctx.tracer.current_id()

    pf = PrefetchIterator(
        staged,
        depth=ctx.conf.get(PIPELINE_DEPTH),
        max_bytes=ctx.conf.get(PIPELINE_MAX_BYTES),
        nbytes=lambda sb: sb.nbytes,
        conf=ctx.conf,
        fault_tag=node.exec_id,
        on_discard=lambda sb: sb.close(),
        name=name or node.exec_id,
        wait_metric=wait,
        depth_peak_metric=dpk,
        bytes_peak_metric=bpk,
        tracer=ctx.tracer,
        parent_span_id=parent_span_id,
        query=ctx.query,
        leak_metric=leaks)

    def consume() -> Iterator:
        try:
            for sb in pf:
                try:
                    batch = sb.get()
                finally:
                    sb.close()
                yield batch
        finally:
            pf.close()
    return consume()


class PrefetchExec(TpuExec):
    """Transparent pipelining node: runs its child on a background
    thread (prefetch_batches) and re-yields. Inserted by the planner
    above blocking sources (today: FileSourceScanExec); schema and
    partitioning pass through. When ``srt.exec.pipeline.enabled`` is
    off at run time (a cached plan re-run under a different conf) it
    degrades to a synchronous pass-through."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._pipeline_ok = True

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    @property
    def output_partitioning(self):
        return self.children[0].output_partitioning

    def do_execute(self, ctx: ExecContext) -> Iterator:
        child = self.children[0]
        if not pipeline_enabled(ctx, self):
            yield from child.execute(ctx)
            return
        yield from prefetch_batches(ctx, self, lambda: child.execute(ctx))

    def node_description(self) -> str:
        return "Prefetch"
