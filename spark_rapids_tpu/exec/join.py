"""Join execs: shuffled/broadcast hash joins with overflow retry.

Rebuild of the reference's join stack (SURVEY §2.4):
GpuShuffledHashJoinExec.scala:90, GpuHashJoin.scala:104
(HashJoinIterator:440, gather-map based), GpuBroadcastHashJoinExecBase,
GpuSubPartitionHashJoin (oversized build sides). The kernel
(ops/kernels.py join_gather_maps) reports the true required output size;
when it exceeds the static output capacity the exec re-runs with the
reported size's capacity bucket (so the second attempt always fits) —
the TPU equivalent of the reference's SplitAndRetryOOM join contract.
_MAX_GROWTH_STEPS is a safety net against a kernel under-reporting, not
a working-set bound. Build sides above srt.sql.join.subPartitionRows
are hash-split into sub-partitions and joined pair-wise
(GpuSubPartitionHashJoin.scala): both sides are bucketed by the SAME
key hash so matching rows co-locate, each sub-build is spillable while
idle, and every probe row lands in exactly one bucket (outer-join
preservation holds per bucket).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnarBatch, choose_capacity
from ..expr.core import Expression
from ..jit_registry import shared_fn_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, Schema, TpuExec

# Join types (Catalyst names)
INNER = "inner"
LEFT_OUTER = "left_outer"
RIGHT_OUTER = "right_outer"
FULL_OUTER = "full_outer"
LEFT_SEMI = "left_semi"
LEFT_ANTI = "left_anti"
CROSS = "cross"

# Output capacity growth is bounded: past this many doublings the probe
# batch gets split instead (GpuSubPartitionHashJoin analogue).
_MAX_GROWTH_STEPS = 4


# --- module-level jit builders (shared process-wide via jit_registry:
# every join over the same keys/type/capacity reuses one traced fn) ---

def _join_run_builder(join_type, probe_keys, build_keys, out_capacity):
    def run(probe, build):
        pk = [e.eval(probe) for e in probe_keys]
        bk = [e.eval(build) for e in build_keys]
        if join_type in (LEFT_SEMI, LEFT_ANTI):
            out, total = K.semi_anti_join(
                probe, bk, pk, build.live_mask(),
                anti=(join_type == LEFT_ANTI),
                scratch_capacity=out_capacity)
        elif join_type == INNER:
            out, total = K.inner_join(probe, build, pk, bk, out_capacity)
        else:  # LEFT_OUTER / RIGHT_OUTER: probe is preserved side
            out, total = K.left_join(probe, build, pk, bk, out_capacity)
        return out, total
    return run


def _bucket_split_builder(exprs, num_parts):
    def run(batch, p):
        return K.bucket_compact(
            batch, [e.eval(batch) for e in exprs], num_parts, p)
    return run


def _chunk_slice_builder(length, cap):
    def run(b, s):
        return K.slice_batch(b, s, length, cap)
    return run


def _bloom_build_builder(exprs, num_bits):
    from ..ops import bloom as B

    def mk(b):
        return B.build_bloom([e.eval(b) for e in exprs],
                             b.live_mask(), num_bits)
    return mk


def _bloom_probe_builder(exprs):
    from ..columnar.vector import ColumnVector
    from ..ops import bloom as B

    def probe_fn(bits_, b):
        keep = B.might_contain(bits_, [e.eval(b) for e in exprs])
        cond = ColumnVector(keep, jnp.ones_like(keep), dt.BOOL)
        return K.filter_batch(b, cond)
    return probe_fn


class _HashJoinBase(TpuExec):
    """Shared machinery: build-side materialization + per-probe-batch
    gather-map join with capacity retry."""

    #: armed by exec/fused.py FusedHashJoinExec (plan/overrides.py
    #: fusion pass): when set, the per-pair join program is the fused
    #: join+suffix program; ALL orchestration around it (broadcast
    #: demotion, skew splits, sub-partitioning, bloom, DPP, growth
    #: retries) stays in this class unchanged
    _fusion = None

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = INNER,
                 build_side: str = "right",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.build_side = build_side
        self.condition = condition
        if join_type in (LEFT_SEMI, LEFT_ANTI):
            if build_side != "right":
                raise ValueError("semi/anti joins build the right side")
        elif join_type == LEFT_OUTER:
            if build_side != "right":
                raise ValueError(
                    "left outer requires build=right (probe preserves left)")
        elif join_type == RIGHT_OUTER:
            if build_side != "left":
                raise ValueError(
                    "right outer requires build=left (probe preserves right)")
        elif join_type not in (INNER,):
            raise NotImplementedError(
                f"join type {join_type!r} not supported on TPU yet "
                "(planner must fall back)")
        self._jit_cache = {}

    @property
    def output_schema(self) -> Schema:
        left_s = self.children[0].output_schema
        right_s = self.children[1].output_schema
        if self.join_type in (LEFT_SEMI, LEFT_ANTI):
            return left_s
        return left_s + right_s

    # --- build side ---
    def _concat_build(self, ctx: ExecContext,
                      stream) -> Optional[ColumnarBatch]:
        batches = [b for b in stream if int(b.num_rows) > 0]
        if not batches:
            return None
        total = sum(int(b.num_rows) for b in batches)
        cap = choose_capacity(total)
        with ctx.semaphore:
            return (batches[0] if len(batches) == 1
                    else K.concat_batches(batches, cap))

    def _key_cols(self, batch: ColumnarBatch, exprs):
        return [e.eval(batch) for e in exprs]

    def _eager_keys(self) -> bool:
        from ..expr.misc import contains_eager
        return contains_eager(list(self._probe_key_exprs)
                              + list(self._build_key_exprs))

    def _join_fn(self, out_capacity: int):
        """jit per output capacity; cached per instance, shared
        process-wide (registry) across joins with equal keys/type.
        Eager keys (ANSI guards) evaluate un-jitted."""
        key = out_capacity
        if key not in self._jit_cache:
            if self._eager_keys():
                self._jit_cache[key] = _join_run_builder(
                    self.join_type, self._probe_key_exprs,
                    self._build_key_exprs, out_capacity)
            else:
                self._jit_cache[key] = shared_fn_jit(
                    _join_run_builder, self.join_type,
                    self._probe_key_exprs, self._build_key_exprs,
                    out_capacity)
        return self._jit_cache[key]

    @property
    def _probe_key_exprs(self):
        return self.left_keys if self.build_side == "right" \
            else self.right_keys

    @property
    def _build_key_exprs(self):
        return self.right_keys if self.build_side == "right" \
            else self.left_keys

    def _probe_stream(self, ctx: ExecContext):
        probe_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        return probe_child.execute(ctx)

    def _build_stream(self, ctx: ExecContext):
        build_child = self.children[1] if self.build_side == "right" \
            else self.children[0]
        return build_child.execute(ctx)

    def _reorder_columns(self, out: ColumnarBatch) -> ColumnarBatch:
        """Kernel output is probe-then-build; plan output is left-then-
        right."""
        if self.build_side == "right" or self.join_type in (LEFT_SEMI,
                                                            LEFT_ANTI):
            return out
        n_right = len(self.children[1].output_schema)
        cols = out.columns[n_right:] + out.columns[:n_right]
        names = out.names[n_right:] + out.names[:n_right]
        return ColumnarBatch(cols, names, out.num_rows)

    def _empty_result(self, probe_stream, ctx) -> Iterator[ColumnarBatch]:
        """Build side empty: inner/semi produce nothing; left-outer and
        anti pass probe rows with null build columns. An armed fusion
        runs its absorbed suffix over the passthrough batches (the
        unfused plan's filter/project/agg would see them too)."""
        stream = self._empty_result_core(probe_stream, ctx)
        if self._fusion is not None and \
                self._fusion._exec_state is not None:
            stream = self._fusion.suffix_fallback(ctx, stream)
        yield from stream

    def _empty_result_core(self, probe_stream, ctx
                           ) -> Iterator[ColumnarBatch]:
        jt = self.join_type
        if jt in (INNER, LEFT_SEMI):
            return
        build_schema = (self.children[1].output_schema
                        if self.build_side == "right"
                        else self.children[0].output_schema)
        for probe in probe_stream:
            if jt == LEFT_ANTI:
                yield probe
                continue
            # left outer with empty build: null-extend
            cap = probe.capacity
            from ..columnar.vector import ColumnVector, StringColumn
            null_cols = []
            for name, t in build_schema:
                if t == dt.STRING:
                    null_cols.append(StringColumn(
                        jnp.zeros(cap + 1, jnp.int32),
                        jnp.zeros(128, jnp.uint8),
                        jnp.zeros(cap, jnp.bool_)))
                else:
                    phys = t.physical
                    null_cols.append(ColumnVector(
                        jnp.zeros(cap, phys), jnp.zeros(cap, jnp.bool_), t))
            out = ColumnarBatch(
                list(probe.columns) + null_cols,
                probe.names + [n for n, _ in build_schema], probe.num_rows)
            yield self._reorder_columns(out)

    def _join_pair(self, ctx: ExecContext, probe: ColumnarBatch,
                   build: ColumnarBatch, retries: Metric
                   ) -> ColumnarBatch:
        """One probe batch against one build batch, with capacity
        growth retry."""
        from ..conf import JOIN_GROWTH_STEPS
        n_probe = int(probe.num_rows)
        max_steps = ctx.conf.get(JOIN_GROWTH_STEPS)
        # initial guess: every probe row matches ~1 build row
        out_cap = choose_capacity(max(n_probe, 16))
        for step in range(max_steps + 1):
            with ctx.semaphore:
                out, total = self._join_fn(out_cap)(probe, build)
            total = int(total)
            if total <= out_cap:
                return self._reorder_columns(out)
            retries.add(1)
            out_cap = choose_capacity(total)
        raise RuntimeError(
            f"join expansion {total} exceeded capacity after "
            f"{max_steps} growth steps")

    def _join_batches(self, ctx: ExecContext, probe: ColumnarBatch,
                      build: ColumnarBatch, retries: Metric
                      ) -> Iterator[ColumnarBatch]:
        """One probe batch against one build batch. Unfused: a single
        capacity-retried gather-map join. When a FusedHashJoinExec
        armed this node, the pair runs through the fused join+suffix
        program with per-batch split-and-retry instead (possibly
        several output batches, or none when an absorbed filter drops
        everything)."""
        if self._fusion is not None and \
                self._fusion._exec_state is not None:
            yield from self._fusion.fused_pairs(ctx, probe, build,
                                                retries)
            return
        yield self._join_pair(ctx, probe, build, retries)

    def _split_fn(self, num_parts: int, side: str):
        """jit'd key-hash bucket filter (ops/kernels.py bucket_compact):
        (batch, p) -> rows of bucket p, same capacity."""
        key = ("split", num_parts, side)
        if key not in self._jit_cache:
            exprs = self._probe_key_exprs if side == "probe" \
                else self._build_key_exprs
            from ..expr.misc import contains_eager
            if contains_eager(exprs):
                self._jit_cache[key] = _bucket_split_builder(exprs,
                                                             num_parts)
            else:
                self._jit_cache[key] = shared_fn_jit(
                    _bucket_split_builder, exprs, num_parts)
        return self._jit_cache[key]

    def _repack(self, ctx: ExecContext, batch: ColumnarBatch
                ) -> ColumnarBatch:
        """Shrink a compacted batch to its tight capacity bucket —
        compact() preserves the source capacity, so without this the
        sub-partition machinery would multiply, not bound, memory."""
        n = int(batch.num_rows)
        cap = choose_capacity(max(n, 8))
        if cap >= batch.capacity:
            return batch
        with ctx.semaphore:
            return K.repack_to(batch, cap)

    def _sub_partition_join(self, ctx: ExecContext, probe_stream,
                            build_holder: List[ColumnarBatch], threshold: int
                            ) -> Iterator[ColumnarBatch]:
        """GpuSubPartitionHashJoin: bucket BOTH sides by the same key
        hash, then join bucket-pairs so each sub-build is materialized
        once. ``build_holder`` transfers ownership of the concatenated
        build (the caller's reference is dropped so it can be freed as
        soon as bucketing finishes). An inner-join bucket still over
        budget (single hot key defeats key hashing) is row-chunked;
        other join types record the skew and run the bucket whole."""
        from ..memory.spill import SpillableBatch, SpillPriority
        m = ctx.metrics_for(self.exec_id)
        retries = m.setdefault("joinOverflowRetries",
                               Metric("joinOverflowRetries", Metric.DEBUG))
        parts_m = m.setdefault("joinSubPartitions",
                               Metric("joinSubPartitions", Metric.DEBUG))
        skew_m = m.setdefault("joinSubPartitionSkew",
                              Metric("joinSubPartitionSkew", Metric.DEBUG))
        build = build_holder.pop()
        P = max(2, -(-int(build.num_rows) // max(threshold, 1)))
        parts_m.add(P)
        sub_builds: List[Optional[SpillableBatch]] = []
        split_b = self._split_fn(P, "build")
        for p in range(P):
            with ctx.semaphore:
                sub = split_b(build, jnp.int32(p))
            if int(sub.num_rows) == 0:
                sub_builds.append(None)
                continue
            sub = self._repack(ctx, sub)
            from ..memory.retry import with_retry_no_split
            sub_builds.append(with_retry_no_split(
                lambda s=sub: SpillableBatch(
                    s, SpillPriority.ACTIVE_ON_DECK)))
        del build, sub

        # bucket the whole probe stream first, so each sub-build is
        # unspilled exactly once (not once per probe batch)
        split_p = self._split_fn(P, "probe")
        probe_buckets: List[List[SpillableBatch]] = [[] for _ in range(P)]
        try:
            for probe in probe_stream:
                if int(probe.num_rows) == 0:
                    continue
                for p in range(P):
                    with ctx.semaphore:
                        sub = split_p(probe, jnp.int32(p))
                    if int(sub.num_rows) == 0:
                        continue
                    sub = self._repack(ctx, sub)
                    from ..memory.retry import with_retry_no_split
                    probe_buckets[p].append(with_retry_no_split(
                        lambda s=sub: SpillableBatch(
                            s, SpillPriority.ACTIVE_ON_DECK)))
            for p in range(P):
                if not probe_buckets[p]:
                    continue
                sb = sub_builds[p]
                if sb is None:
                    for psb in probe_buckets[p]:
                        yield from self._empty_result(
                            iter([psb.get()]), ctx)
                        psb.close()
                    probe_buckets[p] = []
                    continue
                from ..memory.retry import with_retry_no_split
                bucket_build = with_retry_no_split(sb.get)
                n_build = int(bucket_build.num_rows)
                if n_build > threshold:
                    skew_m.add(1)
                if n_build > threshold and self.join_type == INNER:
                    # hot-key bucket: arbitrary row chunks are correct
                    # for inner joins (matches are a disjoint union)
                    chunks = -(-n_build // threshold)
                    chunk_cap = choose_capacity(threshold)
                    ck = ("chunk", bucket_build.capacity, chunk_cap)
                    if ck not in self._jit_cache:
                        self._jit_cache[ck] = shared_fn_jit(
                            _chunk_slice_builder, threshold, chunk_cap)
                    for ci in range(chunks):
                        with ctx.semaphore:
                            chunk = self._jit_cache[ck](
                                bucket_build, jnp.int32(ci * threshold))
                        for psb in probe_buckets[p]:
                            yield from self._join_batches(
                                ctx, psb.get(), chunk, retries)
                else:
                    for psb in probe_buckets[p]:
                        yield from self._join_batches(
                            ctx, psb.get(), bucket_build, retries)
                for psb in probe_buckets[p]:
                    psb.close()
                probe_buckets[p] = []
                sb.close()
                sub_builds[p] = None
        finally:
            for sb in sub_builds:
                if sb is not None:
                    sb.close()
            for bucket in probe_buckets:
                for psb in bucket:
                    psb.close()

    def _bloom_prefilter(self, ctx: ExecContext, probe_stream,
                         build: ColumnarBatch):
        """Runtime bloom join filter (GpuBloomFilterAggregate /
        GpuBloomFilterMightContain role): drop probe rows whose keys
        cannot be in the build side BEFORE the gather-map join. Sound
        only where dropped probe rows produce no output — inner and
        left-semi."""
        from ..conf import JOIN_BLOOM_ENABLED, JOIN_BLOOM_MIN_PROBE_ROWS
        from ..ops import bloom as B
        if not ctx.conf.get(JOIN_BLOOM_ENABLED) or \
                self.join_type not in (INNER, LEFT_SEMI) or \
                not (self.left_keys or self.right_keys):
            return probe_stream
        from ..conf import JOIN_BLOOM_BITS_PER_KEY
        min_rows = ctx.conf.get(JOIN_BLOOM_MIN_PROBE_ROWS)
        num_bits = B.choose_num_bits(
            int(build.num_rows), ctx.conf.get(JOIN_BLOOM_BITS_PER_KEY))
        eager = self._eager_keys()
        bkey = ("bloom_build", num_bits)
        if bkey not in self._jit_cache:
            self._jit_cache[bkey] = _bloom_build_builder(
                self._build_key_exprs, num_bits) if eager else \
                shared_fn_jit(_bloom_build_builder,
                              self._build_key_exprs, num_bits)
        with ctx.semaphore:
            bits = self._jit_cache[bkey](build)
        pkey = ("bloom_probe", num_bits)
        if pkey not in self._jit_cache:
            self._jit_cache[pkey] = _bloom_probe_builder(
                self._probe_key_exprs) if eager else \
                shared_fn_jit(_bloom_probe_builder, self._probe_key_exprs)
        m = ctx.metrics_for(self.exec_id)
        dropped = m.setdefault("bloomFilteredRows",
                               Metric("bloomFilteredRows", Metric.DEBUG))

        def filtered():
            for probe in probe_stream:
                n = int(probe.num_rows)
                if n < min_rows:
                    yield probe
                    continue
                with ctx.semaphore:
                    out = self._jit_cache[pkey](bits, probe)
                dropped.add(n - int(out.num_rows))
                yield out
        return filtered()

    # set True on broadcast joins: their build side fully materializes
    # BEFORE the probe's first scan file opens, so its keys can prune
    # partitioned probe scans (shuffled joins run the probe map phase
    # first — too late to prune)
    _dpp_capable = False

    def _dpp_scans(self, node, name: str):
        """Partitioned FileSourceScanExecs below ``node`` that column
        ``name`` passes through UNCHANGED (conservative walk — any node
        that might rename/compute the column stops the descent)."""
        from ..io.scan import FileSourceScanExec
        from .basic import (CoalesceBatchesExec, FilterExec, LocalLimitExec,
                            ProjectExec)
        from .pipeline import PrefetchExec
        if isinstance(node, FileSourceScanExec):
            if any(k == name for k, _ in node.scan.partition_schema):
                yield node
            return
        if isinstance(node, ProjectExec):
            from ..expr.core import Alias, ColumnRef
            for e, (out_name, _) in zip(node.exprs, node.output_schema):
                if out_name != name:
                    continue
                inner = e.children[0] if isinstance(e, Alias) else e
                if isinstance(inner, ColumnRef) and inner.name == name:
                    yield from self._dpp_scans(node.children[0], name)
                return
            return
        if isinstance(node, (FilterExec, CoalesceBatchesExec,
                             LocalLimitExec, PrefetchExec)):
            yield from self._dpp_scans(node.children[0], name)
            return
        from .fused import FusedPipelineExec
        if isinstance(node, FusedPipelineExec):
            # see through the fusion wrapper via the original chain —
            # the stage nodes keep their unfused child links, so the
            # usual Project/Filter pass-through rules apply unchanged
            yield from self._dpp_scans(node.stages[-1], name)
            return
        # unknown/multi-child operator: don't assume pass-through

    def _runtime_partition_prune(self, ctx: ExecContext,
                                 build: ColumnarBatch) -> None:
        """Runtime DPP (GpuSubqueryBroadcastExec:1-299 +
        GpuDynamicPruningExpression role): the materialized build
        side's distinct join-key values become a partition-value filter
        on probe-side partitioned scans."""
        from ..conf import DPP_ENABLED
        from ..expr.core import ColumnRef
        if not self._dpp_capable or not ctx.conf.get(DPP_ENABLED):
            return
        if self.join_type not in (INNER, LEFT_SEMI):
            # outer/anti joins PRESERVE unmatched probe rows — pruning
            # their files would drop them
            return
        probe_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        for pk, bk in zip(self._probe_key_exprs, self._build_key_exprs):
            if not isinstance(pk, ColumnRef):
                continue
            scans = list(self._dpp_scans(probe_child, pk.name))
            if not scans:
                continue
            kcol = bk.eval(build)
            vals, mask = kcol.to_numpy(int(build.num_rows))
            keys = {v.item() if hasattr(v, "item") else v
                    for v, ok in zip(vals, mask) if ok}
            m = ctx.metrics_for(self.exec_id)
            m.setdefault("dppFilters",
                         Metric("dppFilters", Metric.MODERATE)).add(
                len(scans))
            for s in scans:
                f = dict(s.runtime_part_filter or {})
                f[pk.name] = keys
                s.runtime_part_filter = f

    def _join_partition(self, ctx: ExecContext, probe_stream,
                        build_stream) -> Iterator[ColumnarBatch]:
        """Join one (probe partition, build partition) pair."""
        from ..conf import JOIN_SUB_PARTITION_ROWS
        m = ctx.metrics_for(self.exec_id)
        retries = m.setdefault("joinOverflowRetries",
                               Metric("joinOverflowRetries", Metric.DEBUG))
        build = self._concat_build(ctx, build_stream)
        if build is None:
            yield from self._empty_result(probe_stream, ctx)
            return
        self._runtime_partition_prune(ctx, build)
        probe_stream = self._bloom_prefilter(ctx, probe_stream, build)
        threshold = ctx.conf.get(JOIN_SUB_PARTITION_ROWS)
        n_rows = int(build.num_rows)
        keyed = bool(self.left_keys or self.right_keys)
        sub = n_rows > threshold and keyed
        if not sub and keyed:
            # adaptive byte cap: a build side whose MEASURED bytes
            # exceed srt.sql.adaptive.maxBroadcastJoinBytes joins
            # sub-partitioned even when its row count looks benign
            # (wide rows defeat the row threshold) — the single hash
            # table is bounded either way
            from ..conf import ADAPTIVE_MAX_BROADCAST_BYTES
            if ctx.conf.get(ADAPTIVE_MAX_BROADCAST_BYTES) > 0:
                from ..memory.spill import batch_nbytes
                from ..plan.adaptive import broadcast_oversize_slices
                slices = broadcast_oversize_slices(
                    ctx, self, n_rows, batch_nbytes(build))
                if slices:
                    threshold = max(-(-n_rows // slices), 1)
                    sub = True
        if sub:
            holder = [build]
            del build
            yield from self._sub_partition_join(ctx, probe_stream, holder,
                                                threshold)
            return
        for probe in probe_stream:
            if int(probe.num_rows) == 0:
                continue
            yield from self._join_batches(ctx, probe, build, retries)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        yield from self._join_partition(ctx, self._probe_stream(ctx),
                                        self._build_stream(ctx))


class ShuffledHashJoinExec(_HashJoinBase):
    """Hash join where both sides arrive co-partitioned on the join keys
    (GpuShuffledHashJoinExec.scala:90): the planner exchanges both
    children into the same hash partitioning; each partition pair joins
    independently (the distributed join decomposition)."""

    def required_child_distributions(self):
        from ..plan.distribution import (ClusteredDistribution,
                                         UnspecifiedDistribution)
        if not self.left_keys:
            return [UnspecifiedDistribution(), UnspecifiedDistribution()]
        return [ClusteredDistribution(self.left_keys),
                ClusteredDistribution(self.right_keys)]

    @property
    def output_partitioning(self):
        # rows stay in their partition; the probe side's placement holds
        probe = self.children[0] if self.build_side == "right" \
            else self.children[1]
        return probe.output_partitioning

    def _demoted_broadcast_streams(self, ctx: ExecContext):
        """Execution body of the joinStrategy demotion decided by
        plan/adaptive.py (the AQE decision the reference takes via
        GpuQueryStagePrepOverrides + Spark's DynamicJoinSelection): the
        measured-small build side streams whole as a broadcast-style
        single stream and the probe-side exchange is BYPASSED entirely
        (its map phase never runs). Returns (probe_stream,
        build_stream)."""
        build_child = self.children[1] if self.build_side == "right" \
            else self.children[0]
        probe_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        counts, _ = build_child.materialized_stats(ctx)
        m = ctx.metrics_for(self.exec_id)
        m.setdefault("adaptiveBroadcastJoins",
                     Metric("adaptiveBroadcastJoins",
                            Metric.MODERATE)).add(1)

        def build_stream():
            if ctx.cluster is not None:
                # broadcast semantics: EVERY worker needs the FULL
                # build side — fetch all reduce partitions from all
                # peers (materialized_stats' gather already
                # synchronized the map writes; `allowed` restricts
                # reads to the maps that won speculation)
                from ..parallel.transport import fetch_all_partitions
                peers = ctx.cluster.peers
                allowed = build_child._allowed_by_endpoint(ctx)
                resolver = ctx.cluster.resolve_endpoint
                for reduce_id in range(len(counts)):
                    yield from fetch_all_partitions(
                        peers, build_child.shuffle_id, reduce_id,
                        endpoint_resolver=resolver, allowed=allowed)
                return
            for part in build_child.execute_partitioned(ctx):
                yield from part
        # the probe exchange's CHILD streams directly: its shuffle work
        # is skipped (never registered, nothing to unregister); in
        # cluster mode that child is this worker's scan shard, which is
        # exactly the broadcast-join probe distribution
        return probe_child.children[0].execute(ctx), build_stream()

    def _zipped_partitions(self, ctx: ExecContext, decision):
        """Pairwise (probe, build) partition streams. zip_longest (not
        zip) so both child generators are driven to exhaustion in order
        — an exchange unregisters its shuffle in a finally that must run
        only after its last partition has been consumed. When the
        adaptive decision regrouped partitions, ONE grouping applies to
        both sides (keys stay aligned) and skewed groups read the probe
        side in map-id slices."""
        import itertools
        l, r = self.children[0], self.children[1]
        if decision.mode == "partitioned" and \
                decision.out_groups is not None:
            if decision.n_skewed:
                m = ctx.metrics_for(self.exec_id)
                m.setdefault(
                    "skewedJoinPartitions",
                    Metric("skewedJoinPartitions",
                           Metric.MODERATE)).add(decision.n_skewed)
            probe_is_left = self.build_side == "right"
            probe_x, build_x = (l, r) if probe_is_left else (r, l)
            probe_parts = probe_x.execute_partition_groups(
                ctx, decision.out_groups, map_mod=decision.probe_mod)
            build_parts = build_x.execute_partition_groups(
                ctx, decision.build_groups)
            for pp, bp in itertools.zip_longest(probe_parts,
                                                build_parts):
                yield (pp, bp)
            return
        left_parts = l.execute_partitioned(ctx)
        right_parts = r.execute_partitioned(ctx)
        for lp, rp in itertools.zip_longest(left_parts, right_parts):
            if lp is None or rp is None:
                raise RuntimeError(
                    "join children partition counts differ")
            yield ((lp, rp) if self.build_side == "right" else (rp, lp))

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        for part in self.execute_partitioned(ctx):
            yield from part

    def execute_partitioned(self, ctx: ExecContext):
        # the rules live in plan/adaptive.py; the decision is cached on
        # this node (the eager stage executor may have attached it
        # already), cluster-safe by construction — a pure function of
        # globally gathered statistics
        from ..plan.adaptive import join_decision
        decision = join_decision(ctx, self)
        if decision.mode == "broadcast_build":
            probe_stream, build_stream = \
                self._demoted_broadcast_streams(ctx)
            yield self._join_partition(ctx, probe_stream, build_stream)
            return
        for probe, build in self._zipped_partitions(ctx, decision):
            yield self._join_partition(ctx, probe, build)

    def node_description(self) -> str:
        return (f"ShuffledHashJoin[{self.join_type}, "
                f"build={self.build_side}]")


class BroadcastHashJoinExec(_HashJoinBase):
    """Hash join with a broadcast build side
    (GpuBroadcastHashJoinExecBase.scala): the build child is a
    BroadcastExchangeExec; the probe side streams through unexchanged.
    Under a mesh the build side is replicated to every device
    (all_gather)."""

    _dpp_capable = True

    def required_child_distributions(self):
        from ..plan.distribution import (BroadcastDistribution,
                                         UnspecifiedDistribution)
        if self.build_side == "right":
            return [UnspecifiedDistribution(), BroadcastDistribution()]
        return [BroadcastDistribution(), UnspecifiedDistribution()]

    @property
    def output_partitioning(self):
        probe = self.children[0] if self.build_side == "right" \
            else self.children[1]
        return probe.output_partitioning

    def execute_partitioned(self, ctx: ExecContext):
        """The advertised partitioning is the PROBE side's, so a
        partition-wise consumer (a co-partitioned join above) must see
        one joined output partition per probe partition — the build
        side is the same broadcast table for every one of them. The
        whole-stream default made the advertisement a lie (SF1 q11/q74:
        'join children partition counts differ' one join up).

        The build concats ONCE (each _join_partition then no-ops its
        single-batch concat) and runtime partition pruning runs BEFORE
        the probe side starts executing — the first pull on a probe
        exchange drains its scans, after which a prune is too late."""
        probe_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        build = self._concat_build(ctx, self._build_stream(ctx))
        if build is not None:
            self._runtime_partition_prune(ctx, build)
        for probe in probe_child.execute_partitioned(ctx):
            if build is None:
                yield self._measure_stream(
                    ctx, self._empty_result(probe, ctx))
            else:
                yield self._measure_stream(
                    ctx, self._join_partition(ctx, probe, iter([build])))

    def node_description(self) -> str:
        return (f"BroadcastHashJoin[{self.join_type}, "
                f"build={self.build_side}]")
