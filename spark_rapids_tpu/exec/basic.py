"""Basic physical operators: scan, project, filter, limit, expand,
union, range, coalesce.

Reference counterparts (SURVEY §2.4): basicPhysicalOperators.scala
(GpuProjectExec:350, GpuFilterExec:783), limit.scala, GpuExpandExec,
GpuRangeExec, GpuCoalesceBatches.scala (AbstractGpuCoalesceIterator:250).

Projection/filter evaluate the whole expression list inside one jitted
trace per (capacity, schema) so XLA fuses the expression DAG — there is
no per-expression kernel-launch loop to optimize away.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, choose_capacity,
                               live_mask)
from ..expr.core import Expression, output_name
from ..jit_registry import shared_fn_jit, shared_method_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, NvtxTimer, Schema, TpuExec


class BatchScanExec(TpuExec):
    """Leaf: yields pre-built batches (in-memory table scan).

    File-format scans (parquet/csv/json) subclass the same shape in
    io/scan.py.
    """

    def __init__(self, batches: Sequence[ColumnarBatch], schema: Schema):
        super().__init__()
        self._batches = list(batches)
        self._schema = list(schema)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        yield from self._batches

    def node_description(self) -> str:
        return f"BatchScan[{len(self._batches)} batches]"


class ProjectExec(TpuExec):
    """Tiered projection (GpuProjectExec / GpuTieredProject).

    Context expressions (expr/misc.py) make this operator
    position-aware: (row_offset, partition_id) pass as traced scalars —
    one compiled program for every batch — and eager-only trees
    (input_file_name, uuid, raise_error) evaluate un-jitted."""

    def __init__(self, child: TpuExec, exprs: Sequence[Expression]):
        super().__init__(child)
        self.exprs = list(exprs)
        in_schema = child.output_schema
        self._schema = [(output_name(e, i), e.data_type(in_schema))
                        for i, e in enumerate(self.exprs)]
        from ..expr.misc import contains_eager
        self._eager = contains_eager(self.exprs)
        self._jit = shared_method_jit(self, "_project", ("exprs", "_schema"))
        self._jit_ctx = self._project_ctx if self._eager \
            else shared_method_jit(self, "_project_ctx",
                                   ("exprs", "_schema"))

    def _project(self, batch: ColumnarBatch) -> ColumnarBatch:
        cols = [e.eval(batch) for e in self.exprs]
        return ColumnarBatch(cols, [n for n, _ in self._schema],
                             batch.num_rows)

    def _project_ctx(self, batch: ColumnarBatch, row_offset,
                     partition_id) -> ColumnarBatch:
        from ..expr.misc import traced_context
        with traced_context(row_offset, partition_id):
            return self._project(batch)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        offset = 0
        for batch in self.children[0].execute(ctx):
            with ctx.semaphore:
                out = self._jit_ctx(batch, jnp.int64(offset),
                                    jnp.int32(ctx.partition_id))
            offset += int(batch.num_rows)
            yield out

    def node_description(self) -> str:
        return f"Project[{', '.join(n for n, _ in self._schema)}]"


class FilterExec(TpuExec):
    """WHERE: compacts passing rows to the batch prefix (GpuFilterExec)."""

    def __init__(self, child: TpuExec, condition: Expression):
        super().__init__(child)
        self.condition = condition
        from ..expr.misc import contains_eager
        # eager conditions (ANSI guards, raise_error) must evaluate
        # outside jit so data-dependent raises reach the caller
        self._jit = self._filter if contains_eager([condition]) \
            else shared_method_jit(self, "_filter", ("condition",))

    def _filter(self, batch: ColumnarBatch) -> ColumnarBatch:
        cond = self.condition.eval(batch)
        return K.filter_batch(batch, cond)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute(ctx):
            with ctx.semaphore:
                yield self._jit(batch)

    def node_description(self) -> str:
        return f"Filter[{self.condition!r}]"


class LocalLimitExec(TpuExec):
    """LIMIT n within the stream (GpuLocalLimitExec, limit.scala)."""

    def __init__(self, child: TpuExec, limit: int):
        super().__init__(child)
        self.limit = limit
        # limit passed as a traced scalar: one compile per capacity
        # bucket, not one per distinct remaining-count
        self._jit = shared_fn_jit(_local_limit_builder)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        for batch in self.children[0].execute(ctx):
            if remaining <= 0:
                return
            with ctx.semaphore:
                out = self._jit(batch, jnp.int64(remaining))
            remaining -= int(out.num_rows)
            yield out

    def node_description(self) -> str:
        return f"LocalLimit[{self.limit}]"


def _local_limit_builder():
    return K.local_limit


class UnionExec(TpuExec):
    """UNION ALL: concatenation of child streams (GpuUnionExec)."""

    def __init__(self, *children: TpuExec):
        super().__init__(*children)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        names = [n for n, _ in self.output_schema]
        for child in self.children:
            for batch in child.execute(ctx):
                # normalize column names across the union
                yield ColumnarBatch(batch.columns, names, batch.num_rows)


class ExpandExec(TpuExec):
    """Multiple projection lists per input row — GROUPING SETS / rollup /
    cube (GpuExpandExec)."""

    def __init__(self, child: TpuExec, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str]):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        in_schema = child.output_schema
        # Unify each output column's dtype across ALL projection lists
        # (grouping sets routinely mix e.g. col and NULL literal slots)
        # and cast divergent slots, so every emitted batch matches the
        # declared schema.
        from ..expr.cast import Cast
        from ..expr.conditional import _common_type
        unified = [
            _common_type([p[i].data_type(in_schema)
                          for p in self.projections])
            for i in range(len(names))]
        for p in self.projections:
            for i, t in enumerate(unified):
                if p[i].data_type(in_schema) != t:
                    p[i] = Cast(p[i], t)
        self._schema = list(zip(names, unified))
        from ..expr.misc import contains_eager
        self._jits = [
            _expand_project_builder(p, list(names)) if contains_eager(p)
            else shared_fn_jit(_expand_project_builder, p, list(names))
            for p in self.projections]

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute(ctx):
            for jit in self._jits:
                with ctx.semaphore:
                    yield jit(batch)

    def node_description(self) -> str:
        return f"Expand[{len(self.projections)} projections]"


def _expand_project_builder(exprs, names):
    def run(batch):
        cols = [e.eval(batch) for e in exprs]
        return ColumnarBatch(cols, list(names), batch.num_rows)
    return run


class RangeExec(TpuExec):
    """SELECT id FROM range(start, end, step) (GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> Schema:
        return [("id", dt.INT64)]

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..conf import BATCH_SIZE_ROWS
        per = self.batch_rows or ctx.conf.get(BATCH_SIZE_ROWS)
        total = max(0, -(-(self.end - self.start) // self.step))
        done = 0
        while done < total:
            n = min(per, total - done)
            cap = choose_capacity(n)
            base = self.start + done * self.step
            data = base + jnp.arange(cap, dtype=jnp.int64) * self.step
            live = live_mask(cap, n)
            col = ColumnVector(jnp.where(live, data, 0), live, dt.INT64)
            yield ColumnarBatch([col], ["id"], n)
            done += n

    def node_description(self) -> str:
        return f"Range[{self.start}, {self.end}, step={self.step}]"


class CoalesceBatchesExec(TpuExec):
    """Combine small batches up to the target size (GpuCoalesceBatches,
    AbstractGpuCoalesceIterator:250). Registers pending batches as
    spillable while accumulating, like the reference's on-deck storage."""

    def __init__(self, child: TpuExec, target_rows: Optional[int] = None):
        super().__init__(child)
        self.target_rows = target_rows

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import time as _time

        from ..conf import BATCH_SIZE_ROWS
        from ..memory.spill import SpillableBatch, SpillPriority
        target = self.target_rows or ctx.conf.get(BATCH_SIZE_ROWS)
        # time spent blocked pulling the child: under pipelining the
        # child is a prefetcher, so this is the residual stall the
        # background producer could not hide
        wait = ctx.metrics_for(self.exec_id).setdefault(
            "coalesceWaitTime",
            Metric("coalesceWaitTime", Metric.MODERATE, "ns"))
        pending: List[SpillableBatch] = []
        pending_rows = 0

        def flush() -> Optional[ColumnarBatch]:
            nonlocal pending, pending_rows
            if not pending:
                return None
            batches = [sb.get() for sb in pending]
            if len(batches) == 1:
                out = batches[0]
            else:
                cap = choose_capacity(pending_rows)
                with ctx.semaphore:
                    out = K.concat_batches(batches, cap)
            for sb in pending:
                sb.close()
            pending, pending_rows = [], 0
            return out

        it = iter(self.children[0].execute(ctx))
        while True:
            t0 = _time.perf_counter_ns()
            try:
                batch = next(it)
            except StopIteration:
                wait.add(_time.perf_counter_ns() - t0)
                break
            wait.add(_time.perf_counter_ns() - t0)
            n = int(batch.num_rows)
            if n == 0:
                continue
            if n >= target and not pending:
                # already at target with nothing buffered: skip the
                # spill-registration + get() round-trip entirely
                yield batch
                continue
            if pending_rows + n > target and pending:
                out = flush()
                if out is not None:
                    yield out
                if n >= target:
                    yield batch
                    continue
            pending.append(SpillableBatch(batch,
                                          SpillPriority.ACTIVE_ON_DECK))
            pending_rows += n
            if pending_rows >= target:
                out = flush()
                if out is not None:
                    yield out
        out = flush()
        if out is not None:
            yield out

    def node_description(self) -> str:
        return f"CoalesceBatches[target={self.target_rows or 'conf'}]"


def sample_keep_mask(row_offset, capacity: int, fraction: float,
                     seed: int):
    """Deterministic Bernoulli keep-mask: murmur3 of the stream-global
    row position under ``seed`` compared against fraction * 2^32. The
    SAME function drives the device exec and the CPU engine, so
    fallback sampling is bit-identical (GpuSampleExec role)."""
    from ..columnar import dtypes as dt_
    from ..expr import hashing as H
    pos = jnp.arange(capacity, dtype=jnp.int64) + jnp.int64(row_offset)
    col = ColumnVector(pos, jnp.ones(capacity, jnp.bool_), dt_.INT64)
    h = H.murmur3_column(col, jnp.uint32(seed))
    threshold = jnp.uint32(min(int(fraction * (1 << 32)), (1 << 32) - 1))
    if fraction >= 1.0:
        return jnp.ones(capacity, jnp.bool_)
    return h < threshold


class SampleExec(TpuExec):
    """WHERE-style Bernoulli sampling by position hash (GpuSampleExec,
    basicPhysicalOperators.scala)."""

    def __init__(self, child: TpuExec, fraction: float, seed: int):
        super().__init__(child)
        self.fraction = fraction
        self.seed = seed
        self._jit = shared_method_jit(self, "_sample", ("fraction", "seed"))

    def _sample(self, batch: ColumnarBatch, row_offset):
        keep = sample_keep_mask(row_offset, batch.capacity,
                                self.fraction, self.seed)
        cond = ColumnVector(keep, jnp.ones_like(keep), dt.BOOL)
        return K.filter_batch(batch, cond)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        offset = 0
        for batch in self.children[0].execute(ctx):
            with ctx.semaphore:
                out = self._jit(batch, jnp.int64(offset))
            offset += int(batch.num_rows)
            yield out

    def node_description(self) -> str:
        return f"Sample[{self.fraction}, seed={self.seed}]"
