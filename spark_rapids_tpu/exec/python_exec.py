"""ArrowEvalPythonExec: scalar pandas UDFs over Arrow batches.

Rebuild of GpuArrowEvalPythonExec (sql-plugin/.../execution/python/
GpuArrowEvalPythonExec.scala): child batches pass through unchanged
with one appended column per UDF. The UDF argument expressions evaluate
on device (jit-projected), the argument columns cross host<->worker as
Arrow IPC via the pooled worker processes (udf/worker.py), and results
rejoin the device batch at the child's capacity — row alignment holds
because live rows are always the batch prefix."""

from __future__ import annotations

import io
from typing import Iterator, List, Tuple

import jax

from ..columnar.vector import ColumnarBatch
from .base import ExecContext, Metric, NvtxTimer, Schema, TpuExec


class ArrowEvalPythonExec(TpuExec):
    def __init__(self, child: TpuExec, udfs: List[Tuple["PandasUDF", str]]):
        super().__init__(child)
        self.udfs = list(udfs)
        in_schema = child.output_schema
        self._out_schema = list(in_schema) + \
            [(name, u.return_type) for u, name in self.udfs]

        def project_inputs(batch: ColumnarBatch) -> ColumnarBatch:
            cols, names = [], []
            for i, (u, _) in enumerate(self.udfs):
                for j, ce in enumerate(u.children):
                    cols.append(ce.eval(batch))
                    names.append(f"in{i}_{j}")
            return ColumnarBatch(cols, names, batch.num_rows)

        self._jit_inputs = jax.jit(project_inputs)

    @property
    def output_schema(self) -> Schema:
        return self._out_schema

    def _job_spec(self) -> bytes:
        import pyarrow as pa

        from ..io.arrow_convert import dtype_to_arrow_type
        from ..udf.worker import make_job_spec
        return make_job_spec(
            [(u.fn, len(u.children),
              pa.field(name, dtype_to_arrow_type(u.return_type)))
             for u, name in self.udfs])

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa

        from ..io.arrow_convert import (arrow_to_host_table,
                                        host_table_to_arrow)
        from ..plan.host_table import batch_to_table, table_to_batch
        from ..udf.worker import worker_pool
        m = ctx.metrics_for(self.exec_id)
        udf_time = m.setdefault("pythonUdfTime",
                                Metric("pythonUdfTime", Metric.MODERATE,
                                       "ns"))
        nbatches = m.setdefault("pythonBatches",
                                Metric("pythonBatches", Metric.DEBUG))
        spec = self._job_spec()
        pool = worker_pool()
        names = [n for n, _ in self._out_schema]
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            with ctx.semaphore:
                inputs = self._jit_inputs(batch)
            with NvtxTimer(udf_time, "python.udf"):
                arrow = host_table_to_arrow(batch_to_table(inputs))
                sink = io.BytesIO()
                with pa.ipc.new_stream(sink, arrow.schema) as wr:
                    wr.write_table(arrow)
                out_blob = pool.run_job(spec, sink.getvalue())
                with pa.ipc.open_stream(io.BytesIO(out_blob)) as rd:
                    result = rd.read_all()
            rbatch = table_to_batch(arrow_to_host_table(result),
                                    capacity=batch.capacity)
            nbatches.add(1)
            yield ColumnarBatch(list(batch.columns) + list(rbatch.columns),
                                names, batch.num_rows)

    def node_description(self) -> str:
        fns = ", ".join(getattr(u.fn, "__name__", "<fn>")
                        for u, _ in self.udfs)
        return f"ArrowEvalPython[{fns}]"
