"""Cartesian product + broadcast nested-loop join.

Rebuild of GpuCartesianProductExec.scala and
GpuBroadcastNestedLoopJoinExecBase.scala (SURVEY §2.4): the non-equi
join path. The reference compiles the residual condition to a cuDF AST
and evaluates it over the cross pairs; here the condition is an
ordinary Expression evaluated over a "paired batch" — a virtual batch
where every probe row is replicated across the build rows — so XLA
fuses condition evaluation with the pairing itself.

Pairing is tiled: each (probe batch x build) product evaluates in
build-row-major tiles of at most ``tile_rows`` output slots, keeping
peak HBM bounded the way the reference's nested-loop join streams
partitions.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity, live_mask)
from ..expr.core import Expression
from ..jit_registry import shared_fn_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, Schema, TpuExec


def _replicate_pair(probe: ColumnarBatch, build: ColumnarBatch,
                    probe_rows: int, tile_start: int, tile_cap: int,
                    build_count) -> ColumnarBatch:
    """Virtual cross-pair batch for one tile.

    Output slot j holds (probe_row, build_row) where
      flat = tile_start + j
      probe_row = flat // build_capacity ; build_row = flat % build_cap
    Live slots: probe_row < probe_rows AND build_row < build_count.
    """
    bcap = build.capacity
    j = jnp.arange(tile_cap, dtype=jnp.int32)
    flat = tile_start + j
    p_idx = flat // bcap
    b_idx = flat % bcap
    valid = (p_idx < probe_rows) & (b_idx < build_count)
    p_cols = [c.gather(jnp.clip(p_idx, 0, probe.capacity - 1), valid)
              for c in probe.columns]
    b_cols = [c.gather(jnp.clip(b_idx, 0, bcap - 1), valid)
              for c in build.columns]
    # num_rows = tile_cap: live pair slots are NOT a prefix of the tile,
    # so the whole tile stays "live" and the caller's keep-mask (which
    # includes ``valid``) does all the filtering/compaction.
    return ColumnarBatch(p_cols + b_cols, probe.names + build.names,
                         jnp.int32(tile_cap)), valid


def _tile_run_builder(condition, tile_cap):
    def run(probe, build, probe_rows, tile_start, build_count):
        paired, valid = _replicate_pair(
            probe, build, probe_rows, tile_start, tile_cap, build_count)
        if condition is not None:
            cond = condition.eval(paired)
            keep = cond.data & cond.validity & valid
        else:
            keep = valid
        keep_col = ColumnVector(keep, jnp.ones_like(keep), dt.BOOL)
        return K.filter_batch(paired, keep_col)
    return run


class BroadcastNestedLoopJoinExec(TpuExec):
    """inner/cross nested-loop join with an arbitrary condition.

    left = streamed side, right = broadcast (build) side, like the
    reference's build-side-broadcast formulation.
    """

    def __init__(self, left: TpuExec, right: TpuExec,
                 condition: Optional[Expression] = None,
                 join_type: str = "inner",
                 tile_rows: int = 1 << 16):
        super().__init__(left, right)
        if join_type not in ("inner", "cross"):
            raise NotImplementedError(
                f"nested-loop join type {join_type} (planner must fall "
                "back)")
        self.condition = condition
        self.join_type = join_type
        self.tile_rows = tile_rows
        self._jit_cache = {}

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema + \
            self.children[1].output_schema

    def _tile_fn(self, tile_cap: int, probe_cap: int):
        key = (tile_cap, probe_cap)
        if key not in self._jit_cache:
            from ..expr.misc import contains_eager
            if self.condition is not None and \
                    contains_eager([self.condition]):
                self._jit_cache[key] = _tile_run_builder(self.condition,
                                                         tile_cap)
            else:
                self._jit_cache[key] = shared_fn_jit(
                    _tile_run_builder, self.condition, tile_cap)
        return self._jit_cache[key]

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        build_batches = [b for b in self.children[1].execute(ctx)
                         if int(b.num_rows) > 0]
        if not build_batches:
            return
        total_b = sum(int(b.num_rows) for b in build_batches)
        with ctx.semaphore:
            build = (build_batches[0] if len(build_batches) == 1 else
                     K.concat_batches(build_batches,
                                      choose_capacity(total_b)))
        bcap = build.capacity
        for probe in self.children[0].execute(ctx):
            n_probe = int(probe.num_rows)
            if n_probe == 0:
                continue
            total_slots = probe.capacity * bcap
            tile_cap = min(choose_capacity(self.tile_rows), total_slots)
            fn = self._tile_fn(tile_cap, probe.capacity)
            for start in range(0, total_slots, tile_cap):
                # skip tiles whose every probe row is dead
                if start // bcap >= n_probe:
                    break
                with ctx.semaphore:
                    out = fn(probe, build, jnp.int32(n_probe),
                             jnp.int32(start), build.num_rows)
                if int(out.num_rows) > 0:
                    yield out

    def node_description(self) -> str:
        c = f", cond={self.condition!r}" if self.condition is not None \
            else ""
        return f"BroadcastNestedLoopJoin[{self.join_type}{c}]"


class CartesianProductExec(BroadcastNestedLoopJoinExec):
    """CROSS JOIN (GpuCartesianProductExec): a conditionless nested
    loop."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 tile_rows: int = 1 << 16):
        super().__init__(left, right, condition=None, join_type="cross",
                         tile_rows=tile_rows)

    def node_description(self) -> str:
        return "CartesianProduct"
