"""Exec base: operator protocol, metrics, device semaphore.

Reference counterparts: GpuExec.scala:197 (base trait + metrics
GpuExec.scala:36-188), GpuSemaphore.scala (N tasks share the device,
computeNumPermits :106), GpuMetric ESSENTIAL/MODERATE/DEBUG levels.
"""

from __future__ import annotations

import os

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnarBatch
from ..conf import CONCURRENT_TASKS, SrtConf, active_conf

Schema = List  # [(name, DType), ...]

# Resolved once: the profiler annotation class used by the scoped
# timers. Both timers run on every operator pull, so the per-enter
# ``import jax.profiler`` + except dance was measurable overhead on
# the hot path (part of the roofline layer's <=2% sampling budget);
# a module-level None check is the same cost as the tracer gate.
try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover - jax always present in-tree
    _TraceAnnotation = None


class Metric:
    """One operator metric (GpuMetric). Thread-safe accumulator."""

    ESSENTIAL = "ESSENTIAL"
    MODERATE = "MODERATE"
    DEBUG = "DEBUG"

    def __init__(self, name: str, level: str = MODERATE, unit: str = ""):
        self.name = name
        self.level = level
        self.unit = unit
        self.value = 0
        self._lock = threading.Lock()

    def add(self, v) -> None:
        with self._lock:
            self.value += int(v)

    def set(self, v) -> None:
        with self._lock:
            self.value = int(v)

    def __repr__(self):
        return f"{self.name}={self.value}{self.unit}"


class NvtxTimer:
    """Scoped op-time accumulation (NvtxWithMetrics.scala:21-48).

    On TPU there is no NVTX; ranges surface through jax.profiler traces.
    """

    def __init__(self, metric: Optional[Metric], name: str = ""):
        self.metric = metric
        self.name = name
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        if _TraceAnnotation is not None:
            try:
                self._trace = _TraceAnnotation(self.name or "op")
                self._trace.__enter__()
            except Exception:
                self._trace = None
        else:
            self._trace = None
        return self

    def __exit__(self, *exc):
        try:
            if self._trace is not None:
                self._trace.__exit__(*exc)
        finally:
            self._trace = None
            if self.metric is not None:
                self.metric.add(time.perf_counter_ns() - self._t0)
        return False


class SelfTimer:
    """Self-time accumulation for nested operator pulls.

    Operators pull their children inside ``next()``, so a naive scoped
    timer would charge the whole subtree to every ancestor (the reference
    explicitly excludes child time from op time). A per-context timer
    stack pauses the enclosing operator's clock while a nested one runs:
    each metric receives only the time its own operator spent. Each
    pulling thread has its own stack (ExecContext.timer_stack is
    thread-local): frames on different threads run genuinely in
    parallel — pipelined producers (exec/pipeline.py) — and must not
    pause each other; I/O thread pools do their timing elsewhere.
    """

    def __init__(self, stack: list, metric: Optional[Metric], name: str = "",
                 tracer=None):
        self.stack = stack
        self.metric = metric
        self.name = name
        self.tracer = tracer
        self._t0 = 0
        self._span = None
        self._trace = None

    def __enter__(self):
        t = time.perf_counter_ns()
        if self.stack:
            parent = self.stack[-1]
            if parent.metric is not None:
                parent.metric.add(t - parent._t0)
        self._t0 = t
        self.stack.append(self)
        if self.tracer is not None:
            # Inclusive operator span: parent is the nearest enclosing
            # timed frame's span, else the thread's open scope (the
            # query/task span).
            parent_id = None
            for frame in reversed(self.stack[:-1]):
                sp = getattr(frame, "_span", None)
                if sp is not None:
                    parent_id = sp.span_id
                    break
            if parent_id is None:
                parent_id = self.tracer.current_id()
            self._span = self.tracer.begin(self.name or "op",
                                           kind="operator",
                                           parent=parent_id)
        if _TraceAnnotation is not None:
            try:
                self._trace = _TraceAnnotation(self.name or "op")
                self._trace.__enter__()
            except Exception:
                self._trace = None
        return self

    def __exit__(self, *exc):
        try:
            if self._trace is not None:
                self._trace.__exit__(*exc)
        finally:
            self._trace = None
            t = time.perf_counter_ns()
            if self in self.stack:
                # An exception below us may have abandoned deeper frames
                # (a suspended generator torn down without its __exit__
                # in stack order). Discard them so the stack stays
                # consistent: the deepest one was the frame actually
                # running, so it gets the elapsed time; the others (and
                # we) were already paused at their child's enter and
                # accrue nothing more.
                dangled = False
                while self.stack[-1] is not self:
                    frame = self.stack.pop()
                    if not dangled and frame.metric is not None:
                        frame.metric.add(t - frame._t0)
                    dangled = True
                self.stack.pop()
                if self.metric is not None and not dangled:
                    self.metric.add(t - self._t0)
                if self.stack:
                    self.stack[-1]._t0 = t
            if self._span is not None and self.tracer is not None:
                self.tracer.end(self._span)
                self._span = None
        return False


class TpuSemaphore:
    """Limits concurrent device-work submitters (GpuSemaphore.scala).

    The reference grants 1000/N permits per task so configuration can
    over/under-subscribe; here a plain counting semaphore over host
    threads suffices because XLA serializes execution per device stream.
    """

    def __init__(self, permits: int):
        self._sem = threading.Semaphore(permits)
        self.permits = permits
        self._holders: Dict[int, int] = {}
        self._lock = threading.Lock()

    def acquire_if_necessary(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
        # uncontended fast path: only actual blocking counts as wait
        # (GpuTaskMetrics semaphore-wait accumulator)
        if not self._sem.acquire(blocking=False):
            t0 = time.perf_counter_ns()
            self._sem.acquire()
            from ..memory.budget import task_context
            task_context().semaphore_wait_ns += \
                time.perf_counter_ns() - t0
        with self._lock:
            self._holders[tid] = 1

    def release_if_held(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            n = self._holders.get(tid, 0)
            if n == 0:
                return
            if n > 1:
                self._holders[tid] = n - 1
                return
            del self._holders[tid]
        self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_held()
        return False


_GLOBAL_SEM: Optional[TpuSemaphore] = None
_SEM_LOCK = threading.Lock()


def device_semaphore() -> TpuSemaphore:
    global _GLOBAL_SEM
    with _SEM_LOCK:
        if _GLOBAL_SEM is None:
            _GLOBAL_SEM = TpuSemaphore(active_conf().get(CONCURRENT_TASKS))
        return _GLOBAL_SEM


class ExecContext:
    """Per-query execution context: conf, metrics sink, semaphore."""

    def __init__(self, conf: Optional[SrtConf] = None, query=None):
        self.conf = conf or active_conf()
        #: cancellation/deadline token (robustness/admission.py
        #: QueryContext); None = non-cancellable run. Checked once per
        #: batch in ``TpuExec.execute`` — the universal teardown point
        #: covering every operator — and shipped to producer/fetch
        #: threads spawned on the query's behalf.
        self.query = query
        self.semaphore = device_semaphore()
        self.metrics: Dict[str, Dict[str, Metric]] = {}
        #: SelfTimer stacks, one per pulling thread (see timer_stack)
        self._timer_stacks = threading.local()
        #: current reduce-partition index for context expressions
        #: (spark_partition_id / monotonically_increasing_id); operators
        #: that stream one partition at a time set this while iterating
        self.partition_id = 0
        #: multi-host execution context (parallel/cluster.py
        #: ClusterTaskContext); None = single-process run
        self.cluster = None
        #: crash-dump ring (srt.debug.dumpPath): exec_id -> last batch
        self.last_batches: Dict[str, tuple] = {}
        self._dumped = False
        #: per-query span tracer (obs/trace.py) when
        #: srt.eventLog.trace.enabled; None = no span allocation
        self.tracer = None

    def dump_crash(self, failing_exec, error: BaseException,
                   dump_dir: str) -> Optional[str]:
        """Write every operator's last output batch + the plan tree +
        the error under dump_dir (once per query) so the failure
        replays offline (DumpUtils crash-dump role). Returns the dump
        directory."""
        if self._dumped:
            return None
        self._dumped = True
        import time as _time

        from ..utils.dump import dump_batch
        out = os.path.join(dump_dir,
                           f"crash-{int(_time.time() * 1e3)}")
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, "plan.txt"), "w") as f:
            f.write(failing_exec.tree_string() + "\n\n")
            f.write(f"failing operator: "
                    f"{failing_exec.node_description()}\n")
            f.write(f"error: {type(error).__name__}: {error}\n")
        for exec_id, (desc, batch) in list(self.last_batches.items()):
            safe = exec_id.replace("#", "_")
            try:
                dump_batch(batch, out, prefix=safe)
            except Exception:
                pass  # best-effort: a corrupt batch may be the cause
        return out

    @property
    def timer_stack(self) -> list:
        """This thread's SelfTimer stack. Per-thread so pipelined
        producer threads (exec/pipeline.py) attribute their operators'
        exclusive time on their own stack — frames on different threads
        genuinely run concurrently and must not pause each other."""
        st = getattr(self._timer_stacks, "stack", None)
        if st is None:
            st = self._timer_stacks.stack = []
        return st

    def metrics_for(self, exec_id: str) -> Dict[str, Metric]:
        return self.metrics.setdefault(exec_id, {})


class TpuExec:
    """Base physical operator.

    Children in ``children``; ``output_schema`` is the produced schema;
    ``execute(ctx)`` yields ColumnarBatches. Subclasses implement
    ``do_execute``.
    """

    _counter = [0]

    #: set by the planner when a partition-wise parent consumes this
    #: node's advertised partitioning without a re-exchange: AQE
    #: transforms that change the partition count must stand down
    preserve_partitioning = False

    def __init__(self, *children: "TpuExec"):
        self.children: List[TpuExec] = list(children)
        TpuExec._counter[0] += 1
        self.exec_id = f"{type(self).__name__}#{TpuExec._counter[0]}"

    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError

    # --- distribution protocol (plan/distribution.py; EnsureRequirements) ---
    @property
    def output_partitioning(self):
        """How this node's output rows are spread across partitions.
        Default: unknown (forces an exchange wherever a parent needs a
        specific distribution)."""
        from ..plan.distribution import UnknownPartitioning
        return UnknownPartitioning(1)

    def required_child_distributions(self):
        """Per-child Distribution requirements; the planner inserts
        exchanges for children that do not satisfy them."""
        from ..plan.distribution import UnspecifiedDistribution
        return [UnspecifiedDistribution() for _ in self.children]

    def execute_partitioned(self, ctx: "ExecContext"):
        """Yield one batch-iterator per output partition.

        Exchange nodes yield their reduce partitions; everything else is
        a single stream. Partition-wise consumers (final aggregate,
        shuffled join, partition sort) pull through this instead of
        ``execute`` so partition boundaries survive the operator.
        """
        yield self.execute(ctx)

    def _measure_stream(self, ctx: "ExecContext", stream):
        """Output accounting for partition-wise consumption paths that
        bypass ``execute()`` (which does this for the plain path)."""
        m = ctx.metrics_for(self.exec_id)
        rows = m.setdefault("numOutputRows",
                            Metric("numOutputRows", Metric.ESSENTIAL))
        batches = m.setdefault(
            "numOutputBatches", Metric("numOutputBatches",
                                       Metric.MODERATE))
        for b in stream:
            rows.add(int(b.num_rows))
            batches.add(1)
            yield b

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        m = ctx.metrics_for(self.exec_id)
        rows = m.setdefault("numOutputRows", Metric("numOutputRows",
                                                    Metric.ESSENTIAL))
        batches = m.setdefault("numOutputBatches",
                               Metric("numOutputBatches", Metric.MODERATE))
        optime = m.setdefault("opTime", Metric("opTime", Metric.ESSENTIAL,
                                               "ns"))
        from ..conf import DEBUG_DUMP_PATH
        dump_dir = ctx.conf.get(DEBUG_DUMP_PATH)
        # fault injection at operator granularity: tag the pulling
        # thread with this operator's exec_id so memory.reserve fault
        # sites can ~match on it. Only when a plan is armed — the
        # production path never touches the scope TLS.
        from ..robustness import faults
        scope = faults.op_scope(self.exec_id) if faults.armed() else None
        qctx = ctx.query
        it = iter(self.do_execute(ctx))
        while True:
            # per-batch cancellation/deadline point: every operator's
            # pull loop funnels through here, so one check covers scans,
            # fused programs, joins, and exchanges alike (None check
            # only when cancellation is unused)
            if qctx is not None:
                qctx.check()
            with SelfTimer(ctx.timer_stack, optime, self.exec_id,
                           ctx.tracer):
                try:
                    if scope is None:
                        batch = next(it)
                    else:
                        with scope:
                            batch = next(it)
                except StopIteration:
                    return
                except BaseException as e:
                    if dump_dir:
                        ctx.dump_crash(self, e, dump_dir)
                    raise
            rows.add(int(batch.num_rows))
            batches.add(1)
            if dump_dir:
                ctx.last_batches[self.exec_id] = \
                    (self.node_description(), batch)
            yield batch

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    def reset_for_rerun(self) -> None:
        """Clear one-shot per-run state before a cached physical tree is
        re-executed (plan/plan_cache.py). Compile caches (jit wrappers)
        must survive — they are the point of caching the tree; stateful
        nodes (shuffle writes, broadcast materialization) override."""
        # adaptive decisions are derived from ONE run's measured sizes;
        # the next run measures afresh (plan/adaptive.py caches)
        self.__dict__.pop("_adaptive_decision", None)
        self.__dict__.pop("_adaptive_groups_cache", None)
        for c in self.children:
            if isinstance(c, TpuExec):
                c.reset_for_rerun()

    # --- plan tree utilities ---
    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + "* " + self.node_description()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children])

    def node_description(self) -> str:
        return type(self).__name__

    def __repr__(self):
        return self.tree_string()


def schema_names(schema: Schema) -> List[str]:
    return [n for n, _ in schema]


def schema_types(schema: Schema) -> List[dt.DType]:
    return [t for _, t in schema]
