"""Generate exec: explode/posexplode over list columns.

Rebuild of GpuGenerateExec.scala (SURVEY §2.4 Expand/Generate row): one
output row per array element, with the generating row's columns
replicated. The kernel (ops/kernels.py explode_batch) reports the true
required output size; on overflow the exec re-runs at the reported
size's capacity bucket — the same grow-and-retry contract the join
execs use instead of cuDF's dynamic allocations.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnarBatch, choose_capacity
from ..expr.collections import Explode
from ..jit_registry import shared_fn_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, Schema, TpuExec

_MAX_GROWTH_STEPS = 4


def _explode_builder(generator, element_name, pos_name, out_cap):
    def run(batch: ColumnarBatch):
        lc = generator.children[0].eval(batch)
        return K.explode_batch(batch, lc, element_name, out_cap,
                               outer=generator.outer, pos_name=pos_name)
    return run


class GenerateExec(TpuExec):
    def __init__(self, child: TpuExec, generator: Explode,
                 element_name: str, pos_name: Optional[str] = None):
        super().__init__(child)
        self.generator = generator
        self.element_name = element_name
        self.pos_name = pos_name if generator.with_position else None
        in_schema = child.output_schema
        elem_t = generator.data_type(in_schema)
        self._schema = list(in_schema)
        if self.pos_name:
            self._schema.append((self.pos_name, dt.INT32))
        self._schema.append((element_name, elem_t))
        self._jit_cache = {}

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def _fn(self, out_cap: int):
        if out_cap not in self._jit_cache:
            from ..expr.misc import contains_eager
            if contains_eager([self.generator]):
                self._jit_cache[out_cap] = _explode_builder(
                    self.generator, self.element_name, self.pos_name,
                    out_cap)
            else:
                self._jit_cache[out_cap] = shared_fn_jit(
                    _explode_builder, self.generator, self.element_name,
                    self.pos_name, out_cap)
        return self._jit_cache[out_cap]

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        m = ctx.metrics_for(self.exec_id)
        retries = m.setdefault("generateOverflowRetries",
                               Metric("generateOverflowRetries",
                                      Metric.DEBUG))
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            out_cap = choose_capacity(max(batch.capacity, 16))
            for _ in range(_MAX_GROWTH_STEPS + 1):
                with ctx.semaphore:
                    out, total = self._fn(out_cap)(batch)
                total = int(total)
                if total <= out_cap:
                    break
                retries.add(1)
                out_cap = choose_capacity(total)
            else:
                raise RuntimeError(
                    f"explode expansion {total} exceeded capacity after "
                    f"{_MAX_GROWTH_STEPS} growth steps")
            yield out

    def node_description(self) -> str:
        kind = "posexplode" if self.pos_name else "explode"
        outer = "_outer" if self.generator.outer else ""
        return f"Generate[{kind}{outer} -> {self.element_name}]"
