"""Fused operator pipelines: one jitted program per linear chain.

The planner (plan/overrides.py, ``_insert_fusion``) collapses
scan -> filter -> project -> partial-aggregate chains into a single
``FusedPipelineExec`` whose per-batch compute is ONE ``jax.jit``
program, registered through ``jit_registry.shared_fn_jit`` so the
traced artifact is shared across partitions and across queries with
structurally identical chains. This is the direct analogue of the
reference keeping whole operator pipelines resident on device — cuDF's
fused filter/project paths and GpuHashAggregateExec running its update
pass directly on the scan output — instead of materializing every
operator boundary to HBM and reading it back.

Three things the fused program buys over the stock per-operator path:

- XLA sees the whole chain in one trace, so filter masks, projection
  arithmetic and the aggregate update fuse into one kernel schedule
  with no intermediate batch round-tripping through HBM;
- the input batch's buffers can be DONATED to the program
  (``donate_argnums``) on non-CPU backends, letting XLA alias them for
  scratch/output instead of allocating fresh device memory;
- one compiled program per distinct chain shape, reused by every
  partition of every query with the same structure (the registry key
  covers the expression trees and schemas, nothing per-instance).

Correctness contract: the fused program is the literal composition of
the same stage functions the unfused operators trace (``FilterExec.
_filter``, ``ProjectExec._project``, ``HashAggregateExec._update``),
so fused output is bit-identical to unfused output per batch —
``tests/test_fusion.py`` proves this on NDS queries and the matcher
refuses any chain whose semantics depend on host-side state (eager
expressions, partition-context expressions).

OOM handling: each input batch runs through the memory framework's
``with_retry`` with the standard halve-by-rows split policy, so a
RetryOOM spills-and-retries and a SplitAndRetryOOM re-enters the fused
program on each half. Retryable OOMs are raised by the python-side
budget/fault layer BEFORE the program launches, so donation (which
consumes the input on launch) composes with retry.
"""

from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp

from ..columnar.vector import ColumnarBatch
from ..jit_registry import annotate as _annotate
from ..jit_registry import shared_fn_jit
from ..jit_registry import stats as _registry_stats
from ..ops import kernels as K
from .base import ExecContext, Metric, NvtxTimer, Schema, TpuExec

#: module-level fusion tally (bench reads this + the registry's
#: per-module stats to report compile reuse across a sweep);
#: joins/final_aggs/sorts are the v2 shapes layered on the v1 chains
FUSION_STATS = {"chains": 0, "stages": 0, "joins": 0, "final_aggs": 0,
                "sorts": 0}

#: HashAggregateExec fields the fused terminal stage reads, in spec
#: order (must stay in sync with the agg spec built in __init__)
_AGG_FIELDS = ("group_exprs", "agg_exprs", "_key_names",
               "_state_schemas", "_result_schema", "_packed_schema")


def fusion_stats() -> dict:
    """Chains/stages fused this process plus the jit-registry share
    charged to this module (hits = compiled-program reuse)."""
    s = dict(FUSION_STATS)
    s["registry"] = _registry_stats(module=__name__)
    return s


def _row_stage_fn(spec):
    kind = spec[0]
    if kind == "filter":
        cond = spec[1]

        def filt(batch: ColumnarBatch) -> ColumnarBatch:
            return K.filter_batch(batch, cond.eval(batch))
        return filt
    exprs, names = spec[1], spec[2]

    def proj(batch: ColumnarBatch) -> ColumnarBatch:
        return ColumnarBatch([e.eval(batch) for e in exprs],
                             list(names), batch.num_rows)
    return proj


def _agg_shell(spec):
    from .aggregate import HashAggregateExec
    shell = object.__new__(HashAggregateExec)
    for name, val in zip(_AGG_FIELDS, spec[3:]):
        setattr(shell, name, list(val))
    shell._pallas_max_cap = int(spec[2])
    return shell


def _fused_program_builder(specs):
    """MODULE-LEVEL builder for shared_fn_jit: the fused per-batch
    program, a pure function of the stage specs.

    Non-aggregate chains: ``run(batch) -> batch``. Aggregate-terminated
    chains: ``run(batch, row_offset) -> (packed, rows_in, pallas_used)``
    where ``rows_in`` (rows that reached the update pass) advances the
    caller's row_offset and ``pallas_used`` reports the grouped MXU
    lane's per-batch engagement.
    """
    specs = tuple(specs)
    terminal = specs[-1]
    has_agg = terminal[0] == "agg"
    stage_fns = [_row_stage_fn(s) for s in
                 (specs[:-1] if has_agg else specs)]
    if not has_agg:
        def run(batch):
            for f in stage_fns:
                batch = f(batch)
            return batch
        return run
    shell = _agg_shell(terminal)
    use_pallas = bool(terminal[1])

    def run_agg(batch, row_offset):
        for f in stage_fns:
            batch = f(batch)
        rows_in = batch.num_rows
        if use_pallas:
            packed, used = shell._update_pallas(batch, row_offset)
        else:
            packed = shell._update(batch, row_offset)
            used = jnp.bool_(False)
        return packed, rows_in, used
    return run_agg


def _fused_join_builder(join_type, probe_keys, build_keys, out_capacity,
                        reorder_n, suffix_specs):
    """MODULE-LEVEL builder for shared_fn_jit: one program running the
    build+probe gather-map join, the left/right column reorder, and the
    probe-side suffix chain (filter/project/partial-agg), so the joined
    batch never materializes in HBM between operators.

    Non-aggregate suffixes: ``run(probe, build) -> (batch, total)``.
    Aggregate-terminated: ``run(probe, build, row_offset) ->
    (packed, rows_in, pallas_used, total)``. ``total`` is the join
    kernel's true required output size — the host only trusts the
    suffix output when ``total <= out_capacity`` (the capacity-growth
    contract of exec/join.py, unchanged by fusion)."""
    from .join import _join_run_builder
    base = _join_run_builder(join_type, list(probe_keys),
                             list(build_keys), out_capacity)
    specs = tuple(suffix_specs)
    has_agg = bool(specs) and specs[-1][0] == "agg"
    stage_fns = [_row_stage_fn(s) for s in
                 (specs[:-1] if has_agg else specs)]

    def reorder(out: ColumnarBatch) -> ColumnarBatch:
        # kernel output is probe-then-build; plan output is
        # left-then-right (same rule as _HashJoinBase._reorder_columns)
        if reorder_n is None:
            return out
        cols = out.columns[reorder_n:] + out.columns[:reorder_n]
        names = out.names[reorder_n:] + out.names[:reorder_n]
        return ColumnarBatch(cols, names, out.num_rows)

    if not has_agg:
        def run(probe, build):
            out, total = base(probe, build)
            out = reorder(out)
            for f in stage_fns:
                out = f(out)
            return out, total
        return run
    shell = _agg_shell(specs[-1])
    use_pallas = bool(specs[-1][1])

    def run_agg(probe, build, row_offset):
        out, total = base(probe, build)
        out = reorder(out)
        for f in stage_fns:
            out = f(out)
        rows_in = out.num_rows
        if use_pallas:
            packed, used = shell._update_pallas(out, row_offset)
        else:
            packed = shell._update(out, row_offset)
            used = jnp.bool_(False)
        return packed, rows_in, used, total
    return run_agg


def _fused_merge_builder(prefix_specs, agg_spec, cap):
    """MODULE-LEVEL builder for shared_fn_jit: the FINAL-merge fusion
    program. ``run(*batches)`` concatenates one partition's packed
    partials into ``cap`` slots, applies the projection prefix the
    planner absorbed, and merges+finalizes — one program instead of an
    eager concat followed by a separate merge launch. Each distinct
    batch count is its own cached signature (callers bound it with
    srt.exec.fusion.finalAgg.maxMergeInputs)."""
    stage_fns = [_row_stage_fn(s) for s in tuple(prefix_specs)]
    shell = _agg_shell(agg_spec)

    def run(*batches):
        b = batches[0] if len(batches) == 1 \
            else K.concat_batches(list(batches), cap)
        for f in stage_fns:
            b = f(b)
        return shell._merge_finalize(b)
    return run


def fused_final_merge_fn(agg, projs, cap: int):
    """Shared fused FINAL-merge program for ``agg`` (exec/aggregate.py
    calls this when the planner armed merge fusion). ``projs`` are the
    fused-away ProjectExecs in application order (bottom-up)."""
    prefix_specs = tuple(
        ("project", tuple(p.exprs), tuple(n for n, _ in p.output_schema))
        for p in projs)
    # same spec layout as the v1 "agg" spec so _agg_shell applies
    # (the pallas fields are dead in the merge pass)
    agg_spec = ("agg", False, 0) + tuple(
        tuple(getattr(agg, f)) for f in _AGG_FIELDS)
    fn = shared_fn_jit(_fused_merge_builder, prefix_specs, agg_spec, cap)
    _annotate(fn, "fused-final:concat+" + "project+" * len(projs)
              + "merge[" + ", ".join(agg._key_names) + "]")
    return fn


def _schema_row_bytes(schema: Schema) -> int:
    """Estimated device bytes per capacity slot for ``schema`` (data +
    validity lane); variable-width columns counted at a nominal 16B."""
    total = 0
    for _, t in schema:
        phys = getattr(t, "physical", None)
        if phys is None:
            total += 16
        else:
            try:
                total += jnp.dtype(phys).itemsize
            except Exception:
                total += 16
        total += 1  # validity
    return total


class FusedPipelineExec(TpuExec):
    """A planner-fused linear chain executed as one jitted program.

    ``stages`` are the ORIGINAL exec nodes in application order
    (bottom-up: filter before project before partial aggregate); they
    are kept both as the source of the fused program's specs and so
    tree consumers that must see through the fusion (mesh lowering,
    DPP's column-passthrough walk) can reuse the unfused chain — the
    stage nodes still reference their original children.
    """

    def __init__(self, source: TpuExec, stages: List[TpuExec],
                 use_pallas: bool = False, pallas_max_cap: int = 1 << 24,
                 donate: bool = False):
        super().__init__(source)
        from .aggregate import HashAggregateExec
        from .basic import FilterExec, ProjectExec
        self.stages = list(stages)
        terminal = self.stages[-1]
        self._agg = terminal if isinstance(terminal, HashAggregateExec) \
            else None
        self._use_pallas = bool(use_pallas and self._agg is not None)
        self._schema = list(terminal.output_schema)
        specs = []
        for st in self.stages:
            if isinstance(st, FilterExec):
                specs.append(("filter", st.condition))
            elif isinstance(st, ProjectExec):
                specs.append(("project", tuple(st.exprs),
                              tuple(n for n, _ in st.output_schema)))
            else:
                specs.append(("agg", self._use_pallas,
                              int(pallas_max_cap)) +
                             tuple(tuple(getattr(st, f))
                                   for f in _AGG_FIELDS))
        self._specs = tuple(specs)
        # donation is only sound when the source's buffers are
        # single-use (planner gates on file scans) and only effective
        # off-CPU (the CPU backend ignores donations with a warning)
        self.donate = bool(donate) and jax.default_backend() != "cpu"
        jit_kwargs = {"donate_argnums": (0,)} if self.donate else {}
        self._fn = shared_fn_jit(_fused_program_builder, self._specs,
                                 **jit_kwargs)
        # roofline attribution: name the shared program after the
        # chain (the structural key already covers the specs, so every
        # chain of this shape shares both the program and the label)
        _annotate(self._fn, "Fused[" + " -> ".join(
            type(s).__name__ for s in self.stages) + "]")
        # bytes an unfused pipeline would materialize per capacity slot
        # at every internal operator boundary (each non-terminal
        # stage's output batch) — the HBM round-trips fusion removes
        self._saved_bytes_per_slot = sum(
            _schema_row_bytes(st.output_schema)
            for st in self.stages[:-1])
        FUSION_STATS["chains"] += 1
        FUSION_STATS["stages"] += len(self.stages)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def output_partitioning(self):
        return self.stages[-1].output_partitioning

    def mesh_chain_root(self) -> TpuExec:
        """The unfused terminal of the wrapped chain. The mesh stage
        executor traces THROUGH fusion wrappers — a stage program is
        already one XLA computation, so the single-box fusion adds
        nothing there; the stage nodes keep their original child links,
        and lowering from the terminal recovers the whole chain."""
        return self.stages[-1]

    def node_description(self) -> str:
        inner = " -> ".join(type(s).__name__ for s in self.stages)
        tags = []
        if self._use_pallas:
            tags.append("pallas")
        if self.donate:
            tags.append("donate")
        tag = f" ({', '.join(tags)})" if tags else ""
        return f"FusedPipeline[{inner}]{tag}"

    # --- per-stage attribution (tracer-gated calibration) ---
    def _calibrate(self, ctx: ExecContext, batch: ColumnarBatch,
                   row_offset: int, metrics) -> bool:
        """Run the first batch stage-by-stage through the operators'
        own jitted functions, timing each with a device sync, and emit
        one ``fused:<Stage>`` span + metric per stage. This is the
        per-stage op-time attribution for the fused program (which is
        opaque to host timers); outputs are discarded — the stream's
        results always come from the fused program. Only runs when the
        span tracer is on, and only once per execution.

        Returns False — and emits no spans or metrics — when the batch
        empties mid-chain: the unfused operators never charge op time
        for stages an emptied batch would not reach (_partial_stream
        and the Project/Filter loops all skip empty inputs), so
        calibrating on it would skew fused-vs-unfused op-time
        comparisons. The caller retries on the next batch."""
        import time as _time
        cur = batch
        for st in self.stages:
            if st is self._agg:
                break
            cur = st._jit(cur)
            if int(cur.num_rows) == 0:
                return False
        parent = None
        for frame in reversed(ctx.timer_stack):
            sp = getattr(frame, "_span", None)
            if sp is not None:
                parent = sp.span_id
                break
        if parent is None:
            parent = ctx.tracer.current_id()
        cur = batch
        off = jnp.int64(row_offset)
        for i, st in enumerate(self.stages):
            name = f"fused:{type(st).__name__}"
            span = ctx.tracer.begin(
                name, kind="operator", parent=parent,
                attrs={"stage": i, "fused_in": self.exec_id,
                       "desc": st.node_description()})
            t0 = _time.perf_counter_ns()
            if st is self._agg:
                cur = st._jit_update(cur, off)
            else:
                cur = st._jit(cur)
            jax.block_until_ready(cur)
            ns = _time.perf_counter_ns() - t0
            ctx.tracer.end(span)
            mname = f"fusedStageTime.{i}.{type(st).__name__}"
            metrics.setdefault(
                mname, Metric(mname, Metric.MODERATE, "ns")).add(ns)
        return True

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..memory.retry import (split_spillable_in_half_by_rows,
                                    with_retry)
        from ..memory.spill import SpillableBatch, SpillPriority
        m = ctx.metrics_for(self.exec_id)
        fused_ops = m.setdefault("fusedOps",
                                 Metric("fusedOps", Metric.ESSENTIAL))
        saved = m.setdefault(
            "fusionBytesSaved",
            Metric("fusionBytesSaved", Metric.ESSENTIAL, "B"))
        fuse_time = m.setdefault("fusedTime",
                                 Metric("fusedTime", Metric.MODERATE,
                                        "ns"))
        fused_ops.set(len(self.stages))
        state = {"offset": 0}
        used_flags: List = []
        calibrated = ctx.tracer is None

        def run_one(sb):
            batch = sb.get()
            with ctx.semaphore, NvtxTimer(fuse_time, "fused"):
                if self._agg is not None:
                    out, rows_in, used = self._fn(
                        batch, jnp.int64(state["offset"]))
                    n_in = int(rows_in)
                    state["offset"] += n_in
                    if n_in == 0:
                        # the unfused aggregate never sees (and never
                        # emits a partial for) a batch that filtered
                        # down to nothing (_partial_stream skips them)
                        sb.close()
                        return None
                    if self._use_pallas:
                        used_flags.append(used)
                else:
                    out = self._fn(batch)
            saved.add(self._saved_bytes_per_slot * int(batch.capacity))
            sb.close()
            return out

        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            if not calibrated:
                calibrated = self._calibrate(ctx, batch,
                                             state["offset"], m)
            sb = SpillableBatch(batch, SpillPriority.ACTIVE_ON_DECK)
            for out in with_retry(
                    sb, run_one,
                    split_policy=split_spillable_in_half_by_rows):
                if out is not None:
                    yield out
        if used_flags:
            pb = m.setdefault("pallasBatches",
                              Metric("pallasBatches", Metric.DEBUG))
            pb.add(sum(int(u) for u in used_flags))


class FusedHashJoinExec(TpuExec):
    """A planner-fused hash join plus its probe-side suffix chain
    (fusion v2, shape (a): device-side hash-join fusion).

    Wraps the ORIGINAL join node — ``children = [join]``, so every
    tree walk (exchange-consumer counting, the adaptive stage
    collector's parent checks, pipeline insertion) sees the join and
    its exchanges unchanged — and arms it (``join._fusion = self``) so
    the join's per-pair program is swapped for one jitted program
    running build+probe join, column reorder, and the absorbed
    filter/project/partial-agg suffix. Everything ELSE the join does
    stays in the join: broadcast demotion, skew splits,
    sub-partitioning, bloom prefilter, DPP and the capacity-growth
    retry contract all apply unchanged, which is what keeps fusion
    composable with every plan/adaptive.py decision — the decisions
    re-evaluate at execute time, after any adaptive rewrite, never
    before.

    OOM handling mirrors FusedPipelineExec: each probe batch runs
    under ``with_retry`` with the halve-by-rows split policy (sound
    for every supported join type — the probe is the preserved side,
    so probe-row chunks join independently). Donation: the probe batch
    is donated only on a capacity-measured relaunch, where the
    reported total makes the launch provably final and the batch
    provably dead (a first launch may overflow and need the probe
    again).
    """

    def __init__(self, join: TpuExec, suffix: List[TpuExec],
                 use_pallas: bool = False, pallas_max_cap: int = 1 << 24,
                 donate: bool = False):
        super().__init__(join)
        from .aggregate import HashAggregateExec
        from .basic import FilterExec, ProjectExec
        from .join import LEFT_ANTI, LEFT_SEMI
        self.join = join
        self.suffix = list(suffix)
        terminal = self.suffix[-1]
        self._agg = terminal if isinstance(terminal, HashAggregateExec) \
            else None
        self._use_pallas = bool(use_pallas and self._agg is not None)
        self._schema = list(terminal.output_schema)
        specs = []
        for st in self.suffix:
            if isinstance(st, FilterExec):
                specs.append(("filter", st.condition))
            elif isinstance(st, ProjectExec):
                specs.append(("project", tuple(st.exprs),
                              tuple(n for n, _ in st.output_schema)))
            else:
                specs.append(("agg", self._use_pallas,
                              int(pallas_max_cap)) +
                             tuple(tuple(getattr(st, f))
                                   for f in _AGG_FIELDS))
        self._suffix_specs = tuple(specs)
        reorder = not (join.build_side == "right"
                       or join.join_type in (LEFT_SEMI, LEFT_ANTI))
        self._reorder_n = len(join.children[1].output_schema) \
            if reorder else None
        self.donate = bool(donate) and jax.default_backend() != "cpu"
        self._fn_cache = {}
        # bytes an unfused plan would materialize per capacity slot at
        # the join output and every internal suffix boundary
        self._saved_bytes_per_slot = (
            _schema_row_bytes(join.output_schema) +
            sum(_schema_row_bytes(st.output_schema)
                for st in self.suffix[:-1]))
        build_child = join.children[1] if join.build_side == "right" \
            else join.children[0]
        probe_child = join.children[0] if join.build_side == "right" \
            else join.children[1]
        self._label = ("fused-join:%s⋈%s -> %s [%s]" % (
            type(build_child).__name__, type(probe_child).__name__,
            " -> ".join(type(s).__name__ for s in self.suffix),
            join.join_type))
        self._exec_state = None
        join._fusion = self
        FUSION_STATS["chains"] += 1
        FUSION_STATS["stages"] += len(self.suffix) + 1
        FUSION_STATS["joins"] += 1

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def output_partitioning(self):
        return self.suffix[-1].output_partitioning

    def mesh_chain_root(self) -> TpuExec:
        """Unfused terminal of the join + suffix chain (see
        FusedPipelineExec.mesh_chain_root): the suffix nodes keep their
        child links down to the wrapped join, so lowering the terminal
        suffix stage recovers join and suffix inside one stage trace."""
        return self.suffix[-1]

    def node_description(self) -> str:
        tags = []
        if self._use_pallas:
            tags.append("pallas")
        if self.donate:
            tags.append("donate")
        tag = f" ({', '.join(tags)})" if tags else ""
        return (f"FusedHashJoin[{self.join.node_description()} -> "
                + " -> ".join(type(s).__name__ for s in self.suffix)
                + f"]{tag}")

    def _fused_fn(self, out_cap: int, donate: bool):
        key = (out_cap, donate)
        fn = self._fn_cache.get(key)
        if fn is None:
            jit_kwargs = {"donate_argnums": (0,)} if donate else {}
            fn = shared_fn_jit(
                _fused_join_builder, self.join.join_type,
                tuple(self.join._probe_key_exprs),
                tuple(self.join._build_key_exprs),
                out_cap, self._reorder_n, self._suffix_specs,
                **jit_kwargs)
            _annotate(fn, self._label)
            self._fn_cache[key] = fn
        return fn

    # --- execute-time hooks the armed join calls back into ---

    def fused_pairs(self, ctx: ExecContext, probe: ColumnarBatch,
                    build: ColumnarBatch, retries: Metric
                    ) -> Iterator[ColumnarBatch]:
        """One probe batch against one build batch through the fused
        program, with per-batch split-and-retry re-entry (the join's
        _join_batches delegates here when armed)."""
        from ..memory.retry import (split_spillable_in_half_by_rows,
                                    with_retry)
        from ..memory.spill import SpillableBatch, SpillPriority
        st = self._exec_state

        def run_one(psb):
            pb = psb.get()
            out = self._run_pair(ctx, pb, build, retries, st)
            psb.close()
            return out

        sb = SpillableBatch(probe, SpillPriority.ACTIVE_ON_DECK)
        for out in with_retry(
                sb, run_one,
                split_policy=split_spillable_in_half_by_rows):
            if out is not None:
                yield out

    def _run_pair(self, ctx: ExecContext, probe: ColumnarBatch,
                  build: ColumnarBatch, retries: Metric, st):
        from ..columnar.vector import choose_capacity
        from ..conf import JOIN_GROWTH_STEPS
        n_probe = int(probe.num_rows)
        max_steps = ctx.conf.get(JOIN_GROWTH_STEPS)
        out_cap = choose_capacity(max(n_probe, 16))
        measured = False
        total = 0
        for _ in range(max_steps + 1):
            donate = self.donate and measured
            fn = self._fused_fn(out_cap, donate)
            with ctx.semaphore, NvtxTimer(st["fuse_time"], "fused-join"):
                if self._agg is not None:
                    out, rows_in, used, total = fn(
                        probe, build, jnp.int64(st["offset"]))
                else:
                    out, total = fn(probe, build)
            total = int(total)
            if total <= out_cap:
                st["saved"].add(self._saved_bytes_per_slot * out_cap)
                if self._agg is None:
                    return out
                n_in = int(rows_in)
                st["offset"] += n_in
                if n_in == 0:
                    # mirror the unfused partial aggregate: no partial
                    # emitted for a pair that filtered down to nothing
                    return None
                if self._use_pallas:
                    st["used"].append(used)
                return out
            if donate:
                # the measured capacity makes a relaunch overflow a
                # kernel contract violation — and the probe is gone
                raise RuntimeError(
                    "fused join under-reported its output size on a "
                    "donated relaunch")
            retries.add(1)
            out_cap = choose_capacity(total)
            measured = True
        raise RuntimeError(
            f"join expansion {total} exceeded capacity after "
            f"{max_steps} growth steps")

    def suffix_fallback(self, ctx: ExecContext, stream
                        ) -> Iterator[ColumnarBatch]:
        """Empty-build path: the join produced its passthrough /
        null-extend batches eagerly (_empty_result_core), so run the
        suffix through the operators' OWN jitted functions exactly as
        the unfused plan would — same pallas-lane choice, same
        row_offset threading, same empty-batch skips."""
        st = self._exec_state
        grouped_fn = self._agg._grouped_pallas_fn(ctx) \
            if self._use_pallas and self._agg is not None else None
        for batch in stream:
            if int(batch.num_rows) == 0:
                continue
            cur = batch
            emit = True
            for stage in self.suffix:
                if stage is self._agg:
                    n_in = int(cur.num_rows)
                    if n_in == 0:
                        emit = False
                        break
                    with ctx.semaphore:
                        if grouped_fn is not None:
                            cur, used = grouped_fn(
                                cur, jnp.int64(st["offset"]))
                            st["used"].append(used)
                        else:
                            cur = stage._jit_update(
                                cur, jnp.int64(st["offset"]))
                    st["offset"] += n_in
                else:
                    with ctx.semaphore:
                        cur = stage._jit(cur)
            if emit:
                yield cur

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        m = ctx.metrics_for(self.exec_id)
        m.setdefault("fusedOps",
                     Metric("fusedOps", Metric.ESSENTIAL)).set(
            len(self.suffix) + 1)
        self._exec_state = {
            "offset": 0,
            "saved": m.setdefault(
                "fusionBytesSaved",
                Metric("fusionBytesSaved", Metric.ESSENTIAL, "B")),
            "fuse_time": m.setdefault(
                "fusedTime", Metric("fusedTime", Metric.MODERATE, "ns")),
            "used": [],
        }
        try:
            yield from self.children[0].execute(ctx)
        finally:
            st = self._exec_state
            if st is not None and st["used"]:
                pb = m.setdefault("pallasBatches",
                                  Metric("pallasBatches", Metric.DEBUG))
                pb.add(sum(int(u) for u in st["used"]))
            self._exec_state = None
