"""Hash aggregate exec.

Rebuild of GpuHashAggregateExec (GpuAggregateExec.scala:1711; AggHelper
:175; merge iterator :711). Same staged structure as the reference:

  PARTIAL  : per input batch, raw rows -> packed per-group state batch
  (exchange: hash-partition packed partials by the group keys —
   inserted by the planner, GpuShuffleExchangeExecBase role)
  FINAL    : per partition, concat partials, merge states, finalize
  COMPLETE : both phases in one node (single-stage plans)

The kernel is sort-based (ops/kernels.py group_aggregate/group_merge)
rather than cuDF's hash groupby — sorting composes with XLA's static
shapes. Partial results are registered as spillable between the phases,
mirroring the reference's spillable agg buffers; a merge pass too big
for one batch falls back to split-and-retry via the memory framework.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, choose_capacity,
                               live_mask)
from ..expr.aggregates import AggregateFunction
from ..expr.core import Expression, make_result, output_name
from ..jit_registry import shared_fn_jit, shared_method_jit
from ..ops import kernels as K
from .base import ExecContext, Metric, NvtxTimer, Schema, TpuExec


def _key_bucket_split_builder(key_names, num_parts):
    def run(batch, p):
        return K.bucket_compact(
            batch, [batch.column(n) for n in key_names], num_parts, p)
    return run

PARTIAL = "partial"
FINAL = "final"
COMPLETE = "complete"


def _state_col_name(agg_index: int, state_name: str) -> str:
    return f"__agg{agg_index}__{state_name}"


def make_agg_result(data, validity, out_t: dt.DType):
    """Finalized aggregate -> output column. Decimal aggregates with
    128-bit states finalize to (hi, lo) limb tuples, string min/max to
    a StringColumn; everything else is a plain lane array."""
    from ..columnar.nested import ListColumn
    from ..columnar.vector import StringColumn
    if isinstance(data, (StringColumn, ListColumn)):
        return data.with_validity(data.validity & validity)
    if isinstance(data, tuple):
        from ..columnar import decimal128 as d128
        hi, lo = data
        validity = validity & d128.d128_fits_precision(hi, lo,
                                                       out_t.precision)
        return d128.build_decimal_column(hi, lo, validity, out_t)
    return make_result(data, validity, out_t)


class HashAggregateExec(TpuExec):
    """groupBy(keys).agg(fns) over the child stream.

    ``agg_exprs``: [(AggregateFunction, output_name)]. Aggregate inputs
    are the function's child expressions evaluated against the original
    (pre-partial) input schema. For ``mode=FINAL`` the child produces
    packed partial batches, so the original schema must be supplied via
    ``input_schema``.
    """

    def __init__(self, child: TpuExec, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Tuple[AggregateFunction, str]],
                 mode: str = COMPLETE, input_schema: Optional[Schema] = None):
        super().__init__(child)
        self.mode = mode
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        in_schema = input_schema if input_schema is not None \
            else child.output_schema
        self.input_schema = in_schema
        self._key_names = [output_name(e, i)
                           for i, e in enumerate(self.group_exprs)]
        key_schema = [(n, e.data_type(in_schema))
                      for n, e in zip(self._key_names, self.group_exprs)]
        self._result_schema = (
            key_schema +
            [(name, fn.data_type(in_schema))
             for fn, name in self.agg_exprs])
        self._state_schemas = [fn.state_schema(in_schema)
                               for fn, _ in self.agg_exprs]
        self._packed_schema = list(key_schema)
        for i, sschema in enumerate(self._state_schemas):
            for sname, stype in sschema:
                self._packed_schema.append((_state_col_name(i, sname), stype))
        agg_fields = ("group_exprs", "agg_exprs", "_key_names",
                      "_state_schemas", "_result_schema", "_packed_schema")
        from ..expr.misc import contains_eager
        self._eager = contains_eager(
            list(self.group_exprs) + [fn for fn, _ in self.agg_exprs])
        if self._eager:
            # ANSI guards / eager nodes inside keys or aggregate inputs
            # need un-jitted evaluation to raise
            self._jit_update = self._update
            self._jit_merge = self._merge_finalize
        else:
            self._jit_update = shared_method_jit(self, "_update",
                                                 agg_fields)
            self._jit_merge = shared_method_jit(self, "_merge_finalize",
                                                agg_fields)
        self._split_cache = {}
        from . import pallas_agg
        self._pallas_gate = pallas_agg.pallas_eligible(self)
        self._pallas_grouped_gate = pallas_agg.grouped_eligible(self)
        self._pallas_cache = {}

    @property
    def output_schema(self) -> Schema:
        return self._packed_schema if self.mode == PARTIAL \
            else self._result_schema

    def required_child_distributions(self):
        from ..plan.distribution import (AllTuples, ClusteredDistribution,
                                         UnspecifiedDistribution)
        if self.mode != FINAL:
            return [UnspecifiedDistribution()]
        if not self.group_exprs:
            return [AllTuples()]
        from ..expr.core import col
        return [ClusteredDistribution([col(n) for n in self._key_names])]

    @property
    def output_partitioning(self):
        # grouping keys survive both phases under their output names, so
        # the child's partitioning (hash on those names) still holds.
        if self.mode == FINAL and self.group_exprs:
            return self.children[0].output_partitioning
        from ..plan.distribution import SinglePartition, UnknownPartitioning
        if self.mode == FINAL:
            return SinglePartition()
        return UnknownPartitioning(1)

    # --- phase 1: partial aggregation of one raw batch ---
    def _eval_update_inputs(self, batch: ColumnarBatch):
        key_cols = [e.eval(batch) for e in self.group_exprs]
        agg_in = [fn.children[0].eval(batch) if fn.children else None
                  for fn, _ in self.agg_exprs]
        return key_cols, agg_in

    def _update(self, batch: ColumnarBatch, row_offset) -> ColumnarBatch:
        key_cols, agg_in = self._eval_update_inputs(batch)
        key_batch, states = K.group_aggregate(
            batch, key_cols, agg_in, [fn for fn, _ in self.agg_exprs],
            row_offset=row_offset)
        return self._pack(key_batch, states, key_batch.num_rows,
                          batch.capacity)

    def _pack(self, key_batch: ColumnarBatch, states: List[dict],
              num_groups, cap: int) -> ColumnarBatch:
        """Flatten state dicts into columns so partials flow as batches
        (and therefore through spill + shuffle untouched)."""
        cols: List[ColumnVector] = []
        names: List[str] = []
        lm = live_mask(cap, num_groups)
        for kc, name in zip(key_batch.columns, self._key_names):
            cols.append(kc)
            names.append(name)
        from ..columnar.nested import ListColumn
        from ..columnar.vector import StringColumn
        for i, ((fn, _), sschema) in enumerate(
                zip(self.agg_exprs, self._state_schemas)):
            for sname, stype in sschema:
                arr = states[i][sname]
                if isinstance(arr, (StringColumn, ListColumn)):
                    # Column-valued state (string min/max): the column
                    # itself is the buffer; validity carries "seen"
                    cols.append(arr.with_validity(arr.validity & lm))
                elif arr.dtype == jnp.bool_:
                    cols.append(ColumnVector(arr & lm, lm, stype))
                else:
                    data = jnp.where(lm, arr, jnp.zeros((), arr.dtype))
                    cols.append(ColumnVector(data, lm, stype))
                names.append(_state_col_name(i, sname))
        return ColumnarBatch(cols, names, num_groups)

    def _unpack(self, batch: ColumnarBatch):
        from ..columnar.nested import ListColumn
        from ..columnar.vector import StringColumn
        key_cols = [batch.column(n) for n in self._key_names]
        states = []
        for i, sschema in enumerate(self._state_schemas):
            d = {}
            for sname, _ in sschema:
                c = batch.column(_state_col_name(i, sname))
                d[sname] = c if isinstance(c, (StringColumn, ListColumn)) \
                    else c.data
            states.append(d)
        return key_cols, states

    # --- FINAL-merge fusion (fusion v2, planner-armed) ---

    #: list of fused-away upstream ProjectExecs (top-down order) when
    #: plan/overrides.py armed merge fusion on this FINAL aggregate;
    #: None keeps the stock eager-concat + _jit_merge path
    _merge_fusion = None

    def arm_merge_fusion(self, projs) -> None:
        """plan/overrides.py hook: compile this FINAL aggregate's merge
        pass together with the concat of its partition's partials (and
        any projection prefix the planner absorbed) into one jitted
        program (exec/fused.py _fused_merge_builder)."""
        self._merge_fusion = list(projs)
        self._fused_merge_cache = {}
        from .fused import FUSION_STATS
        FUSION_STATS["chains"] += 1
        FUSION_STATS["stages"] += len(projs) + 1
        FUSION_STATS["final_aggs"] += 1

    def _fused_merge_fn(self, cap: int, with_prefix: bool = True):
        from .fused import fused_final_merge_fn
        key = (cap, with_prefix)
        fn = self._fused_merge_cache.get(key)
        if fn is None:
            projs = list(reversed(self._merge_fusion)) \
                if with_prefix else []
            fn = fused_final_merge_fn(self, projs, cap)
            self._fused_merge_cache[key] = fn
        return fn

    def _apply_merge_prefix(self, ctx: ExecContext,
                            batch: ColumnarBatch) -> ColumnarBatch:
        """Fused-away projection prefix applied eagerly — used where
        the merge path must bucket by group key BEFORE merging (the
        re-partition fallback's bucket split reads post-projection key
        columns)."""
        for p in reversed(self._merge_fusion):
            with ctx.semaphore:
                batch = p._jit(batch)
        return batch

    def _run_merge(self, ctx: ExecContext, batches, cap: int,
                   with_prefix: bool = True) -> ColumnarBatch:
        """Merge one held batch list: the fused concat+prefix+merge
        program when armed (argument count bounded by
        srt.exec.fusion.finalAgg.maxMergeInputs — past it an eager
        pre-concat feeds the single-input program), the stock eager
        concat + _jit_merge otherwise. Bit-identical either way: the
        fused program is the literal composition of the same traced
        functions."""
        if self._merge_fusion is None:
            merged_in = (batches[0] if len(batches) == 1
                         else K.concat_batches(batches, cap))
            return self._jit_merge(merged_in)
        from ..conf import FUSION_MERGE_MAX_INPUTS
        if len(batches) > ctx.conf.get(FUSION_MERGE_MAX_INPUTS):
            batches = [K.concat_batches(batches, cap)]
        return self._fused_merge_fn(cap, with_prefix)(*batches)

    # --- phase 2: merge partials + finalize ---
    def _merge_finalize(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols, states = self._unpack(batch)
        key_batch, merged, num_groups = K.group_merge(
            batch, key_cols, states, [fn for fn, _ in self.agg_exprs])
        if not self.group_exprs:
            # Global aggregate: always exactly one output row, even on
            # empty input (Spark semantics: count()=0, sum()=null).
            num_groups = jnp.maximum(num_groups, 1)
        cap = batch.capacity
        lm = live_mask(cap, num_groups)
        out_cols: List[ColumnVector] = [
            kc for kc in key_batch.columns]
        for i, (fn, name) in enumerate(self.agg_exprs):
            data, ok = fn.finalize(merged[i])
            out_cols.append(make_agg_result(
                data, ok & lm,
                self._result_schema[len(self._key_names) + i][1]))
        names = [n for n, _ in self._result_schema]
        return ColumnarBatch(out_cols, names, num_groups)

    # --- grouped pallas lane (one-hot MXU matmul partials) ---
    def _update_pallas(self, batch: ColumnarBatch, row_offset):
        """_update with the grouped pallas lane compiled in: the
        <= 1024-group hash-claim fast case takes the one-hot MXU
        kernel, everything else the stock scatter/sort path — one
        traced program, lax.cond dispatch. Returns (packed, used)."""
        key_cols, agg_in = self._eval_update_inputs(batch)
        key_batch, states, used = K.group_aggregate_pallas(
            batch, key_cols, agg_in, [fn for fn, _ in self.agg_exprs],
            row_offset=row_offset,
            max_capacity=getattr(self, "_pallas_max_cap", 1 << 24))
        return self._pack(key_batch, states, key_batch.num_rows,
                          batch.capacity), used

    def _grouped_pallas_fn(self, ctx: ExecContext):
        """The jitted grouped-lane update, or None (gate miss, either
        pallas conf off, wrong platform, or Mosaic warmup failure).
        srt.sql.pallas.enabled is the master switch owning the
        f32-tile deviation contract; groupedAgg.enabled scopes this
        lane alone."""
        from ..conf import PALLAS_ENABLED, PALLAS_GROUPED_ENABLED
        from . import pallas_agg
        if self._eager or not self._pallas_grouped_gate \
                or not ctx.conf.get(PALLAS_ENABLED) \
                or not ctx.conf.get(PALLAS_GROUPED_ENABLED) \
                or not pallas_agg.grouped_lane_on() \
                or not pallas_agg.grouped_kernel_ok():
            return None
        fn = self._pallas_cache.get("grouped_update")
        if fn is None:
            from ..conf import PALLAS_GROUP_MAX_CAPACITY
            self._pallas_max_cap = int(
                ctx.conf.get(PALLAS_GROUP_MAX_CAPACITY))
            agg_fields = ("group_exprs", "agg_exprs", "_key_names",
                          "_state_schemas", "_result_schema",
                          "_packed_schema", "_pallas_max_cap")
            fn = self._pallas_cache["grouped_update"] = shared_method_jit(
                self, "_update_pallas", agg_fields)
        return fn

    def _partial_stream(self, ctx: ExecContext, agg_time: Metric
                        ) -> Iterator[ColumnarBatch]:
        row_offset = 0
        grouped_fn = self._grouped_pallas_fn(ctx)
        used_flags: List = []
        for batch in self.children[0].execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            with ctx.semaphore, NvtxTimer(agg_time, "agg.update"):
                if grouped_fn is not None:
                    partial, used = grouped_fn(batch,
                                               jnp.int64(row_offset))
                    # no per-batch sync: flags settle with the stream
                    used_flags.append(used)
                else:
                    partial = self._jit_update(batch,
                                               jnp.int64(row_offset))
            row_offset += int(batch.num_rows)
            yield partial
        if used_flags:
            m = ctx.metrics_for(self.exec_id)
            pb = m.setdefault("pallasBatches",
                              Metric("pallasBatches", Metric.DEBUG))
            pb.add(sum(int(u) for u in used_flags))

    def _merge_partition(self, ctx: ExecContext, partials,
                         agg_time: Metric) -> Iterator[ColumnarBatch]:
        """Concat + merge one partition's packed partials; yields one
        batch normally, several when the merge set exceeds
        srt.sql.agg.mergePartitionRows and gets re-partitioned by key
        hash (disjoint key buckets merge independently — the
        reference's re-partition fallback, GpuAggregateExec.scala:711)."""
        from ..conf import AGG_MERGE_PARTITION_ROWS
        from ..memory.retry import with_retry_no_split
        from ..memory.spill import SpillableBatch, SpillPriority
        held: List = []
        total = 0
        try:
            for p in partials:
                if int(p.num_rows) == 0:
                    continue
                total += int(p.num_rows)
                held.append(with_retry_no_split(
                    lambda b=p: SpillableBatch(
                        b, SpillPriority.ACTIVE_ON_DECK)))
            if not held:
                if not self.group_exprs:
                    yield self._empty_global_result()
                return
            threshold = ctx.conf.get(AGG_MERGE_PARTITION_ROWS)
            if total > threshold and self.group_exprs:
                yield from self._repartition_merge(ctx, held, total,
                                                   threshold, agg_time)
                return
            cap = choose_capacity(max(total, 1))

            def merge_all():
                batches = [sb.get() for sb in held]
                with ctx.semaphore, NvtxTimer(agg_time, "agg.merge"):
                    return self._run_merge(ctx, batches, cap)
            # RetryOOM mid-merge: spill + re-run (the merge is a pure
            # function of the held spillables — RmmRapidsRetryIterator
            # withRetryNoSplit contract)
            yield with_retry_no_split(merge_all)
        finally:
            for sb in held:
                sb.close()

    def _split_fn(self, num_parts: int):
        """jit'd group-key hash bucket filter over packed partials
        (ops/kernels.py bucket_compact — same primitive the
        sub-partition join uses)."""
        if num_parts not in self._split_cache:
            self._split_cache[num_parts] = shared_fn_jit(
                _key_bucket_split_builder, list(self._key_names), num_parts)
        return self._split_cache[num_parts]

    def _repack(self, ctx: ExecContext, batch: ColumnarBatch
                ) -> ColumnarBatch:
        """Shrink a compacted bucket to its tight capacity (compact
        keeps the source capacity; without this the fallback would
        inflate the merge set ~P times)."""
        n = int(batch.num_rows)
        cap = choose_capacity(max(n, 8))
        if cap >= batch.capacity:
            return batch
        with ctx.semaphore:
            return K.repack_to(batch, cap)

    def _repartition_merge(self, ctx: ExecContext, held, total: int,
                           threshold: int, agg_time: Metric
                           ) -> Iterator[ColumnarBatch]:
        m = ctx.metrics_for(self.exec_id)
        parts_m = m.setdefault("aggMergePartitions",
                               Metric("aggMergePartitions", Metric.DEBUG))
        P = max(2, -(-total // max(threshold, 1)))
        parts_m.add(P)
        split = self._split_fn(P)
        from ..memory.spill import SpillableBatch, SpillPriority
        # bucket every partial once; buckets spill while waiting
        buckets: List[List[SpillableBatch]] = [[] for _ in range(P)]
        bucket_rows = [0] * P
        try:
            for sb in held:
                batch = sb.get()
                if self._merge_fusion:
                    # the bucket split reads post-projection key
                    # columns, so an absorbed projection prefix must
                    # land before bucketing (merge_bucket then runs the
                    # prefix-free fused program)
                    batch = self._apply_merge_prefix(ctx, batch)
                for p in range(P):
                    with ctx.semaphore:
                        sub = split(batch, jnp.int32(p))
                    n = int(sub.num_rows)
                    if n:
                        sub = self._repack(ctx, sub)
                        bucket_rows[p] += n
                        from ..memory.retry import with_retry_no_split
                        buckets[p].append(with_retry_no_split(
                            lambda b=sub: SpillableBatch(
                                b, SpillPriority.ACTIVE_ON_DECK)))
                sb.close()
            for p in range(P):
                if not buckets[p]:
                    continue
                cap = choose_capacity(bucket_rows[p])

                def merge_bucket(p=p, cap=cap):
                    batches = [b.get() for b in buckets[p]]
                    with ctx.semaphore, NvtxTimer(agg_time,
                                                  "agg.merge"):
                        return self._run_merge(ctx, batches, cap,
                                               with_prefix=False)
                from ..memory.retry import with_retry_no_split
                yield with_retry_no_split(merge_bucket)
                for b in buckets[p]:
                    b.close()
                buckets[p] = []
        finally:
            for bs in buckets:
                for b in bs:
                    b.close()

    def _child_partitions(self, ctx: ExecContext):
        """Child partition streams; with AQE on and an exchange child,
        small reduce partitions group together before the merge
        (CoalesceShufflePartitions over the FINAL aggregate)."""
        from .exchange import ShuffleExchangeExec
        child = self.children[0]
        if not self.preserve_partitioning and \
                isinstance(child, ShuffleExchangeExec):
            # decision delegated to plan/adaptive.py (byte-target aware,
            # cached on the exchange, shared with the eager stage
            # executor); cluster-safe: computed from gathered GLOBAL
            # statistics, so every worker derives the same groups and
            # streams its own contiguous block of them
            from ..plan.adaptive import stage_groups
            groups = stage_groups(ctx, child)
            if groups is not None:
                return child.execute_partition_groups(ctx, groups)
        return child.execute_partitioned(ctx)

    def execute_partitioned(self, ctx: ExecContext):
        """A FINAL grouped aggregate ADVERTISES its child exchange's
        hash partitioning (output_partitioning above), so partition-wise
        consumers (a co-partitioned join) must see one output partition
        per child partition — the default whole-stream yield made the
        advertisement a lie: a join zipping this against a real
        N-partition exchange raised 'partition counts differ' (or worse
        under same-count coalescing). Found by the SF1 run (q11/q74:
        the build side outgrew adaptive broadcast at 3M rows and the
        zip path engaged)."""
        if self.mode != FINAL or not self.group_exprs:
            yield self.execute(ctx)
            return
        m = ctx.metrics_for(self.exec_id)
        agg_time = m.setdefault("aggTime", Metric("aggTime",
                                                  Metric.MODERATE, "ns"))
        for part in self._final_merge_partitions(ctx, agg_time):
            # partitioned consumers bypass execute(): account here
            yield self._measure_stream(ctx, part)

    def _final_merge_partitions(self, ctx: ExecContext, agg_time):
        """One merged output stream per child partition — the single
        source of truth for FINAL grouped merging (both consumption
        paths flatten this)."""
        for part in self._child_partitions(ctx):
            yield self._merge_partition(ctx, part, agg_time)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        m = ctx.metrics_for(self.exec_id)
        agg_time = m.setdefault("aggTime", Metric("aggTime", Metric.MODERATE,
                                                  "ns"))
        if self.mode in (PARTIAL, COMPLETE):
            fused = self._pallas_stream_or_none(ctx, agg_time)
            if fused is not None:
                yield from fused
                return
        if self.mode == PARTIAL:
            yield from self._partial_stream(ctx, agg_time)
            return
        if self.mode == FINAL:
            if self.group_exprs:
                # same loop the partitioned consumers use — but through
                # the UNMEASURED core: execute() wraps this method with
                # the output accounting already
                for part in self._final_merge_partitions(ctx, agg_time):
                    yield from part
                return
            saw_any = False
            for part in self._child_partitions(ctx):
                for out in self._merge_partition(ctx, part, agg_time):
                    saw_any = True
                    yield out
            if not saw_any and \
                    (ctx.cluster is None or ctx.cluster.owns_first()):
                # cluster mode: exactly ONE worker emits the global
                # empty-input row (count()=0, sum()=null)
                yield self._empty_global_result()
            return
        # COMPLETE: partial + merge fused in one stage
        yield from self._merge_partition(
            ctx, self._partial_stream(ctx, agg_time), agg_time)

    # --- fused pallas path (global aggregates over simple numerics) ---
    def _pallas_stream_or_none(self, ctx: ExecContext, agg_time: Metric):
        """Fused filter+aggregate via ops/pallas_kernels.tile_reduce —
        one HBM pass per batch, no filtered intermediate. None keeps the
        stock XLA path (gate miss, conf off, or warmup lowering
        failure)."""
        from ..conf import PALLAS_ENABLED
        from . import pallas_agg
        if not self._pallas_gate or not ctx.conf.get(PALLAS_ENABLED) \
                or self._pallas_cache.get("failed"):
            return None
        from .basic import CoalesceBatchesExec, FilterExec
        source, pred = self.children[0], None
        node = source
        while isinstance(node, CoalesceBatchesExec):
            node = node.children[0]
        if isinstance(node, FilterExec) and \
                pallas_agg.pred_safe(node.condition, self.input_schema):
            source, pred = node.children[0], node.condition
        key = id(pred)
        entry = self._pallas_cache.get(key)
        if entry is None:
            plan = pallas_agg.build_plan(self, pred)
            fn = jax.jit(plan.batch_fn())
            if not self._pallas_warmup(plan, fn):
                self._pallas_cache["failed"] = True
                return None
            entry = self._pallas_cache[key] = (plan, fn)
        plan, fn = entry

        def stream():
            m = ctx.metrics_for(self.exec_id)
            pb = m.setdefault("pallasBatches",
                              Metric("pallasBatches", Metric.DEBUG))
            totals = plan.init_totals()
            saw = False
            for batch in source.execute(ctx):
                if int(batch.num_rows) == 0:
                    continue
                saw = True
                with ctx.semaphore, NvtxTimer(agg_time, "agg.pallas"):
                    partials = fn(batch)
                plan.combine(totals, partials)
                pb.add(1)
            if not saw:
                if self.mode == COMPLETE:
                    yield self._empty_global_result()
                return
            packed = self._pack(ColumnarBatch([], [], jnp.int32(1)),
                                plan.states(totals), jnp.int32(1), 8)
            if self.mode == PARTIAL:
                yield packed
            else:
                with ctx.semaphore:
                    yield self._jit_merge(packed)
        return stream()

    def _pallas_warmup(self, plan, fn) -> bool:
        """Compile-check the fused kernel on a tiny synthetic batch so a
        Mosaic lowering gap falls back BEFORE the child stream is
        consumed."""
        schema_d = dict(self.input_schema)
        cols, names = [], []
        for n in plan.ref_names:
            t = schema_d[n]
            cols.append(ColumnVector(jnp.zeros(8, t.physical),
                                     jnp.zeros(8, jnp.bool_), t))
            names.append(n)
        for n in getattr(plan, "str_names", ()):
            from ..columnar.vector import StringColumn
            cols.append(StringColumn(jnp.zeros(9, jnp.int32),
                                     jnp.zeros(8, jnp.uint8),
                                     jnp.zeros(8, jnp.bool_),
                                     pad_bucket=8))
            names.append(n)
        try:
            out = fn(ColumnarBatch(cols, names, jnp.int32(0)))
            jax.block_until_ready(out)
            return True
        except Exception:  # pragma: no cover - backend specific
            return False

    def _empty_global_result(self) -> ColumnarBatch:
        cap = 8
        in_schema = self.input_schema
        cols = []
        for i, (fn, name) in enumerate(self.agg_exprs):
            zero_states = {}
            for sname, stype in self._state_schemas[i]:
                if stype == dt.STRING:
                    from ..columnar.vector import StringColumn
                    zero_states[sname] = StringColumn(
                        jnp.zeros(cap + 1, jnp.int32),
                        jnp.zeros(8, jnp.uint8),
                        jnp.zeros(cap, jnp.bool_), pad_bucket=8)
                    continue
                if isinstance(stype, dt.ArrayType):
                    from ..columnar.nested import ListColumn
                    from ..columnar.vector import ColumnVector
                    et = stype.element_type
                    zero_states[sname] = ListColumn(
                        jnp.zeros(cap + 1, jnp.int32),
                        ColumnVector(jnp.zeros(8, et.physical),
                                     jnp.zeros(8, jnp.bool_), et),
                        jnp.ones(cap, jnp.bool_), et)
                    continue
                phys = stype.physical
                zero_states[sname] = jnp.zeros(cap, phys)
            data, ok = fn.finalize(zero_states)
            lm = live_mask(cap, 1)
            cols.append(make_agg_result(data, ok & lm,
                                        fn.data_type(in_schema)))
        return ColumnarBatch(cols, [n for _, n in self.agg_exprs], 1)

    def node_description(self) -> str:
        aggs = ", ".join(f"{fn.name} as {n}" for fn, n in self.agg_exprs)
        keys = ", ".join(self._key_names)
        return (f"HashAggregate[{self.mode}, keys=({keys}), "
                f"aggs=({aggs})]")
