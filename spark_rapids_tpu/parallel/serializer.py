"""Columnar batch wire format (JCudfSerialization equivalent).

Rebuild of GpuColumnarBatchSerializer.scala + the flatbuffers wire
format (sql-plugin/src/main/format/*.fbs, SURVEY §2.7): a
self-describing binary framing for ColumnarBatch so shuffle blocks can
move through host memory, disk, or DCN. Layout:

    magic u32 | version u16 | flags u16 (bit0: zstd)
    num_rows u32 | num_cols u32
    per column: name_len u16 | name utf8 | dtype tag utf8 (u16-len) |
                kind u8 (0=primitive, 1=string)
    payload (possibly zstd-compressed concatenation):
      per column: validity bitmap (ceil(n/8) bytes) then
        primitive: data[:n] raw little-endian lanes
        string:    offsets[:n+1] int32 + chars[:total] uint8

Only LIVE rows serialize (dead padding never crosses the wire) — the
deserializer re-buckets capacity on the receiving side, which also
makes the format independent of either side's capacity choices.
"""

from __future__ import annotations

import struct
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity, round_pow2)

MAGIC = 0x53525442  # "SRTB"
VERSION = 1
FLAG_ZSTD = 1
FLAG_LZ4 = 2  # native codec (native/tputable.cpp slz4_*)


def _dtype_tag(t: dt.DType) -> str:
    if isinstance(t, dt.DecimalType):
        return f"decimal({t.precision},{t.scale})"
    return repr(t) if hasattr(t, "__repr__") else str(t)


def _tag_dtype(tag: str) -> dt.DType:
    if tag.startswith("decimal("):
        p, s = tag[8:-1].split(",")
        return dt.DecimalType(int(p), int(s))
    mapping = {"boolean": dt.BOOL, "tinyint": dt.INT8, "smallint": dt.INT16,
               "int": dt.INT32, "bigint": dt.INT64, "float": dt.FLOAT32,
               "double": dt.FLOAT64, "string": dt.STRING, "date": dt.DATE,
               "timestamp": dt.TIMESTAMP}
    if tag in mapping:
        return mapping[tag]
    raise ValueError(f"unknown dtype tag {tag!r}")


_FALLBACK_LOCK = threading.Lock()
_FALLBACK_WARNED: set = set()  # requested codecs already warned about


def _warn_fallback(requested: str, used: str, err: Exception) -> None:
    with _FALLBACK_LOCK:
        if requested in _FALLBACK_WARNED:
            return
        _FALLBACK_WARNED.add(requested)
    import warnings
    warnings.warn(
        f"srt.shuffle.compression.codec={requested} requested but that "
        f"codec is unavailable here ({err!r}); using {used} for this "
        "process", RuntimeWarning)


def _compress_body(body: bytes, codec: str) -> Tuple[bytes, int]:
    """Compress with the requested codec, falling back (with a
    once-per-process warning) LZ4 -> zstd -> uncompressed when the
    native extension / module is absent. Returns (bytes, flag); the
    flag self-describes the wire bytes, so the receiving side never
    needs to know the sender fell back."""
    last: Optional[Exception] = None
    order = ("lz4", "zstd") if codec == "lz4" else ("zstd", "lz4")
    for attempt in order:
        try:
            if attempt == "lz4":
                from ..native import lz4_compress
                out, flag = lz4_compress(body), FLAG_LZ4
            else:
                import zstandard
                out = zstandard.ZstdCompressor(level=1).compress(body)
                flag = FLAG_ZSTD
        except Exception as e:
            last = e
            continue
        if attempt != codec:
            _warn_fallback(codec, attempt, last)
        return out, flag
    _warn_fallback(codec, "no compression", last)
    return body, 0


def serialize_batch(batch: ColumnarBatch, compress: bool = False,
                    codec: str = "zstd") -> bytes:
    n = int(batch.num_rows)
    flags = 0
    # header and payload build as lists of bytes-like parts joined ONCE
    # at the end — no intermediate io.BytesIO copy of the (potentially
    # large) column data; numpy buffer exports stay zero-copy until the
    # single join
    head: List[bytes] = [struct.pack("<IHHII", MAGIC, VERSION, flags, n,
                                     batch.num_columns)]
    parts: List[bytes] = []
    for name, col in zip(batch.names, batch.columns):
        nb = name.encode("utf-8")
        tag = _dtype_tag(col.dtype).encode("utf-8")
        kind = 1 if isinstance(col, StringColumn) else 0
        head.append(struct.pack("<H", len(nb)))
        head.append(nb)
        head.append(struct.pack("<H", len(tag)))
        head.append(tag)
        head.append(struct.pack("<B", kind))
        validity = np.asarray(col.validity)[:n]
        parts.append(memoryview(
            np.packbits(validity, bitorder="little")).cast("B"))
        if kind == 1:
            offs = np.asarray(col.offsets)[:n + 1].astype("<i4")
            total = int(offs[-1]) if n else 0
            parts.append(memoryview(offs).cast("B"))
            parts.append(memoryview(np.ascontiguousarray(
                np.asarray(col.chars)[:total], dtype="<u1")).cast("B"))
        else:
            data = np.asarray(col.data)[:n]
            parts.append(memoryview(np.ascontiguousarray(
                data, dtype=data.dtype.newbyteorder("<"))).cast("B"))
    body = b"".join(parts)
    raw_len = len(body)
    if compress:
        body, flags = _compress_body(body, codec.lower())
        head[0] = struct.pack("<IHHII", MAGIC, VERSION, flags, n,
                              batch.num_columns)
    head.append(struct.pack("<II", len(body), raw_len))
    head.append(body)
    return b"".join(head)


def deserialize_batch(buf: bytes,
                      capacity: Optional[int] = None) -> ColumnarBatch:
    import jax.numpy as jnp
    view = memoryview(buf)
    magic, version, flags, n, ncols = struct.unpack_from("<IHHII", view, 0)
    if magic != MAGIC:
        raise ValueError("bad shuffle block magic")
    if version != VERSION:
        raise ValueError(f"shuffle block version {version}")
    off = struct.calcsize("<IHHII")
    metas: List[Tuple[str, dt.DType, int]] = []
    for _ in range(ncols):
        (nlen,) = struct.unpack_from("<H", view, off)
        off += 2
        name = bytes(view[off:off + nlen]).decode("utf-8")
        off += nlen
        (tlen,) = struct.unpack_from("<H", view, off)
        off += 2
        tag = bytes(view[off:off + tlen]).decode("utf-8")
        off += tlen
        (kind,) = struct.unpack_from("<B", view, off)
        off += 1
        metas.append((name, _tag_dtype(tag), kind))
    body_len, raw_len = struct.unpack_from("<II", view, off)
    off += 8
    body = bytes(view[off:off + body_len])
    if flags & FLAG_LZ4:
        from ..native import lz4_decompress
        body = lz4_decompress(body, raw_len)
    elif flags & FLAG_ZSTD:
        import zstandard
        body = zstandard.ZstdDecompressor().decompress(body)
    cap = capacity or choose_capacity(max(n, 1))
    pos = 0
    cols = []
    vbytes = (n + 7) // 8
    for name, t, kind in metas:
        validity_bits = np.frombuffer(body, np.uint8, vbytes, pos)
        pos += vbytes
        validity = np.zeros(cap, bool)
        validity[:n] = np.unpackbits(validity_bits,
                                     bitorder="little")[:n].astype(bool)
        if kind == 1:
            offs = np.frombuffer(body, "<i4", n + 1, pos)
            pos += 4 * (n + 1)
            total = int(offs[-1]) if n else 0
            chars = np.frombuffer(body, "<u1", total, pos)
            pos += total
            char_cap = max(round_pow2(max(total, 1), 128), 128)
            chars_full = np.zeros(char_cap, np.uint8)
            chars_full[:total] = chars
            offsets_full = np.zeros(cap + 1, np.int32)
            offsets_full[:n + 1] = offs
            offsets_full[n + 1:] = offs[-1] if n else 0
            lens = (offs[1:] - offs[:-1]) if n else np.zeros(0, np.int32)
            pad = round_pow2(int(lens.max()) if n and len(lens) else 1)
            cols.append(StringColumn(jnp.asarray(offsets_full),
                                     jnp.asarray(chars_full),
                                     jnp.asarray(validity),
                                     pad_bucket=pad))
        else:
            phys = np.dtype(t.physical)
            data = np.frombuffer(body, phys.newbyteorder("<"), n, pos)
            pos += phys.itemsize * n
            full = np.zeros(cap, phys)
            full[:n] = data
            full[:n] = np.where(validity[:n], full[:n],
                                np.zeros(1, phys))
            cols.append(ColumnVector(jnp.asarray(full),
                                     jnp.asarray(validity), t))
    return ColumnarBatch(cols, [m[0] for m in metas], n)
