"""Multi-host runtime: a driver coordinating worker processes that
execute staged plans with a cross-process shuffle.

Rebuild of the reference's distributed runtime seam (SURVEY §5
distributed comm backend; RapidsShuffleHeartbeatManager +
RapidsShuffleServer/Client): Spark provides the driver/executor
process model there, so the plugin only ships the shuffle; HERE the
framework is the engine, so this module provides the missing runtime:

- ``ClusterWorker``: one engine process. Serves its shuffle blocks over
  the TCP transport (parallel/transport.py), executes its share of a
  staged physical plan, and coordinates through the driver's control
  channel (register / shuffle barrier / result).
- ``ClusterDriver``: accepts worker registrations, ships each job as
  (cloudpickled logical plan, conf overrides), releases shuffle
  barriers once every worker's map side is written, and merges ordered
  worker results.

Execution model (one plan, W workers):
- every worker builds the IDENTICAL physical plan from the logical plan
  (apply_overrides is deterministic; workers are fresh processes so
  shuffle ids match),
- non-broadcast file-scan leaves are sharded round-robin by file index;
  leaves under a BroadcastExchange replicate (every worker materializes
  the same build side, the reference's broadcast contract),
- ShuffleExchange map sides write LOCAL blocks, a driver barrier makes
  map outputs visible, and reduce partitions are assigned to workers in
  CONTIGUOUS blocks (so concatenating worker results in id order
  preserves range-partitioned global sort order); reads fetch each
  partition from every peer over the transport,
- final output rows stream back to the driver as pickled pydicts.

Workers run on any reachable host; tests drive the full stack with
subprocess workers on localhost (the reference's own test strategy —
SURVEY §4: no real multi-node cluster anywhere in CI).
"""

from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Tuple

_FRAME = struct.Struct(">I")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_FRAME.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    data = _recv_exact(sock, n)
    return None if data is None else pickle.loads(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class WorkerLost(RuntimeError):
    """A worker process died mid-dialogue (connection closed)."""

    def __init__(self, worker_id: int):
        super().__init__(f"worker {worker_id} lost")
        self.worker_id = worker_id


class ClusterTaskContext:
    """Per-worker execution context handed to the exec layer via
    ExecContext.cluster."""

    def __init__(self, worker_id: int, num_workers: int,
                 peers: List[str], driver_addr: Tuple[str, int]):
        self.worker_id = worker_id
        self.num_workers = num_workers
        self.peers = peers  # shuffle endpoints "host:port", worker order
        self.driver_addr = driver_addr

    def assigned(self, num_partitions: int) -> List[int]:
        """Contiguous block of reduce partitions for this worker."""
        w, W = self.worker_id, self.num_workers
        lo = (num_partitions * w) // W
        hi = (num_partitions * (w + 1)) // W
        return list(range(lo, hi))

    def owns_first(self) -> bool:
        return self.worker_id == 0

    def _timeout(self) -> int:
        from ..conf import CLUSTER_BARRIER_TIMEOUT, active_conf
        return active_conf().get(CLUSTER_BARRIER_TIMEOUT)

    def barrier(self, shuffle_id: int) -> None:
        """Block until every worker's map side for shuffle_id is
        written (driver-released)."""
        if os.environ.get("SRT_CLUSTER_DEBUG"):
            print(f"[w{self.worker_id}] barrier {shuffle_id}",
                  file=sys.stderr, flush=True)
        with socket.create_connection(self.driver_addr,
                                      timeout=self._timeout()) as s:
            _send_msg(s, {"type": "barrier", "shuffle_id": shuffle_id,
                          "worker": self.worker_id})
            reply = _recv_msg(s)
        if not reply or reply.get("type") != "release":
            raise RuntimeError(f"barrier {shuffle_id} failed: {reply!r}")

    def gather(self, key, payload) -> List:
        """All-gather a picklable payload across workers through the
        driver (GpuRangePartitioner.sketch-to-driver role); returns the
        payloads in worker order."""
        if os.environ.get("SRT_CLUSTER_DEBUG"):
            print(f"[w{self.worker_id}] gather {key}",
                  file=sys.stderr, flush=True)
        with socket.create_connection(self.driver_addr,
                                      timeout=self._timeout()) as s:
            _send_msg(s, {"type": "gather", "key": key,
                          "worker": self.worker_id, "payload": payload})
            reply = _recv_msg(s)
        if not reply or reply.get("type") != "gathered":
            raise RuntimeError(f"gather {key} failed: {reply!r}")
        return reply["payloads"]


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _shard_scans(physical, worker_id: int, num_workers: int) -> None:
    """Round-robin file-scan leaves by file index, EXCEPT under
    broadcast exchanges (replicated build sides)."""
    from ..exec.exchange import BroadcastExchangeExec
    from ..io.scan import FileScan

    def walk(node, under_broadcast: bool) -> None:
        from ..io.scan import FileSourceScanExec
        if isinstance(node, FileSourceScanExec) and not under_broadcast:
            scan = node.scan
            mine = [p for i, p in enumerate(scan.paths)
                    if i % num_workers == worker_id]
            sharded = FileScan.__new__(FileScan)
            sharded.__dict__.update(scan.__dict__)
            sharded.paths = mine
            node.scan = sharded
            return
        ub = under_broadcast or isinstance(node, BroadcastExchangeExec)
        for c in node.children:
            walk(c, ub)

    walk(physical, False)


def _worker_has_local_relation(physical, num_workers: int) -> bool:
    """Non-broadcast local relations would duplicate rows W times."""
    from ..exec.exchange import BroadcastExchangeExec
    from ..plan.transitions import HostToDeviceExec

    def walk(node, under_broadcast: bool) -> bool:
        ub = under_broadcast or isinstance(node, BroadcastExchangeExec)
        if not node.children:
            from ..io.scan import FileSourceScanExec
            if not isinstance(node, FileSourceScanExec) and \
                    not ub and num_workers > 1:
                return True
        return any(walk(c, ub) for c in node.children)
    return walk(physical, False)


class ClusterWorker:
    """One engine process: shuffle server + job execution loop."""

    def __init__(self, driver_host: str, driver_port: int,
                 host: str = "127.0.0.1"):
        from ..conf import SrtConf, set_active_conf
        from .shuffle_manager import shuffle_manager
        from .transport import ShuffleBlockServer
        self.driver_addr = (driver_host, driver_port)
        # the transport serves HOST blocks: the process-wide manager
        # must be built in MULTITHREADED (serialize-to-host) mode
        # before anything else instantiates it
        set_active_conf(SrtConf({"srt.shuffle.mode": "MULTITHREADED"}))
        self.manager = shuffle_manager()
        assert self.manager.mode == "MULTITHREADED", self.manager.mode
        self.server = ShuffleBlockServer(self.manager, host=host)
        self.host = host

    def run_forever(self) -> None:
        """Register, then serve job requests until shutdown."""
        with socket.create_connection(self.driver_addr, timeout=120) as s:
            _send_msg(s, {"type": "register",
                          "shuffle_endpoint": self.server.endpoint})
            while True:
                msg = _recv_msg(s)
                if msg is None or msg["type"] == "shutdown":
                    return
                if msg["type"] == "reset":
                    # failed-attempt cleanup before a retry: drop every
                    # shuffle's blocks (stale state must not leak into
                    # the re-run)
                    for sid in list(self.manager._registered):
                        self.manager.unregister_shuffle(sid)
                    _send_msg(s, {"type": "reset_done"})
                elif msg["type"] == "job":
                    try:
                        rows, metrics = self._run_job(msg)
                        _send_msg(s, {"type": "result", "rows": rows,
                                      "metrics": metrics})
                    except BaseException as e:  # surface to driver
                        import traceback
                        _send_msg(s, {"type": "error",
                                      "error": f"{e}\n"
                                      f"{traceback.format_exc()}"})

    def _run_job(self, msg) -> List[dict]:
        from ..conf import SrtConf, set_active_conf
        from ..exec.base import ExecContext
        from ..plan import overrides
        from ..plan.host_table import batch_to_table, to_pydict
        logical = pickle.loads(msg["plan"])
        settings = dict(msg["conf"])
        settings["srt.shuffle.mode"] = "MULTITHREADED"
        conf = SrtConf(settings)
        set_active_conf(conf)
        cluster = ClusterTaskContext(msg["worker_id"], msg["num_workers"],
                                     msg["peers"], self.driver_addr)
        physical = overrides.apply_overrides(logical, conf)
        if _worker_has_local_relation(physical, cluster.num_workers):
            raise RuntimeError(
                "cluster mode shards file scans; non-broadcast local "
                "relations would duplicate (write the input to files)")
        _shard_scans(physical, cluster.worker_id, cluster.num_workers)
        debug = os.environ.get("SRT_CLUSTER_DEBUG")
        if debug:
            print(f"[w{cluster.worker_id}] plan:\n"
                  f"{physical.tree_string()}", file=sys.stderr, flush=True)
        ctx = ExecContext(conf)
        ctx.cluster = cluster
        # distinct per-worker default so monotonically_increasing_id /
        # spark_partition_id stay unique when no exchange streams reduce
        # partitions (exchanges overwrite this with the global reduce id)
        ctx.partition_id = cluster.worker_id
        rows: List[dict] = []
        for batch in physical.execute(ctx):
            if int(batch.num_rows) == 0:
                continue
            d = to_pydict(batch_to_table(batch))
            names = list(d)
            for i in range(len(d[names[0]]) if names else 0):
                rows.append({k: d[k][i] for k in names})
        if debug:
            print(f"[w{cluster.worker_id}] rows={len(rows)}",
                  file=sys.stderr, flush=True)
        metrics = {eid: {m.name: m.value for m in md.values()}
                   for eid, md in ctx.metrics.items()}
        return rows, metrics

    def close(self) -> None:
        self.server.close()


def worker_main(argv=None) -> None:  # pragma: no cover - subprocess body
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--driver", required=True)  # host:port
    args = ap.parse_args(argv)
    host, port = args.driver.rsplit(":", 1)
    w = ClusterWorker(host, int(port))
    try:
        w.run_forever()
    finally:
        w.close()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class ClusterDriver:
    """Coordinates registration, shuffle barriers, and job execution
    across workers."""

    def __init__(self, num_workers: int, host: str = "127.0.0.1",
                 barrier_timeout: float = 120.0):
        self.num_workers = num_workers
        self.barrier_timeout = barrier_timeout
        self._workers: List[Tuple[socket.socket, str]] = []
        self._registered = threading.Event()
        self._barriers: Dict = {}
        self._gathers: Dict = {}
        self._block = threading.Lock()
        self._server = socketserver.ThreadingTCPServer(
            (host, 0), self._make_handler(), bind_and_activate=True)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def _make_handler(driver_self):
        driver = driver_self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                msg = _recv_msg(self.request)
                if not msg:
                    return
                if msg["type"] == "register":
                    with driver._block:
                        driver._workers.append(
                            (self.request, msg["shuffle_endpoint"]))
                        if len(driver._workers) == driver.num_workers:
                            driver._registered.set()
                    # keep the connection open: job dialogue reuses it
                    threading.Event().wait()  # parked; driver drives
                elif msg["type"] == "barrier":
                    driver._barrier(msg["shuffle_id"])
                    _send_msg(self.request, {"type": "release"})
                elif msg["type"] == "gather":
                    payloads = driver._gather(msg["key"], msg["worker"],
                                              msg["payload"])
                    _send_msg(self.request, {"type": "gathered",
                                             "payloads": payloads})
        return Handler

    def _barrier(self, shuffle_id) -> None:
        with self._block:
            b = self._barriers.get(shuffle_id)
            if b is None:
                b = self._barriers[shuffle_id] = threading.Barrier(
                    self.num_workers)
        b.wait(timeout=self.barrier_timeout)

    def _gather(self, key, worker: int, payload) -> List:
        with self._block:
            g = self._gathers.get(key)
            if g is None:
                g = self._gathers[key] = {
                    "data": {},
                    "barrier": threading.Barrier(self.num_workers)}
        g["data"][worker] = payload
        g["barrier"].wait(timeout=self.barrier_timeout)
        return [g["data"].get(w) for w in range(self.num_workers)]

    def wait_for_workers(self, timeout: float = 60.0) -> None:
        if not self._registered.wait(timeout):
            raise TimeoutError(
                f"{len(self._workers)}/{self.num_workers} workers "
                "registered")

    def run(self, logical_plan, conf_settings: Optional[dict] = None,
            max_retries: int = 2) -> List[dict]:
        """Execute one plan across the cluster; returns merged rows in
        worker order (= partition order for sorted plans).

        Failure recovery (SURVEY §5 failure detection / shuffle retry):
        a lost worker aborts the attempt; the driver prunes dead
        workers, breaks any waiting barriers, resets survivors' shuffle
        state, and re-runs the whole job on the surviving set (map
        inputs re-shard automatically because sharding derives from
        worker_id/num_workers). Deterministic worker ERRORS do not
        retry — they reproduce."""
        self.wait_for_workers()
        last: Optional[BaseException] = None
        for _attempt in range(max_retries + 1):
            try:
                return self._run_once(logical_plan, conf_settings)
            except WorkerLost as e:
                last = e
                self._recover()
                if not self._workers:
                    break
        raise RuntimeError(
            f"job failed after worker losses: {last}") from last

    def _run_once(self, logical_plan, conf_settings) -> List[dict]:
        import cloudpickle
        self._barriers.clear()
        self._gathers.clear()
        workers = list(self._workers)
        n = len(workers)
        self.num_workers = n
        peers = [ep for _, ep in workers]
        blob = cloudpickle.dumps(logical_plan)
        for w, (sock, _ep) in enumerate(workers):
            try:
                _send_msg(sock, {"type": "job", "plan": blob,
                                 "conf": dict(conf_settings or {}),
                                 "worker_id": w,
                                 "num_workers": n,
                                 "peers": peers})
            except OSError:
                raise WorkerLost(w)
        results: List[Optional[List[dict]]] = [None] * n
        #: per-worker {exec_id: {metric: value}} of the last successful
        #: job — AQE tests read skew/coalesce counters through this
        worker_metrics: List[dict] = [{} for _ in range(n)]
        for w, (sock, _ep) in enumerate(workers):
            try:
                reply = _recv_msg(sock)
            except OSError:
                reply = None
            if reply is None:
                raise WorkerLost(w)
            if reply["type"] == "error":
                if "barrier" in reply["error"] or \
                        "gather" in reply["error"] or \
                        "peer closed" in reply["error"] or \
                        "refused" in reply["error"]:
                    # collateral of a lost peer, not a plan error
                    raise WorkerLost(w)
                raise RuntimeError(
                    f"worker {w} failed:\n{reply['error']}")
            results[w] = reply["rows"]
            worker_metrics[w] = reply.get("metrics", {})
        # post-job cleanup: peers are done fetching once every worker
        # has returned, so drop all shuffle blocks now — without this a
        # long-lived worker accumulates every past job's map outputs
        # (only the failure path used to reset). Best-effort: the job
        # already succeeded, a worker dying here is the next run's
        # problem.
        for sock, _ep in workers:
            try:
                _send_msg(sock, {"type": "reset"})
                _recv_msg(sock)  # reset_done (keeps protocol in sync)
            except OSError:
                pass
        self.last_metrics = worker_metrics
        out: List[dict] = []
        for rows in results:
            out.extend(rows or [])
        return out

    def _recover(self) -> None:
        """Prune dead workers, unblock stuck barriers, reset
        survivors."""
        for b in self._barriers.values():
            try:
                b.abort()
            except Exception:
                pass
        self._barriers.clear()
        self._gathers.clear()
        alive = []
        for sock, ep in self._workers:
            try:
                _send_msg(sock, {"type": "reset"})
                # drain stale replies of the aborted attempt (a worker
                # stuck at a now-aborted barrier first reports its job
                # error, THEN processes the reset); budget covers a full
                # worker-side barrier timeout plus slack
                sock.settimeout(self.barrier_timeout * 2 + 60)
                try:
                    for _ in range(32):
                        reply = _recv_msg(sock)
                        if reply is None:
                            break
                        if reply.get("type") == "reset_done":
                            alive.append((sock, ep))
                            break
                finally:
                    sock.settimeout(None)
            except OSError:
                pass
        self._workers = alive
        self.num_workers = len(alive)

    def shutdown(self) -> None:
        for sock, _ep in self._workers:
            try:
                _send_msg(sock, {"type": "shutdown"})
            except OSError:
                pass
        self._server.shutdown()
        self._server.server_close()


def launch_local_workers(driver: ClusterDriver, n: int,
                         env: Optional[dict] = None
                         ) -> List[subprocess.Popen]:
    """Spawn n worker processes on this host (the test/SURVEY §4
    topology; production workers run the same module on their hosts)."""
    host, port = driver.address
    procs = []
    base_env = dict(os.environ)
    # local test workers always run the CPU backend: the one real TPU
    # chip cannot be shared by N processes (override via env for real
    # per-host-accelerator deployments)
    base_env["JAX_PLATFORMS"] = "cpu"
    base_env.update(env or {})
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base_env["PYTHONPATH"] = root + os.pathsep + \
        base_env.get("PYTHONPATH", "")
    import tempfile
    for i in range(n):
        # NEVER leave workers on an undrained PIPE: XLA's per-compile
        # cache warnings are large, and a full 64K pipe blocks the
        # worker mid-write (a deadlock that worsens as the compile
        # cache grows). Logs go to files for post-mortem instead.
        log_path = os.path.join(tempfile.gettempdir(),
                                f"srt_worker_{os.getpid()}_{i}.log")
        log_f = open(log_path, "wb")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.parallel.cluster",
             "--driver", f"{host}:{port}"],
            env=base_env, stdout=log_f, stderr=subprocess.STDOUT))
        log_f.close()
    return procs


if __name__ == "__main__":  # pragma: no cover
    worker_main()
