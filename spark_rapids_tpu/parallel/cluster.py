"""Multi-host runtime: a driver coordinating worker processes that
execute staged plans with a cross-process shuffle.

Rebuild of the reference's distributed runtime seam (SURVEY §5
distributed comm backend; RapidsShuffleHeartbeatManager +
RapidsShuffleServer/Client): Spark provides the driver/executor
process model there, so the plugin only ships the shuffle; HERE the
framework is the engine, so this module provides the missing runtime:

- ``ClusterWorker``: one engine process. Serves its shuffle blocks over
  the TCP transport (parallel/transport.py), executes its share of a
  staged physical plan, and coordinates through the driver's control
  channel (register / shuffle barrier / result).
- ``ClusterDriver``: accepts worker registrations, ships each job as
  (cloudpickled logical plan, conf overrides), releases shuffle
  barriers once every worker's map side is written, and merges ordered
  worker results.

Execution model (one plan, W workers):
- every worker builds the IDENTICAL physical plan from the logical plan
  (apply_overrides is deterministic; workers are fresh processes so
  shuffle ids match),
- non-broadcast file-scan leaves are sharded round-robin by file index;
  leaves under a BroadcastExchange replicate (every worker materializes
  the same build side, the reference's broadcast contract),
- ShuffleExchange map sides write LOCAL blocks, a driver barrier makes
  map outputs visible, and reduce partitions are assigned to workers in
  CONTIGUOUS blocks (so concatenating worker results in id order
  preserves range-partitioned global sort order); reads fetch each
  partition from every peer over the transport,
- final output rows stream back to the driver as pickled pydicts.

Fault tolerance (docs/ROBUSTNESS.md has the full contract):
- workers heartbeat the driver's ShuffleHeartbeatManager; silence past
  ``srt.cluster.heartbeatTimeoutSec`` evicts the worker and breaks any
  barrier it would have joined (failure DETECTION, instead of waiting
  out the barrier timeout),
- sharding is by LOGICAL worker id over a fixed modulus: each physical
  worker carries a contiguous ascending segment of logical ids, so a
  dead worker's shard can be re-attached to a survivor without
  reshuffling anyone else's data or breaking global partition order,
- recovery is STAGE-level first: shuffles whose barrier released in the
  failed attempt keep their map outputs — survivors rename the blocks
  under the re-planned exchange's fresh shuffle id and only the dead
  worker's shards re-execute; whole-job retry is the outer last resort.

Workers run on any reachable host; tests drive the full stack with
subprocess workers on localhost (the reference's own test strategy —
SURVEY §4: no real multi-node cluster anywhere in CI).
"""

from __future__ import annotations

import os
import pickle
import select
import socket
import socketserver
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..robustness.faults import FaultDrop, fault_point

_FRAME = struct.Struct(">I")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_FRAME.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    data = _recv_exact(sock, n)
    return None if data is None else pickle.loads(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class WorkerLost(RuntimeError):
    """A worker process died mid-dialogue (connection closed)."""

    def __init__(self, worker_id: int):
        super().__init__(f"worker {worker_id} lost")
        self.worker_id = worker_id


class _DecommissionRequested(BaseException):
    """Raised by the worker's SIGTERM handler to interrupt the IDLE
    control-socket recv (BaseException: must not be swallowed by a
    generic except). Mid-job, the handler only sets the flag — the job
    finishes and replies first."""


class RecoveryTimer:
    """Failure-detection → first-post-recovery-result span. Stamped at
    the moment the driver classifies a failure; ``finish`` observes the
    ``recovery_time_ns`` histogram and emits a RecoveryTimed event —
    the chaos legs' recovery-budget assertion hook."""

    def __init__(self, kind: str):
        self.kind = kind
        self.t0 = time.perf_counter_ns()

    def finish(self, **attrs) -> int:
        dt = time.perf_counter_ns() - self.t0
        from ..obs import events as _events
        from ..obs import registry as _registry
        _registry.observe("recovery_time_ns", dt, "ns")
        _events.emit("RecoveryTimed", kind=self.kind,
                     recovery_time_ns=dt, **attrs)
        return dt


class StageRetryFailed(RuntimeError):
    """A survivor could not satisfy a stage-level retry (its recorded
    job state is gone or from another job) — fall back to whole-job."""

    def __init__(self, worker_id: int, detail: str):
        super().__init__(f"stage retry failed at worker {worker_id}: "
                         f"{detail}")
        self.worker_id = worker_id


class ClusterTaskContext:
    """Per-worker execution context handed to the exec layer via
    ExecContext.cluster.

    ``worker_id``/``num_workers`` are PHYSICAL (this attempt's worker
    list); sharding is by LOGICAL ids over ``shard_mod`` — the worker
    count of the job's first attempt — so a retry can hand a dead
    worker's logical shards to a survivor without moving anyone else's
    data. ``fresh_ids`` are the logical ids this worker newly adopted
    in this attempt: stages feeding a REUSED exchange re-execute only
    those (the survivors' own map outputs were renamed into the new
    shuffle id), while stages feeding a non-reused exchange run all of
    ``logical_ids``.
    """

    def __init__(self, worker_id: int, num_workers: int,
                 peers: List[str], driver_addr: Tuple[str, int],
                 logical_ids: Optional[List[int]] = None,
                 fresh_ids: Optional[List[int]] = None,
                 shard_mod: Optional[int] = None,
                 map_id_base: int = 0, attempt: int = 0,
                 assign: Optional[List[List[int]]] = None,
                 epoch: int = 0):
        self.worker_id = worker_id
        #: incarnation epoch assigned at registration; rides every
        #: barrier/gather frame so the driver can fence a zombie
        #: predecessor after eviction/decommission/rejoin
        self.epoch = epoch
        self.num_workers = num_workers
        self.peers = peers  # shuffle endpoints "host:port", worker order
        self.driver_addr = driver_addr
        self.logical_ids = (sorted(logical_ids) if logical_ids is not None
                            else [worker_id])
        self.fresh_ids = (sorted(fresh_ids) if fresh_ids is not None
                          else list(self.logical_ids))
        self.shard_mod = shard_mod if shard_mod is not None else num_workers
        #: the FULL logical-id assignment of this attempt (one list per
        #: physical worker, same order as ``peers``) — lets the map side
        #: predict which endpoint will read each reduce partition (the
        #: push-based shuffle's routing table)
        self.assign = ([list(a) for a in assign] if assign is not None
                       else [[w] for w in range(num_workers)])
        self.map_id_base = map_id_base
        self.attempt = attempt
        #: shuffle ids (THIS attempt's) whose map outputs were reused
        #: from the previous attempt — gates stage_ids()
        self.reusable_sids: Set[int] = set()
        self.sid_to_pos: Dict[int, int] = {}
        #: range-partition bounds carried over from the previous attempt
        #: (sid -> rows); a reused range exchange must keep its original
        #: bounds or the renamed blocks would disagree with fresh ones
        self._prefilled_bounds: Dict[int, list] = {}
        #: bounds recorded DURING this attempt (aliased into the
        #: worker's _last_job so the next retry can prefill)
        self.bounds_out: Dict[int, list] = {}
        #: speculation callback installed by _run_job:
        #: (pos, unit_lids, map_id_base, live_sid) -> (map_ids, detail)
        #: — builds a re-sharded clone of the stage subtree at plan
        #: position ``pos`` and runs its map phase for the straggler's
        #: logical ids under a disjoint map-id namespace
        self.spec_factory = None

    def lids_csv(self) -> str:
        return ",".join(str(l) for l in self.logical_ids)

    def stage_ids(self, downstream_sid: Optional[int] = None) -> List[int]:
        """Logical shards this worker runs for the plan segment feeding
        ``downstream_sid`` (None/unknown → the full logical set)."""
        if downstream_sid is not None and \
                downstream_sid in self.reusable_sids:
            return self.fresh_ids
        return self.logical_ids

    def assigned(self, num_partitions: int,
                 downstream_sid: Optional[int] = None) -> List[int]:
        """Contiguous reduce partitions for this worker: the union of
        each owned logical id's block. Logical ids are contiguous per
        worker, so the union is one contiguous range and concatenating
        worker results in physical order preserves partition order."""
        out: Set[int] = set()
        for lid in self.stage_ids(downstream_sid):
            lo = (num_partitions * lid) // self.shard_mod
            hi = (num_partitions * (lid + 1)) // self.shard_mod
            out.update(range(lo, hi))
        return sorted(out)

    def partition_owners(self, num_partitions: int) -> Dict[int, str]:
        """reduce partition -> the endpoint expected to READ it, from
        the attempt's full logical-id assignment (same contiguous-range
        arithmetic as ``assigned``). Best-effort by construction: AQE
        may coalesce or skew-split partitions afterwards, so push
        consumers treat a miss as 'pull it instead', never an error."""
        owners: Dict[int, str] = {}
        for w, lids in enumerate(self.assign):
            if w >= len(self.peers):
                break
            for lid in lids:
                lo = (num_partitions * lid) // self.shard_mod
                hi = (num_partitions * (lid + 1)) // self.shard_mod
                for r in range(lo, hi):
                    owners[r] = self.peers[w]
        return owners

    def owns_first(self) -> bool:
        return self.worker_id == 0

    # --- recorded range-partition bounds (stage-retry determinism) ---
    def prefill_bounds(self, shuffle_id: int, rows: list) -> None:
        self._prefilled_bounds[shuffle_id] = rows

    def bounds_for(self, shuffle_id: int) -> Optional[list]:
        return self._prefilled_bounds.get(shuffle_id)

    def record_bounds(self, shuffle_id: int, rows: list) -> None:
        self.bounds_out[shuffle_id] = [tuple(r) for r in rows]

    def _timeout(self) -> int:
        from ..conf import CLUSTER_BARRIER_TIMEOUT, active_conf
        return active_conf().get(CLUSTER_BARRIER_TIMEOUT)

    def barrier(self, shuffle_id: int, pos: int = -1,
                detail: Optional[dict] = None,
                spec_ok: bool = False) -> Optional[dict]:
        """Block until every worker's map side for shuffle_id is
        written (driver-released). ``pos`` is the exchange's stable
        traversal position — the driver's map-output registry records
        completion by position, not by (attempt-fresh) shuffle id.

        ``detail`` is this worker's exact per-(map, reduce)
        (rows, bytes) report, recorded into the driver's map-output
        registry. With speculation enabled the driver may answer
        ``speculate`` instead of ``release``: this worker then runs a
        straggler's shard through ``spec_factory`` under a disjoint
        map-id namespace and re-arrives with the speculative report.
        Returns the driver's winners verdict ({"allowed": {worker:
        (map_ids...)}}) under speculation, else None (no filtering)."""
        fault_point("cluster.barrier",
                    f"attempt={self.attempt};workers={self.lids_csv()};"
                    f"pos={pos};")
        if os.environ.get("SRT_CLUSTER_DEBUG"):
            print(f"[w{self.worker_id}] barrier {shuffle_id} pos={pos}",
                  file=sys.stderr, flush=True)
        spec_on = False
        try:
            from ..conf import ADAPTIVE_SPECULATION_ENABLED, active_conf
            spec_on = bool(active_conf().get(ADAPTIVE_SPECULATION_ENABLED))
        except Exception:
            spec_on = False
        msg: dict = {"type": "barrier", "shuffle_id": shuffle_id,
                     "worker": self.worker_id, "pos": pos,
                     "epoch": self.epoch}
        if detail is not None:
            msg["detail"] = dict(detail)
            msg["map_ids"] = sorted({m for (m, _r) in detail})
        if spec_on:
            msg["speculation"] = True
            msg["spec_ok"] = bool(spec_ok
                                  and self.spec_factory is not None)
            msg["unit"] = list(self.logical_ids)
        while True:
            with socket.create_connection(self.driver_addr,
                                          timeout=self._timeout()) as s:
                _send_msg(s, msg)
                reply = _recv_msg(s)
            if reply and reply.get("type") == "release":
                return reply.get("winners")
            if reply and reply.get("type") == "speculate":
                unit = list(reply.get("unit") or ())
                # disjoint namespace: high bit within this attempt's
                # map-id space, sub-ranged by speculator worker, so a
                # spec map can never collide with a normal map id or
                # another speculator's
                base = self.map_id_base + (1 << 19) + (self.worker_id << 14)
                spec_ids: List[int] = []
                spec_detail: dict = {}
                failed = False
                try:
                    if self.spec_factory is None:
                        raise RuntimeError("no spec_factory installed")
                    spec_ids, spec_detail = self.spec_factory(
                        pos, unit, base, shuffle_id)
                except Exception:
                    # report the failure; the driver must NOT commit an
                    # empty result for the straggler's unit
                    failed = True
                    spec_ids, spec_detail = [], {}
                msg = {"type": "barrier", "shuffle_id": shuffle_id,
                       "worker": self.worker_id, "pos": pos,
                       "epoch": self.epoch,
                       "speculation": True, "spec_report": True,
                       "spec_failed": failed, "unit": unit,
                       "detail": spec_detail,
                       "map_ids": sorted(spec_ids)}
                continue
            raise RuntimeError(
                f"barrier {shuffle_id} failed: {reply!r}")

    def gather(self, key, payload) -> List:
        """All-gather a picklable payload across workers through the
        driver (GpuRangePartitioner.sketch-to-driver role); returns the
        payloads in worker order."""
        if os.environ.get("SRT_CLUSTER_DEBUG"):
            print(f"[w{self.worker_id}] gather {key}",
                  file=sys.stderr, flush=True)
        with socket.create_connection(self.driver_addr,
                                      timeout=self._timeout()) as s:
            _send_msg(s, {"type": "gather", "key": key,
                          "worker": self.worker_id, "payload": payload,
                          "epoch": self.epoch})
            reply = _recv_msg(s)
        if not reply or reply.get("type") != "gathered":
            raise RuntimeError(f"gather {key} failed: {reply!r}")
        return reply["payloads"]

    def resolve_endpoint(self, endpoint: str) -> Optional[str]:
        """Ask the driver's heartbeat registry for the CURRENT endpoint
        of the (live) executor that ever served ``endpoint`` — the
        shuffle fetch failover hook (transport.fetch_all_partitions
        endpoint_resolver). None when that executor is gone."""
        try:
            with socket.create_connection(self.driver_addr,
                                          timeout=10) as s:
                _send_msg(s, {"type": "resolve", "endpoint": endpoint})
                reply = _recv_msg(s)
            if not reply or reply.get("type") != "resolved":
                return None
            return reply.get("endpoint")
        except OSError:
            return None


# ---------------------------------------------------------------------------
# plan annotation (stage positions + downstream-exchange links)
# ---------------------------------------------------------------------------

_MISSING = object()


def _annotate_plan(physical) -> Tuple[Dict[int, int], Set[int]]:
    """Walk the physical plan pre-order, assigning each shuffle
    exchange a stable traversal POSITION (``_cluster_pos``) and
    recording, on every exchange and file scan, the shuffle id of the
    exchange its output feeds (``_downstream_sid`` /
    ``_shard_downstream``; None for the final result segment and under
    broadcasts, which rebuild every attempt).

    Returns ``(sid_to_pos, tainted_sids)``. Pure function of the plan:
    every worker and every attempt derives identical positions, which
    is what lets the driver name stages by position while shuffle ids
    stay fresh per attempt. A subtree SHARED by two different consumer
    exchanges taints both consumers: a fresh-shard-only re-run cannot
    split its output between them, so neither is eligible for reuse.
    """
    from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from ..io.scan import FileSourceScanExec

    sid_to_pos: Dict[int, int] = {}
    tainted: Set[int] = set()
    seen_under: Dict[int, object] = {}  # id(node) -> first downstream sid
    counter = [0]

    def walk(node, downstream: Optional[int]) -> None:
        nid = id(node)
        prev = seen_under.get(nid, _MISSING)
        if prev is not _MISSING:
            if prev != downstream:
                for d in (prev, downstream):
                    if d is not None:
                        tainted.add(d)
            return
        seen_under[nid] = downstream
        if isinstance(node, ShuffleExchangeExec):
            node._cluster_pos = counter[0]
            node._downstream_sid = downstream
            sid_to_pos[node.shuffle_id] = counter[0]
            counter[0] += 1
            for c in node.children:
                walk(c, node.shuffle_id)
            return
        if isinstance(node, BroadcastExchangeExec):
            for c in node.children:
                walk(c, None)
            return
        if isinstance(node, FileSourceScanExec):
            node._shard_downstream = downstream
        for c in node.children:
            walk(c, downstream)

    walk(physical, None)
    return sid_to_pos, tainted


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _shard_scans(physical, worker_id: int, num_workers: int,
                 cluster: Optional[ClusterTaskContext] = None) -> None:
    """Shard file-scan leaves by file index over the logical id set,
    EXCEPT under broadcast exchanges (replicated build sides). With a
    ``cluster`` context the shard set is per-scan: scans feeding a
    REUSED exchange keep only the freshly adopted shards."""
    from ..exec.exchange import BroadcastExchangeExec
    from ..io.scan import FileScan

    done: Set[int] = set()  # shared subtrees: shard each scan once

    def walk(node, under_broadcast: bool) -> None:
        from ..io.scan import FileSourceScanExec
        if isinstance(node, FileSourceScanExec) and not under_broadcast:
            if id(node) in done:
                return
            done.add(id(node))
            if cluster is None:
                ids, mod = {worker_id}, num_workers
            else:
                dsid = getattr(node, "_shard_downstream", None)
                ids = set(cluster.stage_ids(dsid))
                mod = cluster.shard_mod
            scan = node.scan
            mine = [p for i, p in enumerate(scan.paths) if i % mod in ids]
            sharded = FileScan.__new__(FileScan)
            sharded.__dict__.update(scan.__dict__)
            sharded.paths = mine
            node.scan = sharded
            return
        ub = under_broadcast or isinstance(node, BroadcastExchangeExec)
        for c in node.children:
            walk(c, ub)

    walk(physical, False)


def _worker_has_local_relation(physical, num_workers: int) -> bool:
    """Non-broadcast local relations would duplicate rows W times."""
    from ..exec.exchange import BroadcastExchangeExec
    from ..plan.transitions import HostToDeviceExec

    def walk(node, under_broadcast: bool) -> bool:
        ub = under_broadcast or isinstance(node, BroadcastExchangeExec)
        if not node.children:
            from ..io.scan import FileSourceScanExec
            if not isinstance(node, FileSourceScanExec) and \
                    not ub and num_workers > 1:
                return True
        return any(walk(c, ub) for c in node.children)
    return walk(physical, False)


class ClusterWorker:
    """One engine process: shuffle server + job execution loop."""

    def __init__(self, driver_host: str, driver_port: int,
                 host: str = "127.0.0.1"):
        from ..conf import SrtConf, set_active_conf
        from .shuffle_manager import shuffle_manager
        from .transport import ShuffleBlockServer
        self.driver_addr = (driver_host, driver_port)
        # the transport serves HOST blocks: the process-wide manager
        # must be built in MULTITHREADED (serialize-to-host) mode
        # before anything else instantiates it
        set_active_conf(SrtConf({"srt.shuffle.mode": "MULTITHREADED"}))
        self.manager = shuffle_manager()
        assert self.manager.mode == "MULTITHREADED", self.manager.mode
        self.server = ShuffleBlockServer(self.manager, host=host)
        self.host = host
        #: state of the most recent job attempt, kept across failures so
        #: a stage-level retry can rename completed map outputs:
        #: {"token": job_token, "sids": [sid by position],
        #:  "bounds": {sid: bounds_rows}}
        self._last_job: Optional[dict] = None
        # --- graceful decommission state (SIGTERM or driver frame) ---
        self._decommission = threading.Event()
        #: True only while the control thread is blocked in the IDLE
        #: recv — the one place the SIGTERM handler may raise to
        #: interrupt (mid-job it just sets the event; the job replies
        #: first and the loop picks the flag up after)
        self._idle_wait = False
        self._executor_id: Optional[str] = None
        self._epoch = 0
        #: the last job's peer list + own index — the decommission path
        #: computes its ring buddy from these (replicas already live
        #: there under k=2 replication)
        self._last_peers: List[str] = []
        self._last_worker_id = 0

    def _heartbeat_loop(self, executor_id: str, interval: float,
                        stop: threading.Event) -> None:
        """Liveness beats on fresh connections (the control socket is
        owned by the job dialogue). A ``drop`` fault skips one beat; a
        ``delay`` fault models a slow peer; killing this thread (any
        other injected error) models a silently wedged worker."""
        import random
        # ±10% jitter: a fleet of workers started together must not
        # phase-lock their beats into synchronized driver load spikes
        while not stop.wait(interval * random.uniform(0.9, 1.1)):
            try:
                fault_point("cluster.heartbeat",
                            f"executor={executor_id};")
            except FaultDrop:
                continue
            try:
                with socket.create_connection(
                        self.driver_addr,
                        timeout=max(5.0, interval * 2)) as s:
                    _send_msg(s, {"type": "heartbeat",
                                  "executor_id": executor_id,
                                  "endpoint": self.server.endpoint})
                    _recv_msg(s)
            except OSError:
                pass  # driver unreachable; the main loop will notice

    def _on_sigterm(self, signum, frame) -> None:
        self._decommission.set()
        if self._idle_wait:
            raise _DecommissionRequested()

    def _recv_ctl(self, s: socket.socket):
        """Idle control-socket recv, interruptible by SIGTERM: the
        handler's raise (or an already-set flag) converts to a
        synthetic ``decommission`` frame."""
        if self._decommission.is_set():
            return {"type": "decommission", "reason": "sigterm"}
        self._idle_wait = True
        try:
            return _recv_msg(s)
        except _DecommissionRequested:
            return {"type": "decommission", "reason": "sigterm"}
        finally:
            self._idle_wait = False

    def run_forever(self) -> None:
        """Register, then serve job requests until shutdown."""
        from ..conf import DECOMMISSION_ENABLED, active_conf
        if active_conf().get(DECOMMISSION_ENABLED) and \
                threading.current_thread() is threading.main_thread():
            import signal
            try:
                signal.signal(signal.SIGTERM, self._on_sigterm)
            except (ValueError, OSError):
                pass  # exotic embedding: SIGTERM stays default
        stop_hb = threading.Event()
        try:
            with socket.create_connection(self.driver_addr,
                                          timeout=120) as s:
                reg: dict = {"type": "register",
                             "shuffle_endpoint": self.server.endpoint}
                # rejoin: declare which dead incarnation's endpoint
                # this process replaces — the driver re-points block
                # ownership and fences the predecessor's epoch
                prior = os.environ.get("SRT_REJOIN_ENDPOINT")
                if prior:
                    reg["prior_endpoint"] = prior
                _send_msg(s, reg)
                msg = _recv_msg(s)
                if isinstance(msg, dict) and \
                        msg.get("type") == "registered":
                    self._executor_id = msg["executor_id"]
                    self._epoch = int(msg.get("epoch", 0))
                    hb = threading.Thread(
                        target=self._heartbeat_loop,
                        args=(msg["executor_id"],
                              float(msg.get("heartbeat_interval", 2.0)),
                              stop_hb),
                        daemon=True)
                    hb.start()
                    msg = self._recv_ctl(s)
                #: control frames the mid-job cancel listener consumed
                #: early — replayed in order once the job has replied,
                #: preserving the pre-listener queue-in-socket semantics
                pending: List[dict] = []
                while True:
                    if msg is None or msg["type"] == "shutdown":
                        return
                    if msg["type"] == "reset":
                        # failed-attempt / post-job cleanup: drop every
                        # shuffle's blocks (stale state must not leak
                        # into the re-run) and forget the job record
                        for sid in list(self.manager._registered):
                            self.manager.unregister_shuffle(sid)
                        # held replicas too: a fresh run's shuffle ids
                        # restart from the same counter, so a stale
                        # replica under a recycled sid must not survive
                        self.manager.replicas.clear()
                        self._last_job = None
                        _send_msg(s, {"type": "reset_done"})
                    elif msg["type"] == "prepare_retry":
                        # stage-level retry probe: report which job's
                        # map outputs this worker still holds — NO
                        # blocks are dropped (that is the whole point)
                        token = (self._last_job or {}).get("token")
                        _send_msg(s, {"type": "retry_ready",
                                      "token": token})
                    elif msg["type"] == "cancel":
                        # stale cancel: the job it targeted already
                        # replied (the broadcast raced our result) —
                        # nothing to do, stay in protocol sync
                        pass
                    elif msg["type"] == "decommission":
                        self._decommission_now(
                            s, msg.get("reason") or "driver request")
                        return
                    elif msg["type"] == "job":
                        alive = self._serve_job(s, msg, pending)
                        if not alive:
                            return
                    msg = (pending.pop(0) if pending
                           else self._recv_ctl(s))
        finally:
            stop_hb.set()

    def _decommission_now(self, s: socket.socket, reason: str) -> None:
        """Graceful exit: stop taking work, drain in-flight pushes,
        migrate this worker's hot shuffle blocks to a live peer (as
        manifest-covered replicas — the same durability path k=2
        replication uses), then deregister. A worker SIGTERM'd mid-job
        lands here only AFTER the job replied, so the driver never
        loses a result to decommission."""
        from ..conf import DECOMMISSION_TIMEOUT_S, active_conf
        deadline = time.monotonic() + active_conf().get(
            DECOMMISSION_TIMEOUT_S)
        # Briefly drain queued control frames: the post-job reset must
        # apply BEFORE migration, or we would ship a finished job's
        # (already-freed-on-the-driver's-books) blocks to the buddy.
        drain_until = time.monotonic() + 1.0
        while time.monotonic() < drain_until:
            readable, _w, _x = select.select([s], [], [], 0.1)
            if not readable:
                continue
            try:
                ctl = _recv_msg(s)
            except OSError:
                break
            if ctl is None:
                break
            if ctl.get("type") == "reset":
                for sid in list(self.manager._registered):
                    self.manager.unregister_shuffle(sid)
                self.manager.replicas.clear()
                self._last_job = None
                try:
                    _send_msg(s, {"type": "reset_done"})
                except OSError:
                    pass
            elif ctl.get("type") == "shutdown":
                return
        # announce: the driver stops assigning this worker jobs and
        # answers with the surviving peer list (migration targets)
        peers: List[str] = []
        try:
            with socket.create_connection(self.driver_addr,
                                          timeout=10) as c:
                _send_msg(c, {"type": "decommission_request",
                              "executor_id": self._executor_id,
                              "endpoint": self.server.endpoint})
                reply = _recv_msg(c)
            if isinstance(reply, dict):
                peers = list(reply.get("peers") or ())
        except OSError:
            pass  # driver gone: nothing to migrate FOR; exit anyway
        self.manager.drain_pushes()
        own = self.server.endpoint
        candidates = [p for p in peers if p != own]
        target: Optional[str] = None
        if self._last_peers and len(self._last_peers) > 1:
            ring = self._last_peers[(self._last_worker_id + 1)
                                    % len(self._last_peers)]
            if ring in candidates:
                target = ring  # replicas (if any) already live there
        if target is None and candidates:
            target = candidates[0]
        migrated: List[int] = []
        if target is not None:
            migrated = self.manager.migrate_blocks(target, deadline)
            self.manager.drain_pushes()
            for sid in migrated:
                self.manager.publish_replica_manifest(
                    sid, target,
                    timeout_s=max(1.0, deadline - time.monotonic()))
        try:
            with socket.create_connection(self.driver_addr,
                                          timeout=10) as c:
                _send_msg(c, {"type": "decommission_done",
                              "executor_id": self._executor_id,
                              "endpoint": own, "reason": reason,
                              "migrated_sids": migrated,
                              "target": target})
                _recv_msg(c)
        except OSError:
            pass

    def _serve_job(self, s: socket.socket, msg,
                   pending: List[dict]) -> bool:
        """Run one job on a side thread while THIS (control) thread
        keeps listening on the driver socket — the only way a cancel
        can reach a busy worker. Mid-job, a ``cancel`` frame (or a
        closed connection: driver gone) flips the job's cancel token
        and the executing thread surfaces QueryCancelled at its next
        check point; any OTHER frame (reset/prepare_retry of an aborted
        attempt) is appended to ``pending`` for the caller to replay
        after the reply, exactly as it would have queued in the socket
        buffer before this listener existed. Returns False when the
        dialogue is over (driver lost / shutdown mid-job)."""
        from ..robustness.admission import QueryContext
        qctx = QueryContext(
            query_id=f"{msg.get('job_token', 'job')}"
                     f"-w{msg.get('worker_id', 0)}")
        reply: List[Optional[dict]] = [None]

        def _job() -> None:
            try:
                rows, metrics = self._run_job(msg, qctx)
                reply[0] = {"type": "result", "rows": rows,
                            "metrics": metrics}
            except BaseException as e:  # surface to driver
                import traceback
                reply[0] = {"type": "error",
                            "error": f"{e}\n{traceback.format_exc()}"}

        jt = threading.Thread(target=_job, daemon=True,
                              name="srt-worker-job")
        jt.start()
        while jt.is_alive():
            readable, _w, _x = select.select([s], [], [], 0.25)
            if not readable:
                continue
            try:
                ctl = _recv_msg(s)
            except OSError:
                ctl = None
            if ctl is None:
                # driver connection lost: abandon the job (nobody is
                # left to receive the result)
                qctx.cancel("driver connection lost")
                jt.join(timeout=30.0)
                return False
            t = ctl.get("type")
            if t == "cancel":
                qctx.cancel(ctl.get("reason") or "driver cancel")
            elif t == "shutdown":
                qctx.cancel("worker shutdown")
                jt.join(timeout=30.0)
                return False
            else:
                # a reset/prepare_retry mid-job means the driver gave
                # up on this attempt: finish fast, reply (the driver
                # drains it), then let the caller replay the frame
                if t == "reset":
                    qctx.cancel("driver reset during job")
                pending.append(ctl)
        jt.join()
        _send_msg(s, reply[0])
        return True

    def _run_job(self, msg, qctx=None) -> Tuple[List[dict], dict]:
        from ..conf import SrtConf, set_active_conf
        from ..exec.base import ExecContext
        from ..plan import overrides
        from ..plan.host_table import batch_to_table, to_pydict
        from ..robustness import faults
        logical = pickle.loads(msg["plan"])
        settings = dict(msg["conf"])
        settings["srt.shuffle.mode"] = "MULTITHREADED"
        conf = SrtConf(settings)
        set_active_conf(conf)
        # cancellation/deadline token: explicit cancels arrive over the
        # control socket (see _serve_job); the DEADLINE propagates
        # through the job conf — srt.sql.queryTimeout ships with every
        # job, so each worker arms its own clock from job start (driver
        # queueing time is not counted against the worker's slice)
        from ..conf import QUERY_TIMEOUT_S
        from ..robustness.admission import QueryContext, set_current_query
        if qctx is None:
            qctx = QueryContext(
                query_id=f"{msg.get('job_token', 'job')}"
                         f"-w{msg.get('worker_id', 0)}")
        qctx.set_timeout(conf.get(QUERY_TIMEOUT_S))
        set_current_query(qctx)
        # arm (or keep, or disarm) this process's fault plan from the
        # job conf — the driver-side test's spec reaches every worker
        faults.arm_from_conf(conf)
        # same hand-off for the event log: srt.eventLog.* in the job
        # conf lights up (or tears down) this worker's JSONL sink,
        # and srt.obs.resource.intervalMs the resource sampler
        from ..obs import events as _events
        from ..obs import resource as _resource
        from ..obs import roofline as _roofline
        _events.configure_from_conf(conf)
        _resource.configure_from_conf(conf)
        # and the roofline layer: worker-side shared-program launches
        # sample into this process's ledger under the job's stride
        _roofline.configure_from_conf(conf)
        # cross-process tracing: rebuild a child tracer from the
        # driver's shipped context so this worker's task/operator spans
        # share the driver's trace_id and parent under its job span
        from ..conf import TRACE_ENABLED
        from ..obs.trace import Tracer
        tracer = (Tracer.from_context(msg.get("trace_ctx"))
                  if conf.get(TRACE_ENABLED) else None)
        attempt = msg.get("attempt", 0)
        logical_ids = msg.get("logical_ids") or [msg["worker_id"]]
        fresh_ids = msg.get("fresh_ids")
        self._last_peers = list(msg["peers"])
        self._last_worker_id = msg["worker_id"]
        cluster = ClusterTaskContext(
            msg["worker_id"], msg["num_workers"], msg["peers"],
            self.driver_addr, logical_ids=logical_ids,
            fresh_ids=fresh_ids if fresh_ids is not None else logical_ids,
            shard_mod=msg.get("shard_mod") or msg["num_workers"],
            map_id_base=msg.get("map_id_base", 0), attempt=attempt,
            assign=msg.get("assign"),
            epoch=int(msg.get("epoch", self._epoch)))
        fault_point("cluster.job",
                    f"attempt={attempt};workers={cluster.lids_csv()};")
        # shuffle ids are allocated during the translation below, and
        # peers must agree on them: seed the counter from the driver's
        # per-attempt base so veterans and late (re)joiners — whose
        # process-lifetime counters have diverged — produce identical
        # ids for the same plan
        sid_base = msg.get("sid_base")
        if sid_base:
            from ..exec.exchange import seed_shuffle_ids
            seed_shuffle_ids(int(sid_base))
        physical = overrides.apply_overrides(logical, conf)
        if _worker_has_local_relation(physical, cluster.num_workers):
            raise RuntimeError(
                "cluster mode shards file scans; non-broadcast local "
                "relations would duplicate (write the input to files)")
        sid_to_pos, tainted = _annotate_plan(physical)
        sids_by_pos = [sid for sid, _pos in
                       sorted(sid_to_pos.items(), key=lambda kv: kv[1])]
        cluster.sid_to_pos = sid_to_pos
        reuse_token = msg.get("reuse_token")
        if reuse_token is not None:
            self._prepare_reuse(msg, cluster, sids_by_pos, tainted,
                                reuse_token)
        else:
            # fresh attempt: stale blocks (a failed attempt the driver
            # chose not to stage-retry) were dropped by "reset"
            self._last_job = None
        # record BEFORE executing: a crash mid-job must leave behind
        # the sid map + bounds that DID complete (bounds_out is aliased,
        # so _compute_bounds fills it in place as the job runs)
        self._last_job = {"token": msg.get("job_token"),
                          "sids": sids_by_pos,
                          "bounds": cluster.bounds_out}
        _shard_scans(physical, cluster.worker_id, cluster.num_workers,
                     cluster)
        cluster.spec_factory = self._make_spec_factory(msg, conf, qctx,
                                                       cluster)
        debug = os.environ.get("SRT_CLUSTER_DEBUG")
        if debug:
            print(f"[w{cluster.worker_id}] plan (lids="
                  f"{cluster.logical_ids} fresh={cluster.fresh_ids} "
                  f"reuse={sorted(cluster.reusable_sids)}):\n"
                  f"{physical.tree_string()}", file=sys.stderr, flush=True)
        ctx = ExecContext(conf, query=qctx)
        ctx.cluster = cluster
        ctx.tracer = tracer
        # distinct per-worker default so monotonically_increasing_id /
        # spark_partition_id stay unique when no exchange streams reduce
        # partitions (exchanges overwrite this with the global reduce id)
        ctx.partition_id = cluster.worker_id
        rows: List[dict] = []
        t0 = time.perf_counter_ns()
        # the task span opens on THIS thread (the one pulling the
        # operator chain), so operator spans parent under it through
        # the tracer's thread-local scope stack
        task_scope = (tracer.span(
            f"task-w{cluster.worker_id}-a{attempt}", kind="task",
            attrs={"worker_id": cluster.worker_id, "attempt": attempt,
                   "logical_ids": list(cluster.logical_ids),
                   "job_token": msg.get("job_token")})
            if tracer is not None else None)
        if task_scope is not None:
            task_scope.__enter__()
        try:
            from ..plan.adaptive import adaptive_execute
            for batch in adaptive_execute(physical, ctx):
                if int(batch.num_rows) == 0:
                    continue
                d = to_pydict(batch_to_table(batch))
                names = list(d)
                for i in range(len(d[names[0]]) if names else 0):
                    rows.append({k: d[k][i] for k in names})
        finally:
            set_current_query(None)
            if task_scope is not None:
                task_scope.__exit__(None, None, None)
            if tracer is not None:
                log_dir = _events.log_dir()
                if log_dir:
                    try:
                        tracer.write_chrome_trace(os.path.join(
                            log_dir,
                            f"trace-{tracer.trace_id}-"
                            f"w{cluster.worker_id}-a{attempt}-"
                            f"{os.getpid()}.json"))
                    except OSError:
                        pass
        wall_ns = time.perf_counter_ns() - t0
        if debug:
            print(f"[w{cluster.worker_id}] rows={len(rows)}",
                  file=sys.stderr, flush=True)
        metrics = {eid: {m.name: m.value for m in md.values()}
                   for eid, md in ctx.metrics.items()}
        from ..obs import registry as _registry
        _registry.observe("task_time_ns", wall_ns, "ns")
        _events.emit("TaskEnd", worker_id=cluster.worker_id,
                     logical_ids=list(cluster.logical_ids),
                     attempt=attempt, rows=len(rows), wall_ns=wall_ns,
                     job_token=msg.get("job_token"), metrics=metrics)
        return rows, metrics

    def _make_spec_factory(self, msg, conf, qctx,
                           cluster: ClusterTaskContext):
        """Speculation callback for ClusterTaskContext.barrier: build a
        FRESH clone of the plan, locate the exchange at the straggler's
        stage position, point it at the live shuffle id, re-shard its
        subtree's scans to the straggler's logical ids, and run the map
        phase under the given disjoint map-id namespace. Returns
        ``(map_ids, detail)`` — the speculative report the worker
        re-arrives at the barrier with."""
        def spec_factory(pos: int, unit_lids: List[int], base: int,
                         live_sid: int):
            from ..exec.base import ExecContext
            from ..exec.exchange import ShuffleExchangeExec
            from ..plan import overrides
            clone = overrides.apply_overrides(pickle.loads(msg["plan"]),
                                              conf)
            _annotate_plan(clone)
            target: List = [None]

            def find(node):
                if target[0] is not None:
                    return
                if isinstance(node, ShuffleExchangeExec) and \
                        getattr(node, "_cluster_pos", -1) == pos:
                    target[0] = node
                    return
                for c in node.children:
                    find(c)

            find(clone)
            ex = target[0]
            if ex is None:
                raise RuntimeError(
                    f"speculation: no exchange at position {pos}")

            def has_nested(node) -> bool:
                return any(isinstance(c, ShuffleExchangeExec)
                           or has_nested(c) for c in node.children)

            if has_nested(ex):
                # a non-leaf stage would need ANOTHER barrier from
                # inside this one — refuse (spec_ok should have gated)
                raise RuntimeError(
                    "speculation: stage has nested exchanges")
            ex.shuffle_id = live_sid
            spec_cluster = ClusterTaskContext(
                cluster.worker_id, cluster.num_workers, cluster.peers,
                cluster.driver_addr, logical_ids=list(unit_lids),
                shard_mod=cluster.shard_mod,
                map_id_base=base, attempt=cluster.attempt,
                assign=cluster.assign)
            _shard_scans(ex, cluster.worker_id, cluster.num_workers,
                         spec_cluster)
            sctx = ExecContext(conf, query=qctx)
            sctx.partition_id = cluster.worker_id
            spec_ids = ex.run_speculative_maps(sctx, base)
            detail = self.manager.map_output_statistics(
                live_sid, map_ids=set(spec_ids)).detail
            return spec_ids, detail
        return spec_factory

    def _prepare_reuse(self, msg, cluster: ClusterTaskContext,
                       sids_by_pos: List[int], tainted: Set[int],
                       reuse_token: str) -> None:
        """Stage-level retry: re-key the previous attempt's completed
        map outputs under this attempt's fresh shuffle ids; drop the
        rest. Raises when this worker's record cannot satisfy the
        driver's request (driver falls back to whole-job retry)."""
        last = self._last_job
        if last is None or last.get("token") != reuse_token or \
                len(last.get("sids") or []) != len(sids_by_pos):
            raise RuntimeError(
                "stage-reuse state unavailable: worker job record "
                f"{(last or {}).get('token')!r} cannot satisfy retry of "
                f"job {reuse_token!r}")
        reusable_positions = set(msg.get("reusable_positions") or [])
        reused: Set[int] = set()
        for pos, new_sid in enumerate(sids_by_pos):
            old_sid = last["sids"][pos]
            if pos in reusable_positions and new_sid not in tainted:
                if self.manager.is_poisoned(old_sid):
                    # a corrupt block was quarantined from this
                    # shuffle: its map outputs are incomplete and must
                    # NOT be reused — fail the stage retry so the
                    # driver's whole-job fallback regenerates them
                    raise RuntimeError(
                        "stage-reuse state unavailable: shuffle "
                        f"{old_sid} quarantined after DataCorruption")
                self.manager.rename_shuffle(old_sid, new_sid)
                reused.add(new_sid)
                old_bounds = last["bounds"].get(old_sid)
                if old_bounds is not None:
                    cluster.prefill_bounds(new_sid, old_bounds)
            else:
                self.manager.unregister_shuffle(old_sid)
        cluster.reusable_sids = reused

    def close(self) -> None:
        self.server.close()


def worker_main(argv=None) -> None:  # pragma: no cover - subprocess body
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--driver", required=True)  # host:port
    args = ap.parse_args(argv)
    host, port = args.driver.rsplit(":", 1)
    w = ClusterWorker(host, int(port))
    try:
        w.run_forever()
    finally:
        w.close()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class ClusterDriver:
    """Coordinates registration, heartbeats, shuffle barriers, and job
    execution across workers."""

    def __init__(self, num_workers: int, host: str = "127.0.0.1",
                 barrier_timeout: float = 120.0,
                 heartbeat_interval: Optional[float] = None,
                 heartbeat_timeout: Optional[float] = None):
        from ..conf import (HEARTBEAT_INTERVAL_S, HEARTBEAT_TIMEOUT_S,
                            active_conf)
        from .shuffle_manager import (MapOutputRegistry,
                                      ShuffleHeartbeatManager)
        conf = active_conf()
        self.num_workers = num_workers
        self.barrier_timeout = barrier_timeout
        self.heartbeat_interval = (
            heartbeat_interval if heartbeat_interval is not None
            else conf.get(HEARTBEAT_INTERVAL_S))
        self.heartbeat_timeout = (
            heartbeat_timeout if heartbeat_timeout is not None
            else conf.get(HEARTBEAT_TIMEOUT_S))
        self._workers: List[Tuple[socket.socket, str, str]] = []
        #: serializes frames on the worker control sockets — a cancel
        #: broadcast from another thread must not interleave with the
        #: job dialogue's own sends mid-frame
        self._ctl_send_lock = threading.Lock()
        self._registered = threading.Event()
        self._barriers: Dict = {}
        self._gathers: Dict = {}
        #: speculation-aware barrier states (condition-based; used only
        #: when the job conf enables srt.sql.adaptive.speculation) —
        #: shuffle_id -> state dict, see _spec_state
        self._spec_barriers: Dict = {}
        #: (slowWorkerFactor, minWaitSec) parsed from the job conf
        self._spec_conf: Tuple[float, float] = (3.0, 1.0)
        #: per-worker-index unit keys (tuple of logical ids) the
        #: current attempt expects at every speculative barrier
        self._expected_units: Optional[List[Tuple[int, ...]]] = None
        #: executor ids in worker-index order for the current attempt
        self._worker_eids: List[str] = []
        self._block = threading.Lock()
        self._exec_seq = 0
        #: executor_id -> incarnation epoch (assigned at registration);
        #: epochs of evicted/decommissioned/superseded incarnations
        #: move to the fence set — their barrier/gather frames are
        #: refused, so a zombie can never commit or serve blocks
        self._epochs: Dict[str, int] = {}
        self._fenced_epochs: Set[int] = set()
        #: executor_id -> Event set when its decommission completes
        self._decommissioned: Dict[str, threading.Event] = {}
        #: per-attempt shuffle-id base shipped with every job: workers
        #: re-seed their local allocator from it, keeping shuffle ids
        #: identical across veterans and late (re)joiners
        self._sid_attempts = 0
        self._heartbeats = ShuffleHeartbeatManager(
            timeout_s=self.heartbeat_timeout)
        self._registry = MapOutputRegistry()
        #: per-failed-attempt assignment record for stage retries:
        #: executor_id -> logical ids it carried in the last attempt
        self._last_assign: Optional[Dict[str, List[int]]] = None
        self._last_shard_mod: Optional[int] = None
        #: what recovery did, in order — tests and operators read this
        #: ({"type": "stage_retry"|"job_retry"|"heartbeat_eviction", ...})
        self.recovery_events: List[dict] = []
        self._stop = threading.Event()
        self._server = socketserver.ThreadingTCPServer(
            (host, 0), self._make_handler(), bind_and_activate=True)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True)
        self._monitor.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def _make_handler(driver_self):
        driver = driver_self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                msg = _recv_msg(self.request)
                if not msg:
                    return
                t = msg.get("type")
                if t == "register":
                    prior = msg.get("prior_endpoint")
                    with driver._block:
                        eid = f"exec-{driver._exec_seq}"
                        epoch = driver._exec_seq + 1
                        driver._exec_seq += 1
                        driver._epochs[eid] = epoch
                        if prior:
                            # rejoin: fence the incarnation that last
                            # served this endpoint and drop its stale
                            # control socket from the worker list
                            old = driver._heartbeats.owner_of(prior)
                            if old is not None and old != eid:
                                driver._fenced_epochs.add(
                                    driver._epochs.get(old, -1))
                            driver._workers = [
                                w for w in driver._workers
                                if w[1] != prior]
                        driver._workers.append(
                            (self.request, msg["shuffle_endpoint"], eid))
                        driver._heartbeats.register(
                            eid, msg["shuffle_endpoint"],
                            prior_endpoint=prior)
                        ready = (len(driver._workers)
                                 >= driver.num_workers)
                    _send_msg(self.request,
                              {"type": "registered", "executor_id": eid,
                               "epoch": epoch,
                               "heartbeat_interval":
                                   driver.heartbeat_interval})
                    if ready:
                        driver._registered.set()
                    # keep the connection open: job dialogue reuses it
                    threading.Event().wait()  # parked; driver drives
                elif t == "barrier":
                    if driver._is_fenced(msg):
                        self._refuse_fenced(msg)
                        return
                    try:
                        # exact map-output sizes ride every barrier
                        # message: the registry's MapOutputStatistics
                        # is fed here regardless of speculation
                        if msg.get("detail"):
                            driver._registry.record_map_stats(
                                msg["shuffle_id"], msg["worker"],
                                msg["detail"])
                        if msg.get("speculation"):
                            reply = driver._barrier_speculative(msg)
                        else:
                            driver._barrier(msg["shuffle_id"],
                                            msg.get("pos", -1))
                            reply = {"type": "release"}
                    except threading.BrokenBarrierError:
                        # aborted by the failure monitor: answer with a
                        # clean error instead of an EOF'd connection
                        _send_msg(self.request,
                                  {"type": "error",
                                   "error": "barrier aborted"})
                        return
                    _send_msg(self.request, reply)
                elif t == "gather":
                    if driver._is_fenced(msg):
                        self._refuse_fenced(msg)
                        return
                    try:
                        payloads = driver._gather(msg["key"],
                                                  msg["worker"],
                                                  msg["payload"])
                    except threading.BrokenBarrierError:
                        _send_msg(self.request,
                                  {"type": "error",
                                   "error": "gather aborted"})
                        return
                    _send_msg(self.request, {"type": "gathered",
                                             "payloads": payloads})
                elif t == "heartbeat":
                    driver._heartbeats.heartbeat(msg["executor_id"],
                                                 msg.get("endpoint"))
                    _send_msg(self.request, {"type": "ok"})
                elif t == "resolve":
                    _send_msg(self.request,
                              {"type": "resolved",
                               "endpoint": driver._heartbeats.resolve(
                                   msg["endpoint"])})
                elif t == "decommission_request":
                    # the worker stops being schedulable NOW; it keeps
                    # heartbeating (and serving fetches) through the
                    # migration window that follows
                    eid = msg.get("executor_id")
                    with driver._block:
                        driver._workers = [w for w in driver._workers
                                           if w[2] != eid]
                        driver.num_workers = len(driver._workers)
                        peers = [ep for _s, ep, _e in driver._workers]
                    _send_msg(self.request,
                              {"type": "ok", "peers": peers})
                elif t == "decommission_done":
                    eid = msg.get("executor_id")
                    with driver._block:
                        driver._fenced_epochs.add(
                            driver._epochs.get(eid, -1))
                    driver._heartbeats.deregister(eid)
                    migrated = list(msg.get("migrated_sids") or ())
                    driver.recovery_events.append(
                        {"type": "decommission", "executor": eid,
                         "migrated_sids": migrated,
                         "target": msg.get("target")})
                    from ..obs import events as _events
                    _events.emit("WorkerDecommissioned", executor=eid,
                                 endpoint=msg.get("endpoint"),
                                 reason=msg.get("reason"),
                                 migrated_sids=migrated,
                                 target=msg.get("target"))
                    driver._decommissioned.setdefault(
                        eid, threading.Event()).set()
                    _send_msg(self.request, {"type": "ok"})

            def _refuse_fenced(self, msg) -> None:
                from ..obs import events as _events
                _events.emit("ZombieFenced", epoch=msg.get("epoch"),
                             mtype=msg.get("type"),
                             worker=msg.get("worker"))
                try:
                    _send_msg(self.request,
                              {"type": "fenced",
                               "error": "fenced: stale incarnation "
                                        "epoch"})
                except OSError:
                    pass
        return Handler

    def _is_fenced(self, msg) -> bool:
        """True when the frame carries a fenced incarnation epoch —
        checked BEFORE any registry write, so a zombie predecessor can
        neither commit map output nor join a sync point. Frames with no
        epoch (older workers) are treated as live."""
        ep = msg.get("epoch")
        return ep is not None and ep in self._fenced_epochs

    def _barrier(self, shuffle_id, pos: int = -1) -> None:
        with self._block:
            b = self._barriers.get(shuffle_id)
            if b is None:
                b = self._barriers[shuffle_id] = threading.Barrier(
                    self.num_workers)
        b.wait(timeout=self.barrier_timeout)
        # barrier released == every worker's map side wrote: record the
        # stage as complete for stage-level retries (by stable position)
        self._registry.mark_complete(pos, shuffle_id)

    # --- speculation-aware barrier (condition-based, early release) ---
    def _spec_state(self, shuffle_id: int) -> dict:
        with self._block:
            st = self._spec_barriers.get(shuffle_id)
            if st is None:
                st = self._spec_barriers[shuffle_id] = {
                    "cond": threading.Condition(),
                    "arrived": {},      # worker -> monotonic arrival t
                    "spec_ok": {},      # worker -> bool
                    "speculating": set(),  # workers given a directive
                    "assigned_units": {},  # unit -> speculator worker
                    "pos": -1,
                    "released": False,
                    "winners": None,
                    "aborted": False,
                }
            return st

    def _expected_unit_list(self) -> List[Tuple[int, ...]]:
        if self._expected_units:
            return list(self._expected_units)
        return [(w,) for w in range(self.num_workers)]

    def _barrier_speculative(self, msg) -> dict:
        """Condition-based replacement for the all-or-nothing barrier,
        used when the job conf enables speculation. Every arrival
        commits its unit's map ids first-result-wins; release happens
        as soon as every expected unit has a committed producer — which
        may be BEFORE a straggler arrives, because a waiting worker can
        be handed a ``speculate`` directive to re-run the straggler's
        shard. The release reply carries the winners verdict that
        filters all reads."""
        sid = msg["shuffle_id"]
        w = msg["worker"]
        pos = msg.get("pos", -1)
        map_ids = list(msg.get("map_ids") or ())
        unit = tuple(msg.get("unit") or ())
        is_spec = bool(msg.get("spec_report"))
        st = self._spec_state(sid)
        cond = st["cond"]
        from ..obs import events as _events
        with cond:
            if st["aborted"]:
                raise threading.BrokenBarrierError()
            if pos >= 0:
                st["pos"] = pos
            if unit and not (is_spec and msg.get("spec_failed")):
                winner = self._registry.try_commit_maps(
                    sid, unit, w, map_ids)
                if is_spec:
                    _events.emit("SpeculativeTask", phase="result",
                                 shuffle_id=sid, unit=list(unit),
                                 speculator=w, won=winner[0] == w)
            if not is_spec:
                st["arrived"][w] = time.monotonic()
                st["spec_ok"][w] = bool(msg.get("spec_ok"))
            self._maybe_release_spec(sid, st)
            deadline = time.monotonic() + self.barrier_timeout
            while not st["released"]:
                if st["aborted"]:
                    raise threading.BrokenBarrierError()
                if not is_spec:
                    directive = self._maybe_speculate(sid, st, w)
                    if directive is not None:
                        return directive
                cond.wait(timeout=0.1)
                if time.monotonic() > deadline:
                    raise threading.BrokenBarrierError()
            winners = st["winners"]
        reply = {"type": "release"}
        if winners is not None:
            reply["winners"] = winners
        return reply

    def _maybe_release_spec(self, sid: int, st: dict) -> None:
        """cond held. Release once every expected unit committed a
        producer; build the winners verdict ({worker: map_ids}). A
        stage where any unit was won by a NON-owner is not marked
        reuse-complete: stage retry renames each worker's LOCAL blocks,
        and a suppressed straggler's store disagrees with the verdict."""
        if st["released"]:
            return
        committed = self._registry.committed_maps(sid)
        expected = self._expected_unit_list()
        if any(u not in committed for u in expected):
            return
        allowed: Dict[int, Tuple[int, ...]] = {
            wi: () for wi in range(self.num_workers)}
        suppressed = False
        for wi, u in enumerate(expected):
            ww, mids = committed[u]
            allowed[ww] = tuple(sorted(set(allowed[ww]) | set(mids)))
            if ww != wi:
                suppressed = True
        st["winners"] = {"allowed": allowed}
        st["released"] = True
        st["cond"].notify_all()
        if not suppressed:
            self._registry.mark_complete(st["pos"], sid)

    def _maybe_speculate(self, sid: int, st: dict,
                         w: int) -> Optional[dict]:
        """cond held; ``w`` is a non-spec arrival still waiting. Hand
        it a speculate directive when (a) it is the earliest-arrived
        eligible waiter, (b) some expected unit has neither arrived nor
        been assigned, (c) that unit's owner is heartbeat-ALIVE (a dead
        owner is the eviction monitor's job, not speculation's), and
        (d) the wait since the last arrival exceeds
        max(minWaitSec, slowWorkerFactor x arrival spread)."""
        if not st["spec_ok"].get(w) or w in st["speculating"]:
            return None
        candidates = [x for x in st["arrived"]
                      if st["spec_ok"].get(x)
                      and x not in st["speculating"]]
        if not candidates or w != min(
                candidates, key=lambda x: st["arrived"][x]):
            return None
        times = list(st["arrived"].values())
        factor, min_wait = self._spec_conf
        spread = (max(times) - min(times)) if len(times) > 1 else 0.0
        if time.monotonic() - max(times) <= max(min_wait,
                                                factor * spread):
            return None
        expected = self._expected_unit_list()
        for wi, unit in enumerate(expected):
            if wi in st["arrived"] or unit in st["assigned_units"]:
                continue
            eid = (self._worker_eids[wi]
                   if wi < len(self._worker_eids) else None)
            if eid is not None and not self._heartbeats.is_alive(eid):
                continue
            st["assigned_units"][unit] = w
            st["speculating"].add(w)
            from ..obs import events as _events
            _events.emit("SpeculativeTask", phase="launch",
                         shuffle_id=sid, unit=list(unit),
                         speculator=w, straggler=wi)
            return {"type": "speculate", "unit": list(unit)}
        return None

    def _gather(self, key, worker: int, payload) -> List:
        with self._block:
            g = self._gathers.get(key)
            if g is None:
                g = self._gathers[key] = {
                    "data": {},
                    "barrier": threading.Barrier(self.num_workers)}
        g["data"][worker] = payload
        g["barrier"].wait(timeout=self.barrier_timeout)
        return [g["data"].get(w) for w in range(self.num_workers)]

    def _abort_sync(self) -> None:
        """Break every waiting barrier/gather (failure path: blocked
        survivors must error out instead of waiting out the timeout)."""
        with self._block:
            barriers = list(self._barriers.values())
            gathers = list(self._gathers.values())
            spec_states = list(self._spec_barriers.values())
        for b in barriers:
            try:
                b.abort()
            except Exception:
                pass
        for g in gathers:
            try:
                g["barrier"].abort()
            except Exception:
                pass
        for st in spec_states:
            try:
                with st["cond"]:
                    st["aborted"] = True
                    st["cond"].notify_all()
            except Exception:
                pass

    def _monitor_loop(self) -> None:
        """Failure DETECTION: evict workers whose heartbeats went
        silent, break the barriers they would have joined, and shut
        their control sockets so the blocked job dialogue surfaces
        WorkerLost instead of waiting out the barrier timeout."""
        period = max(0.2, min(1.0, self.heartbeat_timeout / 4.0))
        while not self._stop.wait(period):
            try:
                dead = self._heartbeats.expire_dead()
            except Exception:
                continue
            if not dead:
                continue
            print(f"[driver] heartbeat loss: evicting {sorted(dead)}",
                  file=sys.stderr, flush=True)
            self.recovery_events.append({"type": "heartbeat_eviction",
                                         "executors": sorted(dead)})
            with self._block:
                for eid in dead:
                    # fence the evicted incarnation: if it was merely
                    # wedged (not dead) and wakes up, its frames must
                    # not corrupt the retry's registry state
                    self._fenced_epochs.add(self._epochs.get(eid, -1))
            from ..obs import events as _events
            _events.emit("WorkerEvicted", executors=sorted(dead))
            self._abort_sync()
            with self._block:
                targets = [s for s, _ep, eid in self._workers
                           if eid in set(dead)]
            for s in targets:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def cancel_job(self, reason: str = "driver cancel") -> None:
        """Broadcast a cancel to every worker's control socket. Workers
        flip their in-flight job's cancel token (see _serve_job); a
        worker that already replied reads the frame as a stale no-op.
        Safe from any thread; best-effort per socket."""
        from ..obs import events as _events
        with self._block:
            targets = list(self._workers)
        _events.emit("ClusterCancelBroadcast", reason=reason,
                     num_workers=len(targets))
        for sock, _ep, _eid in targets:
            try:
                with self._ctl_send_lock:
                    _send_msg(sock, {"type": "cancel", "reason": reason})
            except OSError:
                pass

    def wait_for_workers(self, timeout: float = 60.0) -> None:
        if not self._registered.wait(timeout):
            raise TimeoutError(
                f"{len(self._workers)}/{self.num_workers} workers "
                "registered")

    def run(self, logical_plan, conf_settings: Optional[dict] = None,
            max_retries: int = 2) -> List[dict]:
        """Execute one plan across the cluster; returns merged rows in
        worker order (= partition order for sorted plans).

        Failure recovery (SURVEY §5 failure detection / shuffle retry),
        innermost first:
        1. transport-level: fetch retries with backoff, then endpoint
           failover through the heartbeat registry (transport.py);
        2. STAGE-level: on WorkerLost, shuffles whose barrier released
           keep their map outputs — survivors rename the blocks under
           the retry's fresh shuffle ids, the dead worker's logical
           shards re-execute on a survivor, everything downstream of
           the last completed exchange re-runs;
        3. whole-job: when no stage completed or a survivor lost its
           job record, reset everyone and re-run on the surviving set.
        Deterministic worker ERRORS do not retry — they reproduce."""
        self.wait_for_workers()
        # the driver process logs events too (workers configure
        # themselves from the same conf dict inside _run_job)
        from ..conf import SrtConf
        from ..obs import events as _events
        from ..obs import resource as _resource
        from ..obs.trace import maybe_tracer
        tracer = None
        try:
            dconf = SrtConf(dict(conf_settings or {}))
            _events.configure_from_conf(dconf)
            _resource.configure_from_conf(dconf)
            from ..obs import roofline as _roofline
            _roofline.configure_from_conf(dconf)
            tracer = maybe_tracer(dconf)
        except Exception:
            pass  # an invalid test conf must not mask the real error
        job_token = os.urandom(8).hex()
        # the driver's job span roots the whole distributed trace; its
        # context ships with every job message so worker spans parent
        # under it across the process boundary
        job_span = (tracer.begin(f"job-{job_token}", kind="job",
                                 attrs={"job_token": job_token})
                    if tracer is not None else None)
        trace_ctx = (tracer.context(job_span)
                     if tracer is not None else None)
        try:
            last: Optional[BaseException] = None
            retry_spec: Optional[dict] = None
            rec_timer: Optional[RecoveryTimer] = None
            from ..robustness.admission import QueryInterrupted
            for attempt in range(max_retries + 1):
                try:
                    out = self._run_once(logical_plan, conf_settings,
                                         job_token, attempt, retry_spec,
                                         trace_ctx)
                    if rec_timer is not None:
                        # failure detection → first post-recovery
                        # result: the recovery span chaos legs budget
                        rec_timer.finish(job_token=job_token,
                                         attempt=attempt)
                    return out
                except QueryInterrupted:
                    # typed cancel/deadline — NOT a failure to retry:
                    # stop the rest of the fleet and drain the aborted
                    # dialogue so the next job starts in protocol sync
                    self.cancel_job("peer query interrupted")
                    self._recover()
                    raise
                except StageRetryFailed as e:
                    last = e
                    retry_spec = None
                    if rec_timer is None:
                        rec_timer = RecoveryTimer("job_retry")
                    self.recovery_events.append({"type": "job_retry",
                                                 "cause": str(e)})
                    _events.emit("RetryAttempt", scope="job",
                                 job_token=job_token, attempt=attempt,
                                 cause=str(e))
                    self._recover()
                except WorkerLost as e:
                    last = e
                    retry_spec = self._plan_stage_retry(job_token)
                    if rec_timer is None:
                        rec_timer = RecoveryTimer(
                            "stage_retry" if retry_spec is not None
                            else "job_retry")
                    if retry_spec is not None:
                        _events.emit("RetryAttempt", scope="stage",
                                     job_token=job_token, attempt=attempt,
                                     reused_positions=list(
                                         retry_spec["reusable_positions"]),
                                     cause=str(e))
                    else:
                        self.recovery_events.append({"type": "job_retry",
                                                     "cause": str(e)})
                        _events.emit("RetryAttempt", scope="job",
                                     job_token=job_token, attempt=attempt,
                                     cause=str(e))
                        self._recover()
                if not self._workers:
                    break
            raise RuntimeError(
                f"job failed after worker losses: {last}") from last
        finally:
            if tracer is not None:
                tracer.end(job_span)
                log_dir = _events.log_dir()
                if log_dir:
                    try:
                        tracer.write_chrome_trace(os.path.join(
                            log_dir,
                            f"trace-{tracer.trace_id}-driver-"
                            f"{os.getpid()}.json"))
                    except OSError:
                        pass

    def _run_once(self, logical_plan, conf_settings, job_token: str,
                  attempt: int, retry_spec: Optional[dict],
                  trace_ctx: Optional[dict] = None) -> List[dict]:
        import cloudpickle
        self._registry.start_attempt()
        with self._block:
            self._barriers.clear()
            self._gathers.clear()
            self._spec_barriers.clear()
            workers = list(self._workers)
        try:
            from ..conf import (ADAPTIVE_SPECULATION_FACTOR,
                                ADAPTIVE_SPECULATION_MIN_WAIT_S)
            from ..conf import SrtConf as _SC
            _c = _SC(dict(conf_settings or {}))
            self._spec_conf = (
                float(_c.get(ADAPTIVE_SPECULATION_FACTOR)),
                float(_c.get(ADAPTIVE_SPECULATION_MIN_WAIT_S)))
        except Exception:
            self._spec_conf = (3.0, 1.0)
        n = len(workers)
        self.num_workers = n
        peers = [ep for _s, ep, _e in workers]
        if retry_spec is not None:
            assign = retry_spec["assign"]
            fresh = retry_spec["fresh"]
            shard_mod = retry_spec["shard_mod"]
            reusable = list(retry_spec["reusable_positions"])
            reuse_token: Optional[str] = job_token
        else:
            assign = [[w] for w in range(n)]
            fresh = [list(a) for a in assign]
            shard_mod = n
            reusable = []
            reuse_token = None
        self._last_assign = {eid: list(a) for (_s, _ep, eid), a
                             in zip(workers, assign)}
        self._last_shard_mod = shard_mod
        # the speculative barrier names its per-worker units by the
        # attempt's logical-id assignment (a speculator re-runs a
        # straggler's WHOLE shard set: one worker's maps are one
        # inseparable unit, first full result wins)
        self._expected_units = [tuple(sorted(a)) for a in assign]
        self._worker_eids = [eid for (_s, _ep, eid) in workers]
        from ..obs import events as _events
        _events.emit("StageSubmitted", job_token=job_token,
                     attempt=attempt, num_workers=n, assign=assign,
                     reused_positions=reusable)
        blob = cloudpickle.dumps(logical_plan)
        # 4096 ids of headroom per attempt covers any plan's exchange
        # count plus AQE/speculative re-allocations within the job
        self._sid_attempts += 1
        sid_base = self._sid_attempts * 4096 + 1
        for w, (sock, _ep, _eid) in enumerate(workers):
            try:
                with self._ctl_send_lock:
                    _send_msg(sock, {"type": "job", "plan": blob,
                                     "epoch": self._epochs.get(_eid, 0),
                                     "sid_base": sid_base,
                                     "conf": dict(conf_settings or {}),
                                     "worker_id": w,
                                     "num_workers": n,
                                     "peers": peers,
                                     "job_token": job_token,
                                     "attempt": attempt,
                                     "logical_ids": assign[w],
                                     "fresh_ids": fresh[w],
                                     "assign": assign,
                                     "shard_mod": shard_mod,
                                     "map_id_base": attempt << 20,
                                     "reusable_positions": reusable,
                                     "reuse_token": reuse_token,
                                     "trace_ctx": trace_ctx})
            except OSError:
                raise WorkerLost(w)
        results: List[Optional[List[dict]]] = [None] * n
        #: per-worker {exec_id: {metric: value}} of the last successful
        #: job — AQE tests read skew/coalesce counters through this
        worker_metrics: List[dict] = [{} for _ in range(n)]
        # reply wait is cancel-aware: when the DRIVER thread runs under
        # a query token (session-driven runs), poll it between select
        # ticks — the first trip broadcasts cancel to every worker, then
        # we keep draining their (now typed-error) replies in order
        from ..robustness.admission import (DeadlineExceeded,
                                            QueryCancelled, current_query)
        qc = current_query()
        cancel_sent = False
        for w, (sock, _ep, _eid) in enumerate(workers):
            try:
                if qc is None:
                    reply = _recv_msg(sock)
                else:
                    while True:
                        if not cancel_sent and (qc.is_cancelled()
                                                or qc.expired()):
                            self.cancel_job(qc.cancel_reason
                                            or "deadline exceeded")
                            cancel_sent = True
                        readable, _w2, _x = select.select(
                            [sock], [], [], 0.25)
                        if readable:
                            reply = _recv_msg(sock)
                            break
            except OSError:
                reply = None
            if reply is None:
                raise WorkerLost(w)
            if reply["type"] == "error":
                err = reply["error"]
                if "QueryCancelled" in err or "DeadlineExceeded" in err:
                    # typed interrupt from the worker — NOT a worker
                    # loss, must NOT trigger stage/job retry (a rerun
                    # of a cancelled query is exactly what cancel
                    # forbids); surface the matching driver-side type
                    first = err.splitlines()[0] if err else err
                    cls = (DeadlineExceeded if "DeadlineExceeded" in err
                           else QueryCancelled)
                    raise cls(f"worker {w}: {first}")
                if "stage-reuse state unavailable" in err:
                    raise StageRetryFailed(w, err)
                if "barrier" in err or "gather" in err or \
                        "peer closed" in err or "refused" in err or \
                        "FetchFailed" in err or "DataCorruption" in err:
                    # collateral of a lost peer — or detected data
                    # corruption, which a rerun regenerates — not a
                    # plan error
                    raise WorkerLost(w)
                raise RuntimeError(
                    f"worker {w} failed:\n{err}")
            results[w] = reply["rows"]
            worker_metrics[w] = reply.get("metrics", {})
        # post-job cleanup: peers are done fetching once every worker
        # has returned, so drop all shuffle blocks now — without this a
        # long-lived worker accumulates every past job's map outputs
        # (only the failure path used to reset). Best-effort: the job
        # already succeeded, a worker dying here is the next run's
        # problem.
        for sock, _ep, _eid in workers:
            try:
                with self._ctl_send_lock:
                    _send_msg(sock, {"type": "reset"})
                _recv_msg(sock)  # reset_done (keeps protocol in sync)
            except OSError:
                pass
        self.last_metrics = worker_metrics
        out: List[dict] = []
        for rows in results:
            out.extend(rows or [])
        return out

    def _plan_stage_retry(self, job_token: str) -> Optional[dict]:
        """After WorkerLost: decide whether the next attempt can reuse
        completed stages. Probes every worker with ``prepare_retry``
        (which also drains the failed attempt's stale replies and
        prunes the dead), re-attaches dead logical ids to survivors
        keeping segments contiguous, and returns the retry spec — or
        None when nothing completed / no usable survivor record, in
        which case the caller falls back to whole-job recovery."""
        completed = self._registry.complete_positions()
        self._abort_sync()
        prev_assign = self._last_assign
        alive: List[Tuple[socket.socket, str, str]] = []
        reuse_refused = False
        for sock, ep, eid in self._workers:
            ok = False
            try:
                _send_msg(sock, {"type": "prepare_retry"})
                sock.settimeout(self.barrier_timeout * 2 + 60)
                try:
                    for _ in range(32):
                        reply = _recv_msg(sock)
                        if reply is None:
                            break
                        # a worker that refused the FAILED attempt's
                        # reuse request (quarantined/poisoned shuffle)
                        # may have its refusal sitting in the stale
                        # backlog while the driver classified a peer's
                        # collateral barrier error first — honor it
                        # here, or the driver would re-plan the same
                        # doomed stage retry until attempts run out
                        if reply.get("type") == "error" and \
                                "stage-reuse state unavailable" in \
                                reply.get("error", ""):
                            reuse_refused = True
                        if reply.get("type") == "retry_ready":
                            ok = reply.get("token") == job_token
                            break
                finally:
                    sock.settimeout(None)
            except OSError:
                ok = False
            if ok:
                alive.append((sock, ep, eid))
        self._fence_pruned(alive)
        if not alive:
            self._workers = []
            self.num_workers = 0
            return None
        self._workers = alive
        self.num_workers = len(alive)
        if reuse_refused:
            print("[driver] stage retry unusable: a worker refused map-"
                  "output reuse (quarantined shuffle); falling back to "
                  "whole-job retry", file=sys.stderr, flush=True)
            return None
        if not completed or not prev_assign or \
                any(eid not in prev_assign for _s, _ep, eid in alive):
            return None
        alive_eids = {eid for _s, _ep, eid in alive}
        dead_lids = sorted(l for eid, lids in prev_assign.items()
                           if eid not in alive_eids for l in lids)
        new_assign = {eid: sorted(prev_assign[eid])
                      for _s, _ep, eid in alive}
        for lid in dead_lids:
            # attach to the LAST survivor whose segment starts below the
            # dead id (else the first): all ids between adjacent
            # survivor segments are dead, so this keeps every survivor's
            # logical ids one contiguous ascending run — which is what
            # preserves global partition order on concat
            best = None
            for _s, _ep, eid in alive:
                if min(new_assign[eid]) < lid:
                    best = eid
            if best is None:
                best = alive[0][2]
            new_assign[best].append(lid)
            new_assign[best].sort()
        assign = [list(new_assign[eid]) for _s, _ep, eid in alive]
        fresh = [sorted(set(new_assign[eid]) - set(prev_assign[eid]))
                 for _s, _ep, eid in alive]
        spec = {"assign": assign, "fresh": fresh,
                "shard_mod": self._last_shard_mod,
                "reusable_positions": list(completed)}
        self.recovery_events.append(
            {"type": "stage_retry", "reused_positions": list(completed),
             "assign": assign, "fresh": fresh})
        print(f"[driver] stage-level retry: reusing map outputs at plan "
              f"positions {list(completed)}; re-executing logical shards "
              f"{sorted(dead_lids)} on {len(alive)} surviving workers",
              file=sys.stderr, flush=True)
        return spec

    def _recover(self) -> None:
        """Whole-job fallback: prune dead workers, unblock stuck
        barriers, reset survivors (drops ALL shuffle state)."""
        self._abort_sync()
        with self._block:
            self._barriers.clear()
            self._gathers.clear()
        alive = []
        for sock, ep, eid in self._workers:
            try:
                with self._ctl_send_lock:
                    _send_msg(sock, {"type": "reset"})
                # drain stale replies of the aborted attempt (a worker
                # stuck at a now-aborted barrier first reports its job
                # error, THEN processes the reset); budget covers a full
                # worker-side barrier timeout plus slack
                sock.settimeout(self.barrier_timeout * 2 + 60)
                try:
                    for _ in range(32):
                        reply = _recv_msg(sock)
                        if reply is None:
                            break
                        if reply.get("type") == "reset_done":
                            alive.append((sock, ep, eid))
                            break
                finally:
                    sock.settimeout(None)
            except OSError:
                pass
        self._fence_pruned(alive)
        self._workers = alive
        self.num_workers = len(alive)

    def _fence_pruned(self, alive: List[Tuple[socket.socket, str, str]]
                      ) -> None:
        """Fence every worker about to be dropped from the roster: a
        pruned-but-breathing process (hung, paused, partitioned) must
        not commit into the attempt that replaces it."""
        alive_eids = {eid for _s, _ep, eid in alive}
        pruned = []
        with self._block:
            for _s, _ep, eid in self._workers:
                if eid not in alive_eids:
                    self._fenced_epochs.add(self._epochs.get(eid, -1))
                    pruned.append(eid)
        if pruned:
            # socket-close detection beats the heartbeat monitor when
            # the death happens mid-dialogue; the eviction is just as
            # real, so it gets the same event
            from ..obs import events as _events
            _events.emit("WorkerEvicted", executors=sorted(pruned),
                         detection="socket")

    def decommission(self, executor_id: Optional[str] = None,
                     timeout: float = 60.0) -> bool:
        """Ask one worker (default: the last-registered) to gracefully
        decommission: it finishes any in-flight job, migrates its hot
        shuffle blocks to a live peer, deregisters, and exits. Returns
        True once the worker's ``decommission_done`` lands."""
        with self._block:
            targets = list(self._workers)
        if executor_id is not None:
            targets = [t for t in targets if t[2] == executor_id]
        if not targets:
            return False
        sock, _ep, eid = targets[-1]
        ev = self._decommissioned.setdefault(eid, threading.Event())
        try:
            with self._ctl_send_lock:
                _send_msg(sock, {"type": "decommission",
                                 "reason": "driver request"})
        except OSError:
            return False
        return ev.wait(timeout)

    def wait_for_n_workers(self, n: int, timeout: float = 60.0) -> None:
        """Block until ``n`` workers are registered — the rejoin/elastic
        counterpart of ``wait_for_workers`` (which waits for the
        roster's ORIGINAL size)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._block:
                if len(self._workers) >= n:
                    self.num_workers = len(self._workers)
                    return
                have = len(self._workers)
            if time.monotonic() > deadline:
                raise TimeoutError(f"{have}/{n} workers registered")
            time.sleep(0.05)

    def shutdown(self) -> None:
        self._stop.set()
        for sock, _ep, _eid in self._workers:
            try:
                _send_msg(sock, {"type": "shutdown"})
            except OSError:
                pass
        self._server.shutdown()
        self._server.server_close()


def launch_local_workers(driver: ClusterDriver, n: int,
                         env: Optional[dict] = None
                         ) -> List[subprocess.Popen]:
    """Spawn n worker processes on this host (the test/SURVEY §4
    topology; production workers run the same module on their hosts)."""
    host, port = driver.address
    procs = []
    base_env = dict(os.environ)
    # local test workers always run the CPU backend: the one real TPU
    # chip cannot be shared by N processes (override via env for real
    # per-host-accelerator deployments)
    base_env["JAX_PLATFORMS"] = "cpu"
    base_env.update(env or {})
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base_env["PYTHONPATH"] = root + os.pathsep + \
        base_env.get("PYTHONPATH", "")
    import tempfile
    for i in range(n):
        # NEVER leave workers on an undrained PIPE: XLA's per-compile
        # cache warnings are large, and a full 64K pipe blocks the
        # worker mid-write (a deadlock that worsens as the compile
        # cache grows). Logs go to files for post-mortem instead.
        log_path = os.path.join(tempfile.gettempdir(),
                                f"srt_worker_{os.getpid()}_{i}.log")
        # append: elastic clusters launch replacements from the same
        # driver pid, and truncating would destroy the incumbent's log
        # (it still holds the old fd, so both would interleave into a
        # truncated file)
        log_f = open(log_path, "ab")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.parallel.cluster",
             "--driver", f"{host}:{port}"],
            env=base_env, stdout=log_f, stderr=subprocess.STDOUT))
        log_f.close()
    return procs


if __name__ == "__main__":  # pragma: no cover
    worker_main()
