"""Distributed execution: device meshes, on-device partitioning, and the
SPMD shuffle.

TPU-native replacement for the reference's distributed layer (SURVEY §2.7):
where spark-rapids moves shuffle blocks point-to-point over UCX/RDMA with a
catalog of device-resident buffers, a TPU pod is an SPMD machine — shuffle
is reformulated as a windowed ``all_to_all`` over a ``jax.sharding.Mesh``
riding ICI, with XLA inserting the collectives.
"""

from .mesh import DATA_AXIS, data_mesh, local_mesh
from .partition import (PartitionedBatch, flatten_partitions,
                        hash_partition_ids, partition_batch,
                        round_robin_partition_ids, string_from_padded)
from .shuffle import (all_gather_batch, all_to_all_partitions,
                      distributed_aggregate, shuffle_exchange,
                      stack_shards, unstack_shards)
