"""TCP shuffle block transport (the DCN path).

Rebuild of the reference's shuffle transport stack (SURVEY §2.7:
RapidsShuffleServer.scala:71 / RapidsShuffleClient.scala:90 /
RapidsShuffleIterator): executors serve their local shuffle blocks over
a length-prefixed TCP protocol; remote reads stream a whole reduce
partition's blocks. Within a pod the MESH mode's in-program all-to-all
replaces this entirely; across pods (DCN) — or between plain hosts —
this transport is the fetch path, with the heartbeat registry
(shuffle_manager.ShuffleHeartbeatManager) distributing endpoints.

Wire protocol (all little-endian), five request kinds sharing the
``magic u32 | shuffle_id u32 | reduce_id u32`` prefix:
  fetch v1  ("SRTS"): response: count u32, then per block:
            map_id u32 | length u64 | bytes
  fetch v2  ("SRTF"): request adds n_excl u32 | n_excl x map_id u32 —
            the server serves every block EXCEPT the excluded map ids
            (the reader already holds those from pushed segments);
            response as v1
  push      ("SRTP"): request adds map_id u32 | rows u64 |
            frame_len u64 | origin_len u16 | origin utf8 | frame bytes;
            the receiver verifies the frame and appends it to the
            (shuffle, reduce) segment, then answers one status byte
            (1 = stored, 0 = verification failed, sender may retry)
  replica push  ("SRTQ"): same request as push, but the receiver
            stores the frame in its origin-keyed ReplicaStore (k=2
            map-output durability / decommission migration) instead of
            the consolidated segment — replicas never serve normal
            fetches
  replica fetch ("SRTR"): request adds origin_len u16 | origin utf8 |
            n_excl u32 | n_excl x map_id u32 — serve the replicas held
            HERE for ``origin``'s blocks of this partition; response:
            have u8 (0 = this origin was never replicated here: the
            reader must NOT treat the empty stream as a complete
            partition) | count u32 | blocks as v1
Each block's bytes are the integrity layer's framed checksum envelope
around the serializer's self-describing block format: the server
verifies the stored frame before serving (corrupt-at-rest blocks are
quarantined and the fetch converted into a failure), the client
verifies after receive (wire corruption becomes a retryable error),
and the receiving side then deserializes straight into
capacity-bucketed batches (ShuffleReceivedBufferCatalog role falls to
the caller's manager).
"""

from __future__ import annotations

import random
import socket
import socketserver
import struct
import threading
import time
from typing import (Callable, Dict, FrozenSet, Iterator, List, Optional,
                    Set, Tuple)

from ..columnar.vector import ColumnarBatch
from ..robustness import integrity
from ..robustness.faults import corrupt_point, fault_point
from ..robustness.integrity import DataCorruption
from .serializer import deserialize_batch
from .shuffle_manager import ShuffleManager

MAGIC = 0x53525453        # "SRTS" fetch v1
MAGIC_FETCH2 = 0x53525446  # "SRTF" fetch with exclude list
MAGIC_PUSH = 0x53525450    # "SRTP" push upload
MAGIC_PUSH_REPL = 0x53525451   # "SRTQ" replica push (durability)
MAGIC_FETCH_REPL = 0x53525452  # "SRTR" origin-addressed replica fetch
MAGIC_SERVE = 0x53525456  # "SRTV" SQL serving front door
#                           (serve/protocol.py frames; registered here
#                           so every wire magic lives in one namespace)
#: replica-push map-id sentinel: the frame is a pickled replica
#: MANIFEST ({reduce: (map ids...)}) for (origin, shuffle), published
#: by the origin after its replica pushes drained — the buddy's
#: completeness contract for serving replica fetches
_MANIFEST_MAP_ID = 0xFFFFFFFF
_REQ = struct.Struct("<III")
_BLOCK_HDR = struct.Struct("<IQ")
_PUSH_HDR = struct.Struct("<IQQH")  # map_id | rows | frame_len | origin_len

#: endpoint -> the ShuffleManager served AT that endpoint by a server in
#: THIS process. Lets a reader recognize its own (or a co-resident)
#: endpoint and short-circuit the fetch through the local block store —
#: no socket round trip, no extra copy of the framed bytes.
_LOCAL_ENDPOINTS: Dict[str, ShuffleManager] = {}
_LOCAL_LOCK = threading.Lock()


def local_manager_for(endpoint: str) -> Optional[ShuffleManager]:
    with _LOCAL_LOCK:
        return _LOCAL_ENDPOINTS.get(endpoint)


class FetchFailed(ConnectionError):
    """A reduce-side fetch exhausted its retries (and failover, when a
    resolver was available). Carries the peer endpoint so the driver
    can attribute the loss to a specific worker (Spark's FetchFailed →
    map-stage resubmission signal)."""

    def __init__(self, endpoint: str, shuffle_id: int, reduce_id: int,
                 cause: BaseException):
        super().__init__(
            f"FetchFailed(endpoint={endpoint}, shuffle={shuffle_id}, "
            f"reduce={reduce_id}): {cause}")
        self.endpoint = endpoint
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.cause = cause


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        mgr: ShuffleManager = self.server.manager  # type: ignore
        raw = self._recv_exact(_REQ.size)
        if raw is None:
            return
        magic, shuffle_id, reduce_id = _REQ.unpack(raw)
        # push and replica traffic dispatch BEFORE the fetch path's
        # "transport.serve" fault point: a plan killing pull serves
        # must not take down the very replication that recovery relies
        # on (replica serving has its own transport.serve_replica site)
        if magic in (MAGIC_PUSH, MAGIC_PUSH_REPL):
            self._handle_push(mgr, shuffle_id, reduce_id,
                              replica=(magic == MAGIC_PUSH_REPL))
            return
        if magic == MAGIC_FETCH_REPL:
            self._handle_replica_fetch(mgr, shuffle_id, reduce_id)
            return
        exclude: FrozenSet[int] = frozenset()
        if magic == MAGIC_FETCH2:
            raw = self._recv_exact(4)
            if raw is None:
                return
            (n_excl,) = struct.unpack("<I", raw)
            if n_excl:
                raw = self._recv_exact(4 * n_excl)
                if raw is None:
                    return
                exclude = frozenset(
                    struct.unpack(f"<{n_excl}I", raw))
        elif magic != MAGIC:
            return
        try:
            fault_point("transport.serve",
                        f"sid={shuffle_id};reduce={reduce_id};")
        except ConnectionResetError:
            return  # injected: drop the request before answering
        if mgr.is_poisoned(shuffle_id):
            # quarantined shuffle: abort without answering — serving
            # the surviving blocks would silently drop the lost one;
            # the client's fetch fails definitively and stage rerun /
            # job retry regenerates the whole map output
            return
        blocks = mgr.host_store.blocks_for_reduce(shuffle_id, reduce_id)
        payload = []
        for b in blocks:
            if b[1] in exclude:
                # the reader already consolidated this map's block from
                # a pushed segment — don't re-ship it
                continue
            framed = mgr.host_store.get(b)
            if framed is None:
                continue
            if mgr.verify_checksums:
                try:
                    integrity.verify_framed(
                        framed, what=f"stored shuffle block {b}")
                except DataCorruption as e:
                    # at-rest corruption caught before a single byte is
                    # served: quarantine and drop the connection
                    mgr.quarantine_block(b, reason=str(e))
                    return
            # seeded wire corruption (chaos/tests): mutates the frame
            # in flight, so the CLIENT-side verification must catch it
            # and the refetch must heal (the stored copy is intact)
            framed = corrupt_point(
                "shuffle.block.wire", framed,
                f"sid={shuffle_id};reduce={reduce_id};m={b[1]};")
            payload.append((b[1], framed))
        self.request.sendall(struct.pack("<I", len(payload)))
        for map_id, data in payload:
            try:
                fault_point("transport.serve_block",
                            f"sid={shuffle_id};reduce={reduce_id};"
                            f"m={map_id};")
            except ConnectionResetError:
                # injected mid-frame reset: promise the block, send half
                # the payload, drop the connection — the client observes
                # a peer death mid-block
                self.request.sendall(_BLOCK_HDR.pack(map_id, len(data)))
                self.request.sendall(data[: len(data) // 2])
                return
            self.request.sendall(_BLOCK_HDR.pack(map_id, len(data)))
            self.request.sendall(data)

    def _handle_push(self, mgr: ShuffleManager, shuffle_id: int,
                     reduce_id: int, replica: bool = False) -> None:
        """Receive one eagerly pushed block and consolidate it into the
        (shuffle, reduce) segment — or, for a replica push, into the
        origin-keyed ReplicaStore (k=2 durability / decommission
        migration). The frame verifies BEFORE it is stored — a
        wire-corrupt push is NAKed (status 0) so the origin can resend;
        the origin's copy stays authoritative either way."""
        raw = self._recv_exact(_PUSH_HDR.size)
        if raw is None:
            return
        map_id, rows, frame_len, origin_len = _PUSH_HDR.unpack(raw)
        origin_b = self._recv_exact(origin_len)
        framed = self._recv_exact(frame_len)
        if origin_b is None or framed is None:
            return
        try:
            fault_point("transport.push",
                        f"sid={shuffle_id};reduce={reduce_id};"
                        f"m={map_id};")
        except ConnectionResetError:
            return  # injected: swallow the upload, never ack
        status = 1
        if mgr.verify_checksums:
            try:
                integrity.verify_framed(
                    framed, what=f"pushed shuffle block sid={shuffle_id} "
                                 f"m={map_id} reduce={reduce_id}")
            except DataCorruption:
                status = 0  # corrupted in flight: reject, sender retries
        if status:
            if replica and map_id == _MANIFEST_MAP_ID:
                import pickle
                try:
                    manifest = pickle.loads(integrity.unwrap(
                        framed, what=f"replica manifest "
                                     f"sid={shuffle_id}"))
                    mgr.replicas.put_manifest(origin_b.decode("utf-8"),
                                              shuffle_id, manifest)
                except Exception:
                    status = 0  # corrupt/garbled manifest: NAK
            elif replica:
                mgr.replicas.put(origin_b.decode("utf-8"), shuffle_id,
                                 map_id, reduce_id, framed)
            else:
                mgr.segments.append(shuffle_id, reduce_id,
                                    origin_b.decode("utf-8"), map_id,
                                    rows, framed)
        self.request.sendall(struct.pack("<B", status))

    def _handle_replica_fetch(self, mgr: ShuffleManager,
                              shuffle_id: int, reduce_id: int) -> None:
        """Serve the replicas held HERE for one origin's blocks of one
        reduce partition — the degraded-mode read a peer issues after
        its pull from the origin failed terminally. The ``have`` byte
        distinguishes 'all blocks excluded' (complete) from 'this
        origin was never replicated here' (the reader must fall back
        to stage retry, not treat silence as completeness)."""
        raw = self._recv_exact(2)
        if raw is None:
            return
        (origin_len,) = struct.unpack("<H", raw)
        origin_b = self._recv_exact(origin_len)
        raw = self._recv_exact(4)
        if origin_b is None or raw is None:
            return
        (n_excl,) = struct.unpack("<I", raw)
        exclude: FrozenSet[int] = frozenset()
        if n_excl:
            raw = self._recv_exact(4 * n_excl)
            if raw is None:
                return
            exclude = frozenset(struct.unpack(f"<{n_excl}I", raw))
        try:
            fault_point("transport.serve_replica",
                        f"sid={shuffle_id};reduce={reduce_id};")
        except ConnectionResetError:
            return
        origin = origin_b.decode("utf-8")
        # coverage contract: only a manifest-complete replica set may
        # serve (None = no manifest, or a best-effort push silently
        # dropped a block — the reader must stage-retry, not consume a
        # partial partition as if it were whole)
        complete = mgr.replicas.coverage(origin, shuffle_id, reduce_id)
        if complete is None:
            self.request.sendall(struct.pack("<BI", 0, 0))
            return
        payload = []
        for map_id, framed in complete:
            if map_id in exclude:
                continue
            if mgr.verify_checksums:
                try:
                    integrity.verify_framed(
                        framed,
                        what=f"replica block sid={shuffle_id} "
                             f"m={map_id} origin={origin}")
                except DataCorruption:
                    # a corrupt-at-rest replica cannot complete the
                    # partition; serving the survivors would be
                    # silently wrong — drop the entry AND the
                    # connection so the reader falls back to retry
                    mgr.replicas.drop(origin, shuffle_id, map_id,
                                      reduce_id)
                    return
            payload.append((map_id, framed))
        self.request.sendall(struct.pack("<BI", 1, len(payload)))
        for map_id, data in payload:
            self.request.sendall(_BLOCK_HDR.pack(map_id, len(data)))
            self.request.sendall(data)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class ShuffleBlockServer:
    """Serves this process's host-store shuffle blocks
    (RapidsShuffleServer)."""

    def __init__(self, manager: ShuffleManager, host: str = "127.0.0.1",
                 port: int = 0):
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self._server.manager = manager  # type: ignore
        self._manager = manager
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        # the serving endpoint is this manager's identity on the wire:
        # readers in the same process short-circuit fetches through it,
        # and pushed blocks stamp it as their origin
        with _LOCAL_LOCK:
            _LOCAL_ENDPOINTS[self.endpoint] = manager
        manager.local_endpoint = self.endpoint

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address
        return f"{host}:{port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with _LOCAL_LOCK:
            if _LOCAL_ENDPOINTS.get(self.endpoint) is self._manager:
                del _LOCAL_ENDPOINTS[self.endpoint]
        if self._manager.local_endpoint == self.endpoint:
            self._manager.local_endpoint = None


class ShuffleBlockClient:
    """Fetches a reduce partition's blocks from a peer with bounded
    retry (RapidsShuffleClient.doFetch): each attempt runs under a
    per-attempt socket timeout; failed attempts reconnect after
    exponential backoff with jitter, and blocks already received are
    skipped on the retried stream so a retry never duplicates."""

    def __init__(self, endpoint: str, timeout_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 backoff_base_s: Optional[float] = None):
        from ..conf import (FETCH_BACKOFF_BASE_S, FETCH_MAX_RETRIES,
                            FETCH_TIMEOUT_S, INTEGRITY_CHECKSUM,
                            active_conf)
        conf = active_conf()
        self.verify_checksums = conf.get(INTEGRITY_CHECKSUM)
        self.endpoint = endpoint
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.timeout_s = conf.get(FETCH_TIMEOUT_S) \
            if timeout_s is None else timeout_s
        self.max_retries = conf.get(FETCH_MAX_RETRIES) \
            if max_retries is None else max_retries
        self.backoff_base_s = conf.get(FETCH_BACKOFF_BASE_S) \
            if backoff_base_s is None else backoff_base_s

    def _stream_attempt(self, shuffle_id: int, reduce_id: int,
                        seen: set, exclude: FrozenSet[int] = frozenset()
                        ) -> Iterator[Tuple[int, bytes]]:
        """STREAM blocks one at a time in map order — the socket's TCP
        window is the only read-ahead, so a huge partition never
        buffers whole in this process (WindowedBlockIterator role).
        ``exclude`` names map ids the caller already holds (pushed
        segment entries): a v2 request ships the list so those blocks
        never cross the wire at all."""
        fault_point("transport.connect", self.endpoint)
        with socket.create_connection((self.host, self.port),
                                      timeout=self.timeout_s) as sock:
            if exclude:
                ex = sorted(exclude)
                sock.sendall(_REQ.pack(MAGIC_FETCH2, shuffle_id,
                                       reduce_id)
                             + struct.pack(f"<I{len(ex)}I",
                                           len(ex), *ex))
            else:
                sock.sendall(_REQ.pack(MAGIC, shuffle_id, reduce_id))
            count = struct.unpack("<I", _recv_exact(sock, 4))[0]
            for _ in range(count):
                map_id, length = _BLOCK_HDR.unpack(
                    _recv_exact(sock, _BLOCK_HDR.size))
                fault_point("transport.block",
                            f"{self.endpoint}#m{map_id}")
                data = _recv_exact(sock, length)
                if map_id in seen:
                    continue
                # verify BEFORE marking seen: a block that fails its
                # checksum was never received, and the retried stream
                # must fetch it again
                try:
                    payload = integrity.unwrap(
                        data, what=f"shuffle block sid={shuffle_id} "
                                   f"m={map_id} from {self.endpoint}") \
                        if self.verify_checksums else integrity.strip(data)
                except DataCorruption as e:
                    # convert to a retryable transport failure: wire
                    # corruption heals on refetch; an at-rest-corrupt
                    # source aborts server-side and ends in FetchFailed
                    raise ConnectionError(str(e)) from e
                seen.add(map_id)
                yield map_id, payload

    def stream_raw(self, shuffle_id: int,
                   reduce_id: int) -> Iterator[Tuple[int, bytes]]:
        yield from _retrying_stream(self, shuffle_id, reduce_id,
                                    set(), None)

    def fetch_raw(self, shuffle_id: int,
                  reduce_id: int) -> List[Tuple[int, bytes]]:
        return list(self.stream_raw(shuffle_id, reduce_id))

    def fetch_partition(self, shuffle_id: int,
                        reduce_id: int) -> Iterator[ColumnarBatch]:
        for _map_id, data in self.stream_raw(shuffle_id, reduce_id):
            yield deserialize_batch(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes. When the calling thread carries a
    query token, the blocking read is chopped into short sub-waits
    that poll the token between chunks — a fetch thread whose query
    was cancelled (or whose worker the driver evicted mid-fetch)
    unwinds within a beat instead of blocking out the full socket
    timeout against a wedged peer, releasing its fetch-pool slot and
    letting PrefetchIterator.close() join its producers. The overall
    deadline stays the socket's configured timeout, so retry/failover
    semantics are unchanged for live queries."""
    from ..robustness.admission import current_query
    qc = current_query()
    if qc is None:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed mid-message")
            buf += chunk
        return buf
    total = sock.gettimeout()
    deadline = None if total is None else time.monotonic() + total
    buf = b""
    try:
        while len(buf) < n:
            qc.check()  # raises on cancel / blown deadline
            left = None if deadline is None \
                else deadline - time.monotonic()
            if left is not None and left <= 0:
                raise socket.timeout("shuffle read timed out")
            sock.settimeout(
                0.25 if left is None else max(min(left, 0.25), 0.001))
            try:
                chunk = sock.recv(n - len(buf))
            except socket.timeout:
                continue  # poll tick: re-check the query token
            if not chunk:
                raise ConnectionError("peer closed mid-message")
            buf += chunk
    finally:
        try:
            sock.settimeout(total)
        except OSError:
            pass
    return buf


#: public name for the cancel-aware exact read — the serving front
#: door (serve/protocol.py) frames its session protocol over the same
#: primitive so a cancelled query's stream unwinds within a beat
recv_exact = _recv_exact


def _retrying_stream(cli: ShuffleBlockClient, shuffle_id: int,
                     reduce_id: int, seen: set,
                     resolver: Optional[Callable[[str], Optional[str]]],
                     exclude: FrozenSet[int] = frozenset()
                     ) -> Iterator[Tuple[int, bytes]]:
    """Drive ``cli`` attempts until the stream completes: bounded
    same-endpoint retries with exponential backoff + jitter, then one
    endpoint failover through ``resolver`` (the heartbeat registry's
    current endpoint for the same executor) with a fresh retry budget.
    ``seen`` spans attempts and endpoints: a block is yielded once."""
    attempt = 0
    failed_over = False
    while True:
        try:
            t0 = time.perf_counter_ns()
            yield from cli._stream_attempt(shuffle_id, reduce_id, seen,
                                           exclude)
            from ..obs import registry as _registry
            _registry.observe("fetch_latency_ns",
                              time.perf_counter_ns() - t0, "ns")
            return
        except OSError as e:
            attempt += 1
            from ..obs import events as _events
            _events.emit("RetryAttempt", scope="fetch",
                         endpoint=cli.endpoint, shuffle_id=shuffle_id,
                         reduce_id=reduce_id, attempt=attempt,
                         error=str(e))
            if attempt <= cli.max_retries:
                backoff = (cli.backoff_base_s * (2 ** (attempt - 1))
                           * (1.0 + random.random() * 0.25))
                # cancel-aware backoff: an in-flight fetch for a
                # cancelled/expired query aborts here instead of
                # sleeping out its whole retry budget
                from ..robustness.admission import current_query
                qc = current_query()
                if qc is not None:
                    qc.sleep(backoff)  # raises on cancel/deadline
                else:
                    time.sleep(backoff)
                continue
            if resolver is not None and not failed_over:
                try:
                    alt = resolver(cli.endpoint)
                except Exception:
                    alt = None
                if alt and alt != cli.endpoint:
                    cli = ShuffleBlockClient(alt, cli.timeout_s,
                                             cli.max_retries,
                                             cli.backoff_base_s)
                    failed_over = True
                    attempt = 0
                    continue
            raise


def stream_with_failover(endpoint: str, shuffle_id: int, reduce_id: int,
                         endpoint_resolver: Optional[
                             Callable[[str], Optional[str]]] = None,
                         timeout_s: Optional[float] = None,
                         max_retries: Optional[int] = None,
                         backoff_base_s: Optional[float] = None,
                         exclude: FrozenSet[int] = frozenset()
                         ) -> Iterator[Tuple[int, bytes]]:
    """Fetch one peer's blocks for a reduce partition, surviving
    transient faults; a definitive failure surfaces as ``FetchFailed``
    naming the peer."""
    cli = ShuffleBlockClient(endpoint, timeout_s, max_retries,
                             backoff_base_s)
    try:
        yield from _retrying_stream(cli, shuffle_id, reduce_id, set(),
                                    endpoint_resolver, exclude)
    except OSError as e:
        if isinstance(e, FetchFailed):
            raise
        from ..obs import events as _events
        _events.emit("FetchFailed", endpoint=endpoint,
                     shuffle_id=shuffle_id, reduce_id=reduce_id,
                     error=str(e))
        raise FetchFailed(endpoint, shuffle_id, reduce_id, e) from e


def _local_stream(mgr: ShuffleManager, endpoint: str, shuffle_id: int,
                  reduce_id: int,
                  exclude: FrozenSet[int] = frozenset()
                  ) -> Iterator[Tuple[int, bytes]]:
    """Self-endpoint short-circuit: the addressed endpoint is served by
    a manager in THIS process, so read its host store directly — same
    verification and failure semantics as the socket path (poisoned
    shuffle / corrupt-at-rest block -> ``FetchFailed``), none of the
    serialize-to-socket round trip."""
    fault_point("transport.local",
                f"sid={shuffle_id};reduce={reduce_id};")
    if mgr.is_poisoned(shuffle_id):
        raise FetchFailed(
            endpoint, shuffle_id, reduce_id,
            DataCorruption(f"shuffle {shuffle_id} quarantined; "
                           f"partition {reduce_id} is incomplete"))
    for b in mgr.host_store.blocks_for_reduce(shuffle_id, reduce_id):
        if b[1] in exclude:
            continue
        framed = mgr.host_store.get(b)
        if framed is None:
            continue
        if not mgr.verify_checksums:
            yield b[1], integrity.strip(framed)
            continue
        try:
            payload = integrity.unwrap(
                framed, what=f"local shuffle block {b}")
        except DataCorruption as e:
            # same recovery as the server path: quarantine at-rest
            # corruption and fail the fetch definitively
            mgr.quarantine_block(b, reason=str(e))
            raise FetchFailed(endpoint, shuffle_id, reduce_id, e) from e
        yield b[1], payload


def _push_once(endpoint: str, shuffle_id: int, reduce_id: int,
               map_id: int, rows: int, framed: bytes, origin: str,
               timeout_s: float, replica: bool = False) -> bool:
    """One push upload attempt. Returns True when the receiver stored
    the block (ACK), False on a NAK (receiver saw a corrupt frame —
    the corruption happened in flight, resending heals it). With
    ``replica`` the receiver files the frame in its origin-keyed
    ReplicaStore instead of the consolidated segment."""
    # seeded push-wire corruption (chaos/tests): applied per attempt so
    # a one-shot corrupt spec NAKs the first send and the retry heals
    wire = corrupt_point(
        "shuffle.block.pushwire", framed,
        f"sid={shuffle_id};reduce={reduce_id};m={map_id};")
    host, port = endpoint.rsplit(":", 1)
    ob = origin.encode("utf-8")
    magic = MAGIC_PUSH_REPL if replica else MAGIC_PUSH
    with socket.create_connection((host, int(port)),
                                  timeout=timeout_s) as sock:
        sock.sendall(_REQ.pack(magic, shuffle_id, reduce_id)
                     + _PUSH_HDR.pack(map_id, rows, len(wire), len(ob))
                     + ob)
        sock.sendall(wire)
        status = _recv_exact(sock, 1)[0]
    return status == 1


def _replica_stream(buddy: str, origin: str, shuffle_id: int,
                    reduce_id: int, exclude: FrozenSet[int],
                    timeout_s: float, verify: bool = True
                    ) -> Iterator[Tuple[int, bytes]]:
    """Degraded-mode read: stream ``origin``'s replicated blocks for
    one reduce partition from its ``buddy``. Single attempt, no retry
    budget — the caller already burned the origin's, and on any
    failure it re-raises the ORIGINAL FetchFailed so recovery falls
    back to the stage-retry path. Raises ConnectionError when the
    buddy holds no replicas for this origin (the ``have`` bit): an
    empty stream must never be mistaken for a complete partition."""
    local = local_manager_for(buddy)
    if local is not None:
        # in a 2-worker cluster the reader IS the dead peer's buddy:
        # its replica store serves without a socket
        complete = local.replicas.coverage(origin, shuffle_id,
                                           reduce_id)
        if complete is None:
            raise ConnectionError(
                f"no replica coverage for origin={origin} "
                f"sid={shuffle_id} on {buddy}")
        for map_id, framed in complete:
            if map_id in exclude:
                continue
            try:
                payload = integrity.unwrap(
                    framed, what=f"replica block sid={shuffle_id} "
                                 f"m={map_id} origin={origin}") \
                    if verify else integrity.strip(framed)
            except DataCorruption as e:
                raise ConnectionError(str(e)) from e
            yield map_id, payload
        return
    host, port = buddy.rsplit(":", 1)
    ob = origin.encode("utf-8")
    ex = sorted(exclude)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout_s) as sock:
        sock.sendall(_REQ.pack(MAGIC_FETCH_REPL, shuffle_id, reduce_id)
                     + struct.pack("<H", len(ob)) + ob
                     + struct.pack(f"<I{len(ex)}I", len(ex), *ex))
        have = _recv_exact(sock, 1)[0]
        if not have:
            raise ConnectionError(
                f"no replica coverage for origin={origin} "
                f"sid={shuffle_id} on {buddy}")
        count = struct.unpack("<I", _recv_exact(sock, 4))[0]
        for _ in range(count):
            map_id, length = _BLOCK_HDR.unpack(
                _recv_exact(sock, _BLOCK_HDR.size))
            data = _recv_exact(sock, length)
            try:
                payload = integrity.unwrap(
                    data, what=f"replica block sid={shuffle_id} "
                               f"m={map_id} origin={origin} "
                               f"from {buddy}") \
                    if verify else integrity.strip(data)
            except DataCorruption as e:
                raise ConnectionError(str(e)) from e
            yield map_id, payload


class BlockPusher:
    """Map-side eager push (the magnet/push-based-shuffle sender role):
    blocks enqueue onto the process-wide fetch pool and upload in the
    background while the map task moves on, bounded PER ENDPOINT by a
    ``ByteBudget`` window of un-acknowledged bytes — a slow reducer
    backpressures only its own pushes. Push is best-effort replication:
    any failure just leaves the block to the pull path, so no push
    outcome can ever affect correctness."""

    def __init__(self, max_in_flight: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        from ..conf import (FETCH_TIMEOUT_S, SHUFFLE_PUSH_IN_FLIGHT_BYTES,
                            active_conf)
        conf = active_conf()
        self.max_in_flight = conf.get(SHUFFLE_PUSH_IN_FLIGHT_BYTES) \
            if max_in_flight is None else max_in_flight
        self.timeout_s = conf.get(FETCH_TIMEOUT_S) \
            if timeout_s is None else timeout_s
        self._budgets: Dict[str, ByteBudget] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._in_flight = 0
        self.pushed_blocks = 0
        self.pushed_bytes = 0
        self.failed_blocks = 0

    def _budget(self, endpoint: str) -> "ByteBudget":
        with self._lock:
            b = self._budgets.get(endpoint)
            if b is None:
                b = self._budgets[endpoint] = ByteBudget(
                    self.max_in_flight)
            return b

    def push(self, endpoint: str, shuffle_id: int, reduce_id: int,
             map_id: int, rows: int, framed: bytes,
             origin: str, who: str = "", replica: bool = False) -> None:
        """Enqueue one block for background upload. Blocks the CALLING
        (map) thread only while the target endpoint's in-flight window
        is full. ``who`` is an opaque sender label (e.g. ``w=1``) that
        chaos plans can match to target one worker's push path.
        ``replica`` uploads into the receiver's origin-keyed
        ReplicaStore (durability/migration) instead of its segment."""
        try:
            fault_point("push.send",
                        f"sid={shuffle_id};reduce={reduce_id};"
                        f"m={map_id};ep={endpoint};"
                        + (who + ";" if who else ""))
        except OSError:
            # injected send failure: this block silently degrades to
            # the pull path
            with self._cv:
                self.failed_blocks += 1
            return
        budget = self._budget(endpoint)
        budget.acquire(len(framed))
        with self._cv:
            self._in_flight += 1

        def task() -> None:
            ok = False
            try:
                for _attempt in range(2):
                    try:
                        if _push_once(endpoint, shuffle_id, reduce_id,
                                      map_id, rows, framed, origin,
                                      self.timeout_s, replica=replica):
                            ok = True
                            break
                        # NAK: receiver rejected a wire-corrupt frame;
                        # resend the (intact) origin copy once
                    except OSError:
                        break  # dead/slow peer: pull covers it
            finally:
                budget.release(len(framed))
                with self._cv:
                    if ok:
                        self.pushed_blocks += 1
                        self.pushed_bytes += len(framed)
                    else:
                        self.failed_blocks += 1
                    self._in_flight -= 1
                    self._cv.notify_all()

        fetch_pool().submit(task)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until every enqueued push resolved (acked or failed).
        Called before the stage barrier; a timeout just means late
        pushes land after the readers snapshot — they'll be ignored
        (readers exclude exactly what they consumed) and the blocks
        pull normally."""
        deadline = time.monotonic() + timeout_s
        from ..robustness.admission import current_query
        qc = current_query()
        with self._cv:
            while self._in_flight > 0:
                if qc is not None:
                    qc.check()  # cancelled query: stop waiting
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.25))
        return True


class ByteBudget:
    """Bounded in-flight byte accounting for concurrent fetches — the
    BounceBufferManager role: producers block while the window is full,
    so reduce fan-in memory is capped regardless of partition sizes.
    A single block larger than the whole budget is still admitted
    (alone) so progress is always possible."""

    def __init__(self, limit: int):
        self.limit = max(int(limit), 1)
        self._used = 0
        self.peak = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._used > 0 and self._used + n > self.limit:
                self._cv.wait()
            self._used += n
            self.peak = max(self.peak, self._used)

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class _FetchPool:
    """Process-wide fetch worker pool (RapidsShuffleClient exec pool
    role). One reduce partition used to spawn a fresh one-shot
    ``threading.Thread`` per endpoint — hundreds of thread creations
    per shuffle-heavy query; the pool's daemon workers are reused
    across every reduce of every query in the process. Tasks are plain
    closures; per-reduce fan-out stays capped by ``maxConcurrent``, the
    pool size only bounds PROCESS-wide fetch parallelism."""

    def __init__(self, size: int):
        import queue as _q
        self.size = max(int(size), 1)
        self._q: "_q.SimpleQueue" = _q.SimpleQueue()
        self._threads = []
        for i in range(self.size):
            t = threading.Thread(target=self._loop, daemon=True,
                                 name=f"srt-fetch-{i}")
            t.start()
            self._threads.append(t)

    def _loop(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            try:
                task()
            except BaseException:
                pass  # tasks report through their own channels

    def submit(self, task: Callable[[], None]) -> None:
        self._q.put(task)


_POOL: Optional[_FetchPool] = None
_POOL_LOCK = threading.Lock()


def fetch_pool() -> _FetchPool:
    """The process-wide pool, created on first use at the size of
    ``srt.shuffle.fetch.poolSize`` (later conf changes do not resize —
    the pool outlives any one query by design)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            from ..conf import SHUFFLE_FETCH_POOL_SIZE, active_conf
            _POOL = _FetchPool(active_conf().get(SHUFFLE_FETCH_POOL_SIZE))
        return _POOL


def fetch_all_partitions(endpoints: List[str], shuffle_id: int,
                         reduce_id: int,
                         max_concurrent: Optional[int] = None,
                         in_flight_bytes: Optional[int] = None,
                         budget: Optional[ByteBudget] = None,
                         map_mod=None,
                         endpoint_resolver: Optional[
                             Callable[[str], Optional[str]]] = None,
                         allowed: Optional[dict] = None,
                         manager: Optional[ShuffleManager] = None,
                         metrics_cb: Optional[
                             Callable[[str, int], None]] = None,
                         replicas: Optional[Dict[str, str]] = None
                         ) -> Iterator[ColumnarBatch]:
    """Reduce-side iterator over every peer's blocks for one partition
    (RapidsShuffleIterator role): up to ``max_concurrent`` peers fetch
    in parallel threads, blocks stage through a ``ByteBudget``-bounded
    hand-off, and each deserializes on the consuming thread. Block
    order is preserved per peer (map order); cross-peer order is
    arrival order, which no consumer depends on (partition contents
    are set-semantics until a downstream sort).

    With push-based shuffle on, the read is SEGMENT-FIRST: one
    sequential scan over the locally consolidated segment yields every
    pushed block that passes the filters, then the residual pull sends
    per-peer exclude lists (fetch v2) so consumed blocks never cross
    the wire again. A corrupt segment entry is quarantined alone and —
    being absent from the exclude list — re-pulled from its origin.
    Self-owned endpoints short-circuit through the local block store
    without a socket. ``metrics_cb(kind, nbytes)`` (kind in
    {"segment", "local", "remote"}) attributes each block's source.

    Per-peer streams retry with backoff and, when ``endpoint_resolver``
    is given (cluster mode wires the driver's heartbeat registry), fail
    over once to the peer's current endpoint before surfacing
    ``FetchFailed``. ``replicas`` (origin endpoint -> buddy endpoint)
    arms one further layer: a terminally failed pull degrades to an
    origin-addressed replica fetch from the buddy (k=2 durability /
    decommission migration), excluding everything already received —
    the recovery the RecoveryTimed/recovery_time_ns span measures. A
    buddy without coverage re-raises the ORIGINAL failure, so the
    fallback can never turn a lost partition into a silently partial
    one. Conf knobs resolve HERE, on the consuming thread —
    fetch worker threads are fresh and would only see defaults."""
    from ..conf import (FETCH_BACKOFF_BASE_S, FETCH_MAX_RETRIES,
                        FETCH_TIMEOUT_S, SHUFFLE_FETCH_IN_FLIGHT_BYTES,
                        SHUFFLE_FETCH_MAX_CONCURRENT, active_conf)
    conf = active_conf()
    if max_concurrent is None:
        max_concurrent = conf.get(SHUFFLE_FETCH_MAX_CONCURRENT)
    if in_flight_bytes is None:
        in_flight_bytes = conf.get(SHUFFLE_FETCH_IN_FLIGHT_BYTES)
    timeout_s = conf.get(FETCH_TIMEOUT_S)
    max_retries = conf.get(FETCH_MAX_RETRIES)
    backoff_base_s = conf.get(FETCH_BACKOFF_BASE_S)

    def keep(map_id: int, ep: str) -> bool:
        # skew split: client-side map-slice filter ((s, S) keeps
        # map_id % S == s); blocks outside the slice are dropped before
        # deserialization
        if map_mod is not None and map_id % map_mod[1] != map_mod[0]:
            return False
        # speculation dedup: ``allowed`` maps each ORIGINAL peer
        # endpoint to the map ids the driver committed as winners
        # there; anything else on that peer (a losing duplicate, or a
        # straggler's late write) is dropped before deserialization.
        # Keyed by the endpoint the fetch was ADDRESSED to, so failover
        # to a moved peer keeps the same filter.
        if allowed is not None and map_id not in allowed.get(ep, ()):
            return False
        return True

    if manager is None:
        from .shuffle_manager import shuffle_manager
        manager = shuffle_manager()

    # --- segment-first: drain the consolidated pushed blocks, building
    # per-origin exclude sets as we go (only what was actually CONSUMED
    # is excluded — a quarantined entry stays pullable) ---
    excludes: Dict[str, Set[int]] = {}
    if getattr(manager, "push_enabled", False):
        epset = set(endpoints)
        for origin, map_id, payload in manager.segments.scan(
                shuffle_id, reduce_id,
                # entries from endpoints outside this read's peer list
                # (a replaced worker's stale pushes) never serve — the
                # live peer re-executed those maps and pull owns them
                keep=lambda o, m: o in epset and keep(m, o),
                verify=manager.verify_checksums):
            excludes.setdefault(origin, set()).add(map_id)
            if metrics_cb is not None:
                metrics_cb("segment", len(payload))
            yield deserialize_batch(payload)

    def guarded_stream(ep: str, base: Iterator[Tuple[int, bytes]],
                       ex: FrozenSet[int]
                       ) -> Iterator[Tuple[int, bytes]]:
        # buddy-replica fallback: track every map id this peer DID
        # deliver (plus the segment-consumed excludes) so the replica
        # fetch after a mid-stream death resumes exactly where the
        # pull stopped, never duplicating a block
        got: Set[int] = set(ex)
        try:
            for map_id, data in base:
                got.add(map_id)
                yield map_id, data
            return
        except (FetchFailed, OSError) as primary:
            buddy = replicas.get(ep) if replicas else None
            if not buddy or buddy == ep:
                raise
            from ..obs import events as _events
            from ..obs import registry as _registry
            _events.emit("ReplicaFetch", origin=ep, buddy=buddy,
                         shuffle_id=shuffle_id, reduce_id=reduce_id,
                         cause=str(primary))
            t0 = time.perf_counter_ns()
            served = 0
            try:
                for map_id, data in _replica_stream(
                        buddy, ep, shuffle_id, reduce_id,
                        frozenset(got), timeout_s,
                        verify=manager.verify_checksums):
                    if served == 0:
                        # failure detection -> first post-recovery
                        # block: the RecoveryTimer span of the ISSUE
                        dt = time.perf_counter_ns() - t0
                        _registry.observe("recovery_time_ns", dt, "ns")
                        _events.emit("RecoveryTimed",
                                     kind="buddy_fetch", origin=ep,
                                     buddy=buddy, shuffle_id=shuffle_id,
                                     reduce_id=reduce_id,
                                     recovery_time_ns=dt)
                    served += 1
                    yield map_id, data
            except (OSError, ConnectionError):
                # no coverage / buddy also failing: surface the
                # ORIGINAL failure so stage retry attributes the loss
                # to the right peer
                raise primary
            if served == 0:
                # coverage existed but every block was already held:
                # the recovery completed instantly
                dt = time.perf_counter_ns() - t0
                _registry.observe("recovery_time_ns", dt, "ns")
                _events.emit("RecoveryTimed", kind="buddy_fetch",
                             origin=ep, buddy=buddy,
                             shuffle_id=shuffle_id,
                             reduce_id=reduce_id, recovery_time_ns=dt)

    def open_stream(ep: str) -> Iterator[Tuple[int, bytes]]:
        ex = frozenset(excludes.get(ep, ()))
        local = local_manager_for(ep)
        if local is not None:
            base = _local_stream(local, ep, shuffle_id, reduce_id, ex)
        else:
            base = stream_with_failover(ep, shuffle_id, reduce_id,
                                        endpoint_resolver, timeout_s,
                                        max_retries, backoff_base_s, ex)
        if replicas and replicas.get(ep) not in (None, ep):
            return guarded_stream(ep, base, ex)
        return base

    def block_kind(ep: str) -> str:
        return "local" if local_manager_for(ep) is not None else "remote"

    if len(endpoints) <= 1 or max_concurrent <= 1:
        for ep in endpoints:
            kind = block_kind(ep)
            for map_id, data in open_stream(ep):
                if keep(map_id, ep):
                    if metrics_cb is not None:
                        metrics_cb(kind, len(data))
                    yield deserialize_batch(data)
        return

    import queue as _q
    from ..robustness.admission import current_query, query_scope
    budget = budget or ByteBudget(in_flight_bytes)
    outq: "_q.Queue" = _q.Queue()
    stop = threading.Event()
    pool = fetch_pool()
    # captured HERE on the consuming thread (pool workers are reused
    # across queries and carry no query identity of their own): each
    # worker re-binds it so retry backoffs abort and staged blocks
    # stop flowing the moment the query is torn down
    qc = current_query()

    def worker(ep: str) -> None:
        try:
            if stop.is_set():  # abandoned before this task ran
                return
            kind = block_kind(ep)
            with query_scope(qc):
                for map_id, data in open_stream(ep):
                    if stop.is_set() or (
                            qc is not None and (qc.is_cancelled()
                                                or qc.expired())):
                        return
                    if not keep(map_id, ep):
                        continue
                    budget.acquire(len(data))
                    outq.put(("block", (data, kind)))
        except BaseException as e:  # surfaced on the consumer side
            outq.put(("error", e))
        finally:
            outq.put(("done", None))

    pending = list(endpoints)
    try:
        live = 0
        while pending and live < max_concurrent:
            pool.submit(lambda ep=pending.pop(0): worker(ep))
            live += 1
        done = 0
        total = len(endpoints)
        while done < total:
            if qc is None:
                kind, payload = outq.get()
            else:
                # bounded waits: a hung peer must not outlast the
                # query's deadline or ignore its cancel token
                while True:
                    qc.check()
                    try:
                        kind, payload = outq.get(timeout=0.25)
                        break
                    except _q.Empty:
                        continue
            if kind == "done":
                done += 1
                if pending:
                    pool.submit(lambda ep=pending.pop(0): worker(ep))
                continue
            if kind == "error":
                # fail fast: the partition is already doomed — raising
                # now (instead of after every endpoint drains) stops
                # the consumer deserializing blocks it will throw away;
                # the finally below unwinds the other workers
                raise payload
            data, kind = payload
            if metrics_cb is not None:
                metrics_cb(kind, len(data))
            try:
                batch = deserialize_batch(data)
            finally:
                budget.release(len(data))
            yield batch
    finally:
        stop.set()
        # unblock any producer stuck on a full budget
        with budget._cv:
            budget._used = 0
            budget._cv.notify_all()
