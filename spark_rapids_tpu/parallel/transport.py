"""TCP shuffle block transport (the DCN path).

Rebuild of the reference's shuffle transport stack (SURVEY §2.7:
RapidsShuffleServer.scala:71 / RapidsShuffleClient.scala:90 /
RapidsShuffleIterator): executors serve their local shuffle blocks over
a length-prefixed TCP protocol; remote reads stream a whole reduce
partition's blocks. Within a pod the MESH mode's in-program all-to-all
replaces this entirely; across pods (DCN) — or between plain hosts —
this transport is the fetch path, with the heartbeat registry
(shuffle_manager.ShuffleHeartbeatManager) distributing endpoints.

Wire protocol (all little-endian):
  request:  magic u32 | shuffle_id u32 | reduce_id u32
  response: count u32, then per block: map_id u32 | length u64 | bytes
Transfers reuse the serializer's self-describing block format, so the
receiving side deserializes straight into capacity-bucketed batches
(ShuffleReceivedBufferCatalog role falls to the caller's manager).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Iterator, List, Optional, Tuple

from ..columnar.vector import ColumnarBatch
from .serializer import deserialize_batch
from .shuffle_manager import ShuffleManager

MAGIC = 0x53525453  # "SRTS"
_REQ = struct.Struct("<III")
_BLOCK_HDR = struct.Struct("<IQ")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        mgr: ShuffleManager = self.server.manager  # type: ignore
        raw = self._recv_exact(_REQ.size)
        if raw is None:
            return
        magic, shuffle_id, reduce_id = _REQ.unpack(raw)
        if magic != MAGIC:
            return
        blocks = mgr.host_store.blocks_for_reduce(shuffle_id, reduce_id)
        payload = [(b[1], mgr.host_store.get(b)) for b in blocks]
        payload = [(m, d) for m, d in payload if d is not None]
        self.request.sendall(struct.pack("<I", len(payload)))
        for map_id, data in payload:
            self.request.sendall(_BLOCK_HDR.pack(map_id, len(data)))
            self.request.sendall(data)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class ShuffleBlockServer:
    """Serves this process's host-store shuffle blocks
    (RapidsShuffleServer)."""

    def __init__(self, manager: ShuffleManager, host: str = "127.0.0.1",
                 port: int = 0):
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self._server.manager = manager  # type: ignore
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address
        return f"{host}:{port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class ShuffleBlockClient:
    """Fetches a reduce partition's blocks from a peer
    (RapidsShuffleClient.doFetch)."""

    def __init__(self, endpoint: str, timeout_s: float = 30.0):
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.timeout_s = timeout_s

    def fetch_raw(self, shuffle_id: int,
                  reduce_id: int) -> List[Tuple[int, bytes]]:
        with socket.create_connection((self.host, self.port),
                                      timeout=self.timeout_s) as sock:
            sock.sendall(_REQ.pack(MAGIC, shuffle_id, reduce_id))
            count = struct.unpack("<I", _recv_exact(sock, 4))[0]
            out = []
            for _ in range(count):
                map_id, length = _BLOCK_HDR.unpack(
                    _recv_exact(sock, _BLOCK_HDR.size))
                out.append((map_id, _recv_exact(sock, length)))
            return out

    def fetch_partition(self, shuffle_id: int,
                        reduce_id: int) -> Iterator[ColumnarBatch]:
        for _map_id, data in sorted(self.fetch_raw(shuffle_id,
                                                   reduce_id)):
            yield deserialize_batch(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def fetch_all_partitions(endpoints: List[str], shuffle_id: int,
                         reduce_id: int) -> Iterator[ColumnarBatch]:
    """Reduce-side iterator over every peer's blocks for one partition
    (RapidsShuffleIterator role)."""
    for ep in endpoints:
        yield from ShuffleBlockClient(ep).fetch_partition(shuffle_id,
                                                          reduce_id)
