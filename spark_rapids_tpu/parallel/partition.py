"""On-device partitioning of columnar batches.

Rebuild of the reference's GPU partitioning layer (GpuPartitioning.scala
``sliceInternalOnGpuAndClose:63``, GpuHashPartitioningBase.scala:64,
GpuRoundRobinPartitioning.scala): rows are assigned a destination
partition on device, then sliced into per-partition sub-batches. The
static-shape formulation packs every partition into a dense
``(num_parts, slot_capacity)`` layout — exactly the shape
``lax.all_to_all`` wants — with per-partition row counts carried
alongside. A partition that would overflow ``slot_capacity`` reports its
true count so the host can split-and-retry, mirroring the reference's
SplitAndRetryOOM contract.

Spark semantics preserved: hash partitioning is
``pmod(murmur3(keys, seed=42), num_parts)`` so a row lands on the same
partition id the CPU would send it to (GpuHashPartitioningBase.scala:64).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               StringColumn)
from ..expr.hashing import murmur3_row_hash


def hash_partition_ids(key_cols: Sequence[Column], num_parts: int,
                       seed: int = 42) -> jnp.ndarray:
    """int32[capacity] destination partition per row (Spark pmod rule)."""
    h = murmur3_row_hash(key_cols, seed=seed)  # int32
    m = h % jnp.int32(num_parts)
    return jnp.where(m < 0, m + num_parts, m)


def round_robin_partition_ids(capacity: int, num_parts: int,
                              start: int = 0) -> jnp.ndarray:
    return ((jnp.arange(capacity, dtype=jnp.int32) + start) % num_parts)


def _order_class(col: Column, n: int, asc: bool, nf: bool) -> jnp.ndarray:
    """int8[n] ordering class consistent with ops.kernels.sort_indices:
    nulls-first nulls < values < NaN (ascending) with NaN leading under
    descending, nulls-last nulls always last."""
    valid = col.validity
    if isinstance(col, ColumnVector) and \
            jnp.issubdtype(col.data.dtype, jnp.floating):
        nan = jnp.isnan(col.data)
    else:
        nan = jnp.zeros(n, jnp.bool_)
    value_cls = jnp.where(nan, jnp.int8(2 if asc else 1),
                          jnp.int8(1 if asc else 2))
    return jnp.where(valid, value_cls, jnp.int8(0 if nf else 3))


def range_partition_ids(key_cols: Sequence[Column],
                        bound_cols: Sequence[Column],
                        ascending: Sequence[bool],
                        nulls_first: Sequence[bool]) -> jnp.ndarray:
    """int32[capacity] destination partition by bound search.

    GpuRangePartitioner semantics: partition id = number of bounds the
    row sorts strictly after, so rows equal to a bound land with that
    bound's partition and the concatenation of partitions in id order is
    globally sorted. ``bound_cols`` hold exactly ``num_parts - 1`` rows
    (capacity == row count; null bounds are legitimate sampled keys).
    Comparison semantics match ops.kernels.sort_indices exactly —
    required for distributed sort correctness.
    """
    from ..ops.kernels import _rank_keys
    cap = key_cols[0].capacity
    B = bound_cols[0].capacity
    before = jnp.zeros((cap, B), jnp.bool_)
    eq = jnp.ones((cap, B), jnp.bool_)
    for rc, bc, asc, nf in zip(key_cols, bound_cols, ascending, nulls_first):
        rcls = _order_class(rc, cap, asc, nf)
        bcls = _order_class(bc, B, asc, nf)
        before = before | (eq & (rcls[:, None] < bcls[None, :]))
        eq = eq & (rcls[:, None] == bcls[None, :])
        rkeys = list(_rank_keys(rc))
        bkeys = list(_rank_keys(bc))
        # strings of different pad buckets produce different word counts;
        # zero-extend (zero == empty suffix, ordered before any byte)
        while len(rkeys) < len(bkeys):
            rkeys.append(jnp.zeros(cap, rkeys[0].dtype))
        while len(bkeys) < len(rkeys):
            bkeys.append(jnp.zeros(B, bkeys[0].dtype))
        for rk, bk in zip(rkeys, bkeys):
            lt = (rk[:, None] < bk[None, :]) if asc \
                else (rk[:, None] > bk[None, :])
            before = before | (eq & lt)
            eq = eq & (rk[:, None] == bk[None, :])
    after = ~(before | eq)
    return jnp.sum(after.astype(jnp.int32), axis=1)


class PartitionedBatch:
    """A batch split into ``num_parts`` dense slots.

    ``columns[i]`` holds per-column arrays with a leading partition dim:
      - primitive: data (P, S), validity (P, S)
      - string:    padded bytes (P, S, W), lengths (P, S), validity (P, S)
    ``counts`` is int32[P] live rows per partition. All shapes static.
    """

    __slots__ = ("columns", "names", "dtypes", "counts", "slot_capacity")

    def __init__(self, columns, names, dtypes, counts, slot_capacity: int):
        self.columns = columns
        self.names = list(names)
        self.dtypes = list(dtypes)
        self.counts = counts
        self.slot_capacity = slot_capacity

    @property
    def num_parts(self) -> int:
        return self.counts.shape[0]


def _pb_flatten(p: PartitionedBatch):
    return (tuple(p.columns), p.counts), (tuple(p.names), tuple(p.dtypes),
                                          p.slot_capacity)


def _pb_unflatten(aux, children):
    names, dtypes, slot_capacity = aux
    columns, counts = children
    return PartitionedBatch(list(columns), list(names), list(dtypes), counts,
                            slot_capacity)


jax.tree_util.register_pytree_node(PartitionedBatch, _pb_flatten, _pb_unflatten)


def partition_batch(batch: ColumnarBatch, part_ids: jnp.ndarray,
                    num_parts: int,
                    slot_capacity: Optional[int] = None) -> PartitionedBatch:
    """Pack rows into a dense (num_parts, slot_capacity) layout.

    Rows keep their relative order within a partition (stable sort by
    destination). Dead rows are routed past the live buckets and dropped.
    """
    cap = batch.capacity
    S = slot_capacity or cap
    live = batch.live_mask()
    pid = jnp.where(live, part_ids, jnp.int32(num_parts))
    counts_all = jnp.zeros(num_parts + 1, jnp.int32).at[
        jnp.clip(pid, 0, num_parts)].add(1)
    counts = counts_all[:num_parts]
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    if num_parts <= 32:
        # counting sort: per-partition rank via one cumsum per partition
        # — replaces the full argsort whose cost dominated partitioning
        within = jnp.zeros(cap, jnp.int32)
        for p in range(num_parts):
            is_p = pid == p
            within = jnp.where(
                is_p, jnp.cumsum(is_p.astype(jnp.int32)) - 1, within)
        slot = jnp.take(offsets, jnp.clip(pid, 0, num_parts - 1)) + within
        order = jnp.zeros(cap, jnp.int32).at[
            jnp.where(pid < num_parts, slot, cap)].set(
            jnp.arange(cap, dtype=jnp.int32), mode="drop")
    else:
        order = jnp.argsort(pid, stable=True).astype(jnp.int32)
    j = jnp.arange(S, dtype=jnp.int32)
    srcpos = offsets[:num_parts, None] + j[None, :]          # (P, S)
    row = jnp.take(order, jnp.clip(srcpos, 0, cap - 1))      # (P, S)
    valid = j[None, :] < jnp.minimum(counts, S)[:, None]     # (P, S)

    from ..columnar.nested import ListColumn
    cols_out = []
    for c in batch.columns:
        if isinstance(c, ListColumn):
            # lists shuffle as (lens, validity) row planes plus a child
            # plane packed row-major PER PARTITION: every element gets
            # its row's destination, then the same dense-pack as rows
            # runs on the element axis (no fixed-width truncation, so
            # collect-style states of any length survive)
            lens_all = jnp.where(c.validity & live, c.lengths(), 0)
            pl = jnp.where(valid, jnp.take(lens_all, row), 0)
            pv = valid & jnp.take(c.validity, row)
            ccap = c.child_capacity
            epos = jnp.arange(ccap, dtype=jnp.int32)
            erow = jnp.clip(jnp.searchsorted(c.offsets[1:], epos,
                                             side="right"),
                            0, cap - 1).astype(jnp.int32)
            e_live = epos < c.offsets[cap]
            e_pid = jnp.where(e_live & jnp.take(live, erow),
                              jnp.take(part_ids, erow),
                              jnp.int32(num_parts))
            # elements of one row stay contiguous and rows keep their
            # relative order inside a partition: sort by (pid, position)
            e_order = jnp.argsort(e_pid, stable=True).astype(jnp.int32)
            e_counts = jnp.zeros(num_parts + 1, jnp.int32).at[
                jnp.clip(e_pid, 0, num_parts)].add(1)[:num_parts]
            e_offsets = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(e_counts, dtype=jnp.int32)])
            j2 = jnp.arange(ccap, dtype=jnp.int32)
            esrc = e_offsets[:num_parts, None] + j2[None, :]
            etake = jnp.take(e_order, jnp.clip(esrc, 0, ccap - 1))
            e_valid = j2[None, :] < e_counts[:, None]       # (P, Sc)
            cdata = jnp.where(e_valid,
                              jnp.take(c.child.data, etake),
                              jnp.zeros((), c.child.data.dtype))
            cok = e_valid & jnp.take(c.child.validity, etake)
            cols_out.append((pl, pv, cdata, cok,
                             jnp.minimum(e_counts, ccap)))
            continue
        if isinstance(c, StringColumn):
            padded = c.padded()                              # (cap, W)
            lens = c.lengths()
            pb = jnp.take(padded, row, axis=0)               # (P, S, W)
            pl = jnp.where(valid, jnp.take(lens, row), 0)
            pv = valid & jnp.take(c.validity, row)
            pb = jnp.where(valid[:, :, None], pb, jnp.zeros((), jnp.uint8))
            cols_out.append((pb, pl, pv))
        else:
            from ..columnar.decimal128 import Decimal128Column
            if isinstance(c, Decimal128Column):
                hi = jnp.where(valid, jnp.take(c.hi, row),
                               jnp.zeros((), jnp.int64))
                lo = jnp.where(valid, jnp.take(c.lo, row),
                               jnp.zeros((), jnp.uint64))
                v = valid & jnp.take(c.validity, row)
                cols_out.append((hi, lo, v))
            else:
                data = jnp.take(c.data, row)
                data = jnp.where(valid, data, jnp.zeros((), data.dtype))
                v = valid & jnp.take(c.validity, row)
                cols_out.append((data, v))
    return PartitionedBatch(cols_out, batch.names,
                            [c.dtype for c in batch.columns],
                            jnp.minimum(counts, S), S)


def list_from_packed(lens: jnp.ndarray, validity: jnp.ndarray,
                     child_vals: jnp.ndarray, child_ok: jnp.ndarray,
                     n_elems, element_type):
    """Rebuild a ListColumn from the packed shuffle layout: row lens +
    validity, and child elements packed row-major with ``n_elems``
    live."""
    from ..columnar.nested import ListColumn
    from ..columnar.vector import ColumnVector
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    pos = jnp.arange(child_vals.shape[0], dtype=jnp.int32)
    live = pos < n_elems
    child = ColumnVector(
        jnp.where(live & child_ok, child_vals,
                  jnp.zeros((), child_vals.dtype)),
        live & child_ok, element_type)
    return ListColumn(offsets, child, validity, element_type)


def string_from_padded(padded: jnp.ndarray, lens: jnp.ndarray,
                       validity: jnp.ndarray,
                       char_capacity: Optional[int] = None) -> StringColumn:
    """Rebuild a StringColumn from a fixed-width (N, W) padded view.

    The inverse of ``StringColumn.padded()`` — used on the receive side of
    the shuffle, where strings travel as fixed-width byte lanes.
    """
    n, w = padded.shape
    nbytes = char_capacity or n * w
    from ..columnar.vector import rows_from_offsets
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    row_c = rows_from_offsets(offsets[:-1], lens, nbytes)
    within = pos - jnp.take(offsets, row_c)
    total = offsets[n]
    chars = jnp.where(
        pos < total,
        padded[row_c, jnp.clip(within, 0, w - 1)],
        jnp.zeros((), jnp.uint8))
    return StringColumn(offsets, chars, validity, pad_bucket=w)


def flatten_partitions(pb: PartitionedBatch,
                       received_counts: Optional[jnp.ndarray] = None
                       ) -> ColumnarBatch:
    """Flatten a (P, S) partitioned layout back into one dense batch.

    ``received_counts`` overrides ``pb.counts`` (after an all_to_all, the
    exchanged counts describe the blocks now held). Rows are compacted so
    the output is a standard live-prefix batch of capacity P*S.
    """
    from ..columnar.vector import compaction_indices, live_mask
    P, S = pb.num_parts, pb.slot_capacity
    counts = pb.counts if received_counts is None else received_counts
    cap = P * S
    j = jnp.arange(S, dtype=jnp.int32)
    slot_valid = (j[None, :] < counts[:, None]).reshape(cap)
    n = jnp.sum(jnp.minimum(counts, S)).astype(jnp.int32)
    order = compaction_indices(slot_valid)
    keep = live_mask(cap, n)  # compacted output: live rows are a prefix

    cols: List[Column] = []
    for spec, dtype in zip(pb.columns, pb.dtypes):
        if isinstance(dtype, dt.ArrayType):
            lens, valid, cdata, cok, e_counts = spec
            flat_l = jnp.take(lens.reshape(cap), order)
            flat_v = jnp.take(valid.reshape(cap), order)
            flat_l = jnp.where(keep, flat_l, 0)
            flat_v = flat_v & keep
            # child planes: compact each partition's live element run,
            # partition-major (matches the row flattening order)
            P_, Sc = cdata.shape
            je = jnp.arange(Sc, dtype=jnp.int32)
            e_slot_valid = (je[None, :] < e_counts[:, None]).reshape(
                P_ * Sc)
            n_elems = jnp.sum(e_counts).astype(jnp.int32)
            e_order = compaction_indices(e_slot_valid)
            flat_cd = jnp.take(cdata.reshape(P_ * Sc), e_order)
            flat_co = jnp.take(cok.reshape(P_ * Sc), e_order) & \
                live_mask(P_ * Sc, n_elems)
            cols.append(list_from_packed(flat_l, flat_v, flat_cd,
                                         flat_co, n_elems,
                                         dtype.element_type))
            continue
        if dtype == dt.STRING:
            padded, lens, valid = spec
            w = padded.shape[-1]
            flat_b = jnp.take(padded.reshape(cap, w), order, axis=0)
            flat_l = jnp.take(lens.reshape(cap), order)
            flat_v = jnp.take(valid.reshape(cap), order)
            flat_l = jnp.where(keep, flat_l, 0)
            flat_v = flat_v & keep
            cols.append(string_from_padded(flat_b, flat_l, flat_v))
        elif isinstance(dtype, dt.DecimalType) and dtype.is_wide:
            from ..columnar.decimal128 import Decimal128Column
            hi, lo, valid = spec
            h = jnp.take(hi.reshape(cap), order)
            l = jnp.take(lo.reshape(cap), order)
            v = jnp.take(valid.reshape(cap), order) & keep
            h = jnp.where(v, h, jnp.zeros((), jnp.int64))
            l = jnp.where(v, l, jnp.zeros((), jnp.uint64))
            cols.append(Decimal128Column(h, l, v, dtype))
        else:
            data, valid = spec
            d = jnp.take(data.reshape(cap), order)
            v = jnp.take(valid.reshape(cap), order) & keep
            d = jnp.where(v, d, jnp.zeros((), d.dtype))
            cols.append(ColumnVector(d, v, dtype))
    return ColumnarBatch(cols, pb.names, n)
