"""Device mesh construction.

The mesh is the TPU analogue of the reference's executor topology: one
axis, ``"data"``, plays the role of Spark's task/partition parallelism
(SURVEY header table: "Spark tasks x partitions"). Shuffle exchanges ride
this axis as ICI all-to-alls; broadcast joins ride it as all-gathers.
Cross-slice (DCN) scaling adds an outer axis later without changing any
operator code — shard_map composes over multi-axis meshes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices, axis ``"data"``."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def local_mesh() -> Mesh:
    """Mesh over every visible device."""
    return data_mesh()
