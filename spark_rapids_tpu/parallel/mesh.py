"""Device mesh construction.

The mesh is the TPU analogue of the reference's executor topology: one
axis, ``"data"``, plays the role of Spark's task/partition parallelism
(SURVEY header table: "Spark tasks x partitions"). Shuffle exchanges ride
this axis as ICI all-to-alls; broadcast joins ride it as all-gathers.
Cross-slice (DCN) scaling adds an outer axis later without changing any
operator code — shard_map composes over multi-axis meshes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices, axis ``"data"``."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def local_mesh() -> Mesh:
    """Mesh over every visible device."""
    return data_mesh()


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS,
                  rank: int = 1) -> NamedSharding:
    """Sharding that splits a stacked tree's leading shard dim over
    ``axis`` and replicates trailing dims (rank-1 padding)."""
    return NamedSharding(mesh, P(axis, *((None,) * max(rank - 1, 0))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Full copy on every mesh device — broadcast build sides."""
    return NamedSharding(mesh, P())


def mesh_key(mesh: Mesh) -> tuple:
    """Hashable identity of a mesh (device set + axis layout) for
    structural program-sharing keys: two ``data_mesh(8)`` calls build
    distinct Mesh objects over the same devices and must share
    compiled stage programs."""
    return (tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(str(d) for d in mesh.devices.flat))


def tree_nbytes(tree) -> int:
    """Total concrete bytes across a pytree's array leaves (stage-
    boundary shuffle accounting; 0 for abstract/traced leaves)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if isinstance(nb, (int, np.integer)):
            total += int(nb)
    return total
