"""Shuffle manager: pluggable block-based shuffle with three modes.

Rebuild of RapidsShuffleInternalManagerBase.scala (:1075, SURVEY §2.7)
and its catalogs (ShuffleBufferCatalog / ShuffleReceivedBufferCatalog),
re-architected for TPU:

- CACHE_ONLY:     blocks stay device-resident as SpillableBatches in a
                  ShuffleBlockCatalog (RapidsCachingWriter path); spill
                  tiering applies automatically under memory pressure.
- MULTITHREADED:  blocks serialize on a writer thread pool to host
                  memory (optionally zstd-compressed, the nvcomp-LZ4
                  role) and deserialize on a reader pool — the
                  reference's threaded file shuffle with host RAM
                  standing in for shuffle files.
- NATIVE:         the SPMD path: shuffle IS a mesh all-to-all inside
                  the compiled program (shuffle.py shuffle_exchange) —
                  this manager only records metadata for it, because
                  ICI collectives live inside jit, not behind an RPC
                  (SURVEY §2.7 "TPU equivalent" row).

A driver-side heartbeat registry (RapidsShuffleHeartbeatManager role)
tracks executor liveness for multi-host deployments.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..columnar.vector import ColumnarBatch, choose_capacity
from ..conf import (SHUFFLE_COMPRESS, SHUFFLE_MODE, SHUFFLE_PARTITIONS,
                    SrtConf, active_conf)
from ..memory.spill import SpillPriority, SpillableBatch
from ..robustness import integrity
from ..robustness.faults import corrupt_point, fault_point
from .serializer import deserialize_batch, serialize_batch

BlockId = Tuple[int, int, int]  # (shuffle_id, map_id, reduce_id)


class ShuffleBlockCatalog:
    """Device-resident shuffle blocks as spillables
    (ShuffleBufferCatalog.scala role)."""

    def __init__(self):
        self._blocks: Dict[BlockId, List[SpillableBatch]] = {}
        self._lock = threading.Lock()

    def add(self, block: BlockId, batch: ColumnarBatch) -> None:
        sb = SpillableBatch(batch, SpillPriority.SHUFFLE_OUTPUT)
        with self._lock:
            old = self._blocks.get(block)
            # a replayed map task (OOM retry) OVERWRITES its block —
            # appending would duplicate the partition's rows
            self._blocks[block] = [sb]
        if old:
            for prev in old:
                prev.close()

    def get(self, block: BlockId) -> List[ColumnarBatch]:
        from ..memory.retry import with_retry_no_split
        with self._lock:
            sbs = list(self._blocks.get(block, []))
        # rematerializing a spilled block reserves device budget; OOM
        # here spills other blocks and retries (pure re-read)
        return with_retry_no_split(lambda: [sb.get() for sb in sbs])

    def blocks_for_reduce(self, shuffle_id: int,
                          reduce_id: int) -> List[BlockId]:
        with self._lock:
            return sorted(b for b in self._blocks
                          if b[0] == shuffle_id and b[2] == reduce_id)

    def remove_shuffle(self, shuffle_id: int) -> int:
        with self._lock:
            gone = [b for b in self._blocks if b[0] == shuffle_id]
            n = 0
            for b in gone:
                for sb in self._blocks.pop(b):
                    sb.close()
                    n += 1
        return n


class HostBlockStore:
    """Serialized host-memory blocks (the MULTITHREADED mode's 'shuffle
    files'). Blocks are stored inside the integrity layer's framed
    checksum envelope — the checksum is computed once at
    write/registration (SPARK-35275 role) and verified at every
    consumption point (server serve, remote fetch, local read)."""

    def __init__(self):
        self._blocks: Dict[BlockId, bytes] = {}
        self._lock = threading.Lock()
        self.bytes_written = 0

    def put(self, block: BlockId, data: bytes) -> None:
        framed = integrity.wrap(data)
        # seeded at-rest corruption (chaos/tests): flips a byte of the
        # stored frame so every later verification path must catch it
        framed = corrupt_point(
            "shuffle.block.store", framed,
            f"sid={block[0]};map={block[1]};reduce={block[2]};")
        with self._lock:
            self._blocks[block] = framed
            self.bytes_written += len(framed)

    def get(self, block: BlockId) -> Optional[bytes]:
        """The raw FRAMED bytes (header + payload) — what the transport
        serves; consumers unwrap/verify."""
        with self._lock:
            return self._blocks.get(block)

    def remove_block(self, block: BlockId) -> bool:
        with self._lock:
            data = self._blocks.pop(block, None)
            if data is not None:
                self.bytes_written -= len(data)
            return data is not None

    def blocks_for_reduce(self, shuffle_id: int,
                          reduce_id: int) -> List[BlockId]:
        with self._lock:
            return sorted(b for b in self._blocks
                          if b[0] == shuffle_id and b[2] == reduce_id)

    def blocks_for_map(self, shuffle_id: int,
                       map_id: int) -> List[BlockId]:
        with self._lock:
            return sorted(b for b in self._blocks
                          if b[0] == shuffle_id and b[1] == map_id)

    def remove_shuffle(self, shuffle_id: int) -> int:
        with self._lock:
            gone = [b for b in self._blocks if b[0] == shuffle_id]
            for b in gone:
                self.bytes_written -= len(self._blocks.pop(b))
            return len(gone)

    def rename_shuffle(self, old_id: int, new_id: int) -> int:
        with self._lock:
            gone = [b for b in self._blocks if b[0] == old_id]
            for b in gone:
                self._blocks[(new_id, b[1], b[2])] = self._blocks.pop(b)
            return len(gone)


class _Segment:
    """One reduce partition's append-only consolidated bytes: the
    framed envelopes of every pushed block, back to back, plus an index
    of where each (origin, map_id) entry sits."""

    __slots__ = ("buf", "index")

    def __init__(self):
        self.buf = bytearray()
        #: (origin_endpoint, map_id) -> (offset, length, rows); a
        #: re-pushed entry (map replay) re-points the index at its new
        #: bytes — the old range becomes dead space, never re-read
        self.index: Dict[Tuple[str, int], Tuple[int, int, int]] = {}


class SegmentStore:
    """Receive-side consolidation of PUSHED shuffle blocks into
    per-reducer segments (the push-based shuffle's 'merged shuffle
    file' role, Spark's magnet push-merge). Each pushed block is
    appended — still inside its integrity frame — to the segment for
    its (shuffle_id, reduce_id), so a reducer's read is ONE sequential
    scan over local memory instead of maps-many socket round trips.

    Integrity granularity is the ENTRY: every frame verifies on scan,
    and a corrupt entry is quarantined alone (dropped from the index)
    — the reader re-pulls just that (origin, map_id) from its origin,
    never losing the rest of the segment. Entries carry exact
    (rows, bytes), so the index doubles as the receive-side
    MapOutputStatistics source (no second accounting pass)."""

    def __init__(self):
        self._segments: Dict[Tuple[int, int], _Segment] = {}
        self._lock = threading.Lock()
        self.bytes_appended = 0
        self.entries_appended = 0
        self.entries_quarantined = 0

    def append(self, shuffle_id: int, reduce_id: int, origin: str,
               map_id: int, rows: int, framed: bytes) -> None:
        # seeded corrupt-at-rest-in-segment (chaos/tests): flips a byte
        # of the entry as stored, so the per-entry verification on scan
        # must quarantine exactly this entry
        framed = corrupt_point(
            "shuffle.segment.store", framed,
            f"sid={shuffle_id};reduce={reduce_id};m={map_id};"
            f"origin={origin};")
        with self._lock:
            seg = self._segments.setdefault((shuffle_id, reduce_id),
                                            _Segment())
            off = len(seg.buf)
            seg.buf += framed
            seg.index[(origin, map_id)] = (off, len(framed), int(rows))
            self.bytes_appended += len(framed)
            self.entries_appended += 1

    def entries(self, shuffle_id: int, reduce_id: int
                ) -> List[Tuple[str, int, int, int]]:
        """Sorted (origin, map_id, length, rows) index view."""
        with self._lock:
            seg = self._segments.get((shuffle_id, reduce_id))
            if seg is None:
                return []
            return sorted((o, m, ln, rows)
                          for (o, m), (_off, ln, rows) in
                          seg.index.items())

    def map_ids_from(self, shuffle_id: int,
                     reduce_id: int) -> Dict[str, set]:
        """origin endpoint -> map ids present — the pull path's
        per-peer exclude sets."""
        out: Dict[str, set] = {}
        with self._lock:
            seg = self._segments.get((shuffle_id, reduce_id))
            if seg is None:
                return out
            for (o, m) in seg.index:
                out.setdefault(o, set()).add(m)
        return out

    def scan(self, shuffle_id: int, reduce_id: int, keep=None,
             verify: bool = True):
        """One sequential pass over the segment: yields
        ``(origin, map_id, payload)`` for every live index entry that
        passes ``keep(origin, map_id)``, verifying each frame. A frame
        that fails verification quarantines ONLY its own entry (the
        index forgets it; the dead bytes stay) — the caller's pull
        fallback refetches that (origin, map_id) from its origin."""
        with self._lock:
            seg = self._segments.get((shuffle_id, reduce_id))
            if seg is None:
                return
            # snapshot in OFFSET order (the sequential scan); appends
            # during iteration only extend past the snapshot
            items = sorted(((off, ln, rows, o, m)
                            for (o, m), (off, ln, rows) in
                            seg.index.items()))
            buf = seg.buf
        for off, ln, _rows, origin, map_id in items:
            if keep is not None and not keep(origin, map_id):
                continue
            framed = bytes(buf[off:off + ln])
            if not verify:
                yield origin, map_id, integrity.strip(framed)
                continue
            try:
                payload = integrity.unwrap(
                    framed, what=f"segment entry sid={shuffle_id} "
                                 f"reduce={reduce_id} m={map_id} "
                                 f"from {origin}")
            except integrity.DataCorruption as e:
                self.quarantine_entry(shuffle_id, reduce_id, origin,
                                      map_id, reason=str(e))
                continue
            yield origin, map_id, payload

    def quarantine_entry(self, shuffle_id: int, reduce_id: int,
                         origin: str, map_id: int,
                         reason: str = "") -> bool:
        """Drop ONE corrupt entry from the index — unlike block-store
        quarantine this never poisons the shuffle: the origin still
        holds the authoritative block, so recovery is a point refetch
        (recompute of one entry), not a whole-segment loss."""
        import logging
        with self._lock:
            seg = self._segments.get((shuffle_id, reduce_id))
            dropped = (seg is not None
                       and seg.index.pop((origin, map_id), None)
                       is not None)
            if dropped:
                self.entries_quarantined += 1
        if dropped:
            logging.getLogger("spark_rapids_tpu.shuffle").warning(
                "quarantined corrupt segment entry sid=%s reduce=%s "
                "map=%s origin=%s%s", shuffle_id, reduce_id, map_id,
                origin, f": {reason}" if reason else "")
        return dropped

    def statistics(self, shuffle_id: int,
                   num_partitions: int) -> MapOutputStatistics:
        """Exact per-(map, reduce) (rows, bytes) straight from the
        segment index — what pushed entries declared at write time, no
        re-walk of any block store. Bytes are the framed payload sizes
        (frame header excluded) to match the write-side accounting."""
        detail: Dict[Tuple[int, int], Tuple[int, int]] = {}
        with self._lock:
            for (sid, rid), seg in self._segments.items():
                if sid != shuffle_id or rid >= num_partitions:
                    continue
                for (_o, m), (_off, ln, rows) in seg.index.items():
                    pr, pb = detail.get((m, rid), (0, 0))
                    detail[(m, rid)] = (
                        pr + rows,
                        pb + max(ln - integrity.HEADER_SIZE, 0))
        rows_by = [0] * num_partitions
        bytes_by = [0] * num_partitions
        for (_m, rid), (rows, nbytes) in detail.items():
            rows_by[rid] += rows
            bytes_by[rid] += nbytes
        return MapOutputStatistics(shuffle_id, num_partitions, rows_by,
                                   bytes_by, detail)

    def remove_shuffle(self, shuffle_id: int) -> int:
        with self._lock:
            gone = [k for k in self._segments if k[0] == shuffle_id]
            n = 0
            for k in gone:
                seg = self._segments.pop(k)
                n += len(seg.index)
                self.bytes_appended -= len(seg.buf)
            return n

    def rename_shuffle(self, old_id: int, new_id: int) -> int:
        """Stage-level retry: received segments re-key alongside the
        origin blocks, so surviving pushed entries keep serving reads
        under the re-planned exchange's fresh shuffle id."""
        with self._lock:
            gone = [k for k in self._segments if k[0] == old_id]
            n = 0
            for k in gone:
                self._segments[(new_id, k[1])] = self._segments.pop(k)
                n += 1
            return n


@dataclass
class ShuffleWriteMetrics:
    blocks_written: int = 0
    rows_written: int = 0
    bytes_written: int = 0
    write_time_ns: int = 0


@dataclass
class MapOutputStatistics:
    """Exact per-(map, reduce) shuffle sizes for one shuffle — what the
    reference's AQE reads from Spark's MapOutputStatistics, here with
    both rows and bytes so adaptive rules can reason in either unit.
    ``detail`` maps (map_id, reduce_id) -> (rows, bytes); the
    ``*_by_reduce`` lists are its per-reduce sums."""

    shuffle_id: int
    num_partitions: int
    rows_by_reduce: List[int]
    bytes_by_reduce: List[int]
    detail: Dict[Tuple[int, int], Tuple[int, int]]

    @property
    def total_rows(self) -> int:
        return sum(self.rows_by_reduce)

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes_by_reduce)

    @classmethod
    def from_events(cls, events: Sequence[dict],
                    shuffle_id: int) -> "MapOutputStatistics":
        """Rebuild the statistics offline from ShuffleWrite event-log
        records (tools/history_report.py's path; the in-engine path
        reads ShuffleManager.map_output_statistics instead). JSON
        round-trips dict keys as strings, hence the int() parses."""
        detail: Dict[Tuple[int, int], Tuple[int, int]] = {}
        nparts = 0
        for rec in events:
            if (rec.get("event") != "ShuffleWrite"
                    or rec.get("shuffle_id") != shuffle_id):
                continue
            mid = int(rec.get("map_id", 0))
            rrows = rec.get("reduce_rows") or {}
            rbytes = rec.get("reduce_bytes") or {}
            for rid_s, rows in rrows.items():
                rid = int(rid_s)
                nparts = max(nparts, rid + 1)
                detail[(mid, rid)] = (int(rows),
                                      int(rbytes.get(rid_s, 0) or 0))
        rows_by = [0] * nparts
        bytes_by = [0] * nparts
        for (_mid, rid), (rows, nbytes) in detail.items():
            rows_by[rid] += rows
            bytes_by[rid] += nbytes
        return cls(shuffle_id, nparts, rows_by, bytes_by, detail)


class ReplicaStore:
    """Buddy copies of OTHER workers' completed map output, keyed
    ``(origin_endpoint, shuffle_id, map_id, reduce_id)`` — origin is
    part of the key because map ids are only unique per worker (every
    worker numbers maps from the same ``attempt << 20`` base), so
    merging replicas into the host store would silently collide.
    Replicas never feed normal fetches, statistics, or local reads;
    they serve only origin-addressed replica fetches (transport
    MAGIC_FETCH_REPL) issued by a reader whose pull from the origin
    failed terminally. Entries keep their integrity framing so the
    checksum travels with the bytes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._blocks: Dict[Tuple[str, int, int, int], bytes] = {}
        #: (origin, shuffle_id) -> {reduce_id: (map ids...)} — what a
        #: COMPLETE replica set contains, published by the origin only
        #: AFTER its replica pushes drained. Replica pushes are
        #: best-effort (a dead buddy or timeout silently drops one), so
        #: without the manifest a buddy fetch could serve a partial
        #: partition as if it were whole. No manifest, or a manifest
        #: block missing from the store -> no coverage -> the reader
        #: falls back to stage retry.
        self._manifests: Dict[Tuple[str, int],
                              Dict[int, Tuple[int, ...]]] = {}
        self.bytes_stored = 0
        self.blocks_stored = 0

    def put(self, origin: str, shuffle_id: int, map_id: int,
            reduce_id: int, framed: bytes) -> None:
        with self._lock:
            key = (origin, shuffle_id, map_id, reduce_id)
            prev = self._blocks.get(key)
            self._blocks[key] = framed
            self.bytes_stored += len(framed) - (len(prev) if prev else 0)
            if prev is None:
                self.blocks_stored += 1

    def put_manifest(self, origin: str, shuffle_id: int,
                     manifest: Dict[int, Tuple[int, ...]]) -> None:
        with self._lock:
            self._manifests[(origin, shuffle_id)] = {
                int(r): tuple(sorted(ms))
                for r, ms in manifest.items()}

    def coverage(self, origin: str, shuffle_id: int, reduce_id: int
                 ) -> Optional[List[Tuple[int, bytes]]]:
        """The COMPLETE replica set for one (origin, reduce) — (map_id,
        framed) in map order — or None when this store cannot vouch for
        completeness (no manifest from the origin, or a manifest block
        that never arrived). An empty list is a real answer: the origin
        produced no blocks for this partition."""
        with self._lock:
            man = self._manifests.get((origin, shuffle_id))
            if man is None:
                return None
            out: List[Tuple[int, bytes]] = []
            for map_id in man.get(reduce_id, ()):
                framed = self._blocks.get(
                    (origin, shuffle_id, map_id, reduce_id))
                if framed is None:
                    return None
                out.append((map_id, framed))
            return out

    def drop(self, origin: str, shuffle_id: int, map_id: int,
             reduce_id: int) -> None:
        with self._lock:
            prev = self._blocks.pop(
                (origin, shuffle_id, map_id, reduce_id), None)
            if prev is not None:
                self.bytes_stored -= len(prev)
                self.blocks_stored -= 1

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for k in [k for k in self._blocks if k[1] == shuffle_id]:
                self.bytes_stored -= len(self._blocks[k])
                self.blocks_stored -= 1
                del self._blocks[k]
            for k in [k for k in self._manifests if k[1] == shuffle_id]:
                del self._manifests[k]

    def rename_shuffle(self, old_id: int, new_id: int) -> None:
        with self._lock:
            for k in [k for k in self._blocks if k[1] == old_id]:
                self._blocks[(k[0], new_id, k[2], k[3])] = \
                    self._blocks.pop(k)
            for k in [k for k in self._manifests if k[1] == old_id]:
                self._manifests[(k[0], new_id)] = \
                    self._manifests.pop(k)

    def clear(self) -> None:
        """Drop everything — replicas have no cross-job value (shuffle
        ids are fresh per attempt), and a rejoined worker process
        restarts its shuffle-id counter, so stale entries from an
        earlier incarnation could otherwise collide with new sids."""
        with self._lock:
            self._blocks.clear()
            self._manifests.clear()
            self.bytes_stored = 0
            self.blocks_stored = 0


class ShuffleManager:
    """getWriter/getReader surface over the mode-selected store."""

    def __init__(self, conf: Optional[SrtConf] = None,
                 num_threads: int = 4):
        self.conf = conf or active_conf()
        self.mode = self.conf.get(SHUFFLE_MODE).upper()  # MESH|MULTITHREADED|CACHE_ONLY
        self.codec = self.conf.get(SHUFFLE_COMPRESS).lower()
        self.compress = self.codec != "none"
        from ..conf import INTEGRITY_CHECKSUM
        self.verify_checksums = self.conf.get(INTEGRITY_CHECKSUM)
        from ..conf import (SHUFFLE_PUSH_ENABLED, SHUFFLE_PUSH_LOCAL_BYPASS)
        #: push-based shuffle only applies to the serialized-block mode;
        #: CACHE_ONLY never leaves the process and MESH shuffles inside
        #: the compiled program
        self.push_enabled = (self.conf.get(SHUFFLE_PUSH_ENABLED)
                             and self.mode == "MULTITHREADED")
        self.local_bypass = self.conf.get(SHUFFLE_PUSH_LOCAL_BYPASS)
        self.catalog = ShuffleBlockCatalog()
        self.host_store = HostBlockStore()
        self.segments = SegmentStore()
        self.replicas = ReplicaStore()
        #: this process's shuffle-server endpoint ("host:port"), set by
        #: ShuffleBlockServer — the ORIGIN stamped on every pushed block
        #: (map ids are only unique per peer, so segment entries key on
        #: (origin, map_id))
        self.local_endpoint: Optional[str] = None
        self._pusher = None
        #: bytes handed through the zero-copy local channel instead of
        #: serializer+socket+deserializer (shuffleBytesBypassed)
        self.bypassed_bytes = 0
        #: shuffles with a corrupt-at-rest block: their outputs must
        #: never be served or reused (stage-level reuse of a poisoned
        #: sid fails over to a whole-job retry that regenerates them)
        self._poisoned_sids: set = set()
        self.integrity_failures = 0
        self._pool = cf.ThreadPoolExecutor(max_workers=num_threads)
        self._registered: Dict[int, int] = {}  # shuffle_id -> num_parts
        #: (shuffle_id, reduce_id) -> rows written (AQE statistics — the
        #: MapOutputStatistics the reference's AQE reads from Spark)
        #: rows per (shuffle, map, reduce): replays overwrite their
        #: own map's contribution instead of double-counting
        self._part_rows: Dict[Tuple[int, int, int], int] = {}
        #: exact serialized bytes per (shuffle, map, reduce) — recorded
        #: at write time (CACHE_ONLY estimates from device buffers);
        #: the byte half of MapOutputStatistics
        self._part_bytes: Dict[Tuple[int, int, int], int] = {}
        #: running per-(shuffle, reduce) sums maintained at write time —
        #: partition_row_counts/partition_byte_counts read these in O(n)
        #: instead of scanning every (map, reduce) entry per call
        self._reduce_rows: Dict[Tuple[int, int], int] = {}
        self._reduce_bytes: Dict[Tuple[int, int], int] = {}
        self.write_metrics = ShuffleWriteMetrics()
        self._lock = threading.Lock()

    # --- lifecycle ---
    def register_shuffle(self, shuffle_id: int, num_partitions: int) -> None:
        with self._lock:
            self._registered[shuffle_id] = num_partitions

    def unregister_shuffle(self, shuffle_id: int) -> None:
        self.catalog.remove_shuffle(shuffle_id)
        self.host_store.remove_shuffle(shuffle_id)
        self.segments.remove_shuffle(shuffle_id)
        self.replicas.remove_shuffle(shuffle_id)
        with self._lock:
            self._registered.pop(shuffle_id, None)
            self._poisoned_sids.discard(shuffle_id)
            for k in [k for k in self._part_rows if k[0] == shuffle_id]:
                del self._part_rows[k]
            for k in [k for k in self._part_bytes if k[0] == shuffle_id]:
                del self._part_bytes[k]
            for d in (self._reduce_rows, self._reduce_bytes):
                for k in [k for k in d if k[0] == shuffle_id]:
                    del d[k]

    # --- integrity ---
    def is_poisoned(self, shuffle_id: int) -> bool:
        with self._lock:
            return shuffle_id in self._poisoned_sids

    def quarantine_block(self, block: BlockId, reason: str = "") -> None:
        """A stored block failed verification: drop it and poison its
        shuffle so no consumer can ever read a partial partition — the
        ONLY safe recoveries are stage rerun / whole-job retry, both of
        which refuse poisoned state and regenerate from scratch."""
        import logging
        self.host_store.remove_block(block)
        with self._lock:
            self._poisoned_sids.add(block[0])
            self.integrity_failures += 1
        logging.getLogger("spark_rapids_tpu.shuffle").warning(
            "quarantined corrupt shuffle block %s%s", block,
            f": {reason}" if reason else "")

    def rename_shuffle(self, old_id: int, new_id: int) -> int:
        """Re-key every surviving block (and its AQE row stats) from
        ``old_id`` to ``new_id`` — stage-level recovery reuses a prior
        attempt's completed map outputs under the re-planned exchange's
        fresh shuffle id instead of recomputing them."""
        moved = self.host_store.rename_shuffle(old_id, new_id)
        self.segments.rename_shuffle(old_id, new_id)
        self.replicas.rename_shuffle(old_id, new_id)
        with self._lock:
            if old_id in self._poisoned_sids:  # defensive: reuse of a
                self._poisoned_sids.discard(old_id)  # poisoned sid is
                self._poisoned_sids.add(new_id)      # refused upstream
            if old_id in self._registered:
                self._registered[new_id] = self._registered.pop(old_id)
            for k in [k for k in self._part_rows if k[0] == old_id]:
                self._part_rows[(new_id, k[1], k[2])] = \
                    self._part_rows.pop(k)
            for k in [k for k in self._part_bytes if k[0] == old_id]:
                self._part_bytes[(new_id, k[1], k[2])] = \
                    self._part_bytes.pop(k)
            for d in (self._reduce_rows, self._reduce_bytes):
                for k in [k for k in d if k[0] == old_id]:
                    d[(new_id, k[1])] = d.pop(k)
        return moved

    def partition_row_counts(self, shuffle_id: int) -> List[int]:
        """Rows per reduce partition (valid once the map side wrote).
        Reads the running per-reduce sums maintained at write time —
        O(partitions), not O(all blocks ever written)."""
        n = self.num_partitions(shuffle_id)
        with self._lock:
            return [self._reduce_rows.get((shuffle_id, r), 0)
                    for r in range(n)]

    def partition_byte_counts(self, shuffle_id: int) -> List[int]:
        """Serialized bytes per reduce partition (CACHE_ONLY: device
        buffer estimate)."""
        n = self.num_partitions(shuffle_id)
        with self._lock:
            return [self._reduce_bytes.get((shuffle_id, r), 0)
                    for r in range(n)]

    def map_output_statistics(self, shuffle_id: int,
                              map_ids: Optional[set] = None
                              ) -> MapOutputStatistics:
        """Exact per-(map, reduce) rows/bytes for this process's map
        outputs of ``shuffle_id``. ``map_ids`` restricts the view to a
        subset of maps — speculation reports only the maps a worker WON
        so losing duplicates never reach the global statistics."""
        n = self.num_partitions(shuffle_id)
        detail: Dict[Tuple[int, int], Tuple[int, int]] = {}
        with self._lock:
            for (sid, mid, rid), rows in self._part_rows.items():
                if sid != shuffle_id or rid >= n:
                    continue
                if map_ids is not None and mid not in map_ids:
                    continue
                detail[(mid, rid)] = (
                    rows, self._part_bytes.get((sid, mid, rid), 0))
        rows_by = [0] * n
        bytes_by = [0] * n
        for (_mid, rid), (rows, nbytes) in detail.items():
            rows_by[rid] += rows
            bytes_by[rid] += nbytes
        return MapOutputStatistics(shuffle_id, n, rows_by, bytes_by,
                                   detail)

    def num_partitions(self, shuffle_id: int) -> int:
        return self._registered[shuffle_id]

    def received_statistics(self, shuffle_id: int) -> MapOutputStatistics:
        """Receive-side view: exact per-(map, reduce) sizes of every
        pushed entry, read straight from the segment index."""
        return self.segments.statistics(shuffle_id,
                                        self.num_partitions(shuffle_id))

    # --- push path ---
    def _get_pusher(self):
        if self._pusher is None:
            from .transport import BlockPusher
            with self._lock:
                if self._pusher is None:
                    self._pusher = BlockPusher()
        return self._pusher

    def push_map_output(self, shuffle_id: int, map_id: int,
                        route: Dict[int, str], who: str = "") -> int:
        """Eagerly replicate this map's freshly serialized blocks to
        the endpoints that own their reduce partitions (``route``:
        reduce_id -> endpoint), so the reduce-side fetch overlaps the
        remaining map work. Push is REPLICATION — the origin keeps its
        blocks, a failed push silently degrades to the pull path, and
        self-owned partitions are skipped (they read through the local
        short-circuit, no copy needed). Returns blocks enqueued."""
        if not self.push_enabled or self.mode != "MULTITHREADED":
            return 0
        origin = self.local_endpoint
        if not origin:
            return 0  # no server running: nothing can address us back
        pusher = self._get_pusher()
        pushed = 0
        for reduce_id, endpoint in route.items():
            if not endpoint or endpoint == origin:
                continue
            block = (shuffle_id, map_id, reduce_id)
            framed = self.host_store.get(block)
            if framed is None:
                continue  # empty partition for this map
            with self._lock:
                rows = self._part_rows.get(block, 0)
            pusher.push(endpoint, shuffle_id, reduce_id, map_id, rows,
                        framed, origin, who=who)
            pushed += 1
        return pushed

    def replicate_map_output(self, shuffle_id: int, map_id: int,
                             buddy: str, who: str = "") -> int:
        """Conf-gated k=2 durability (srt.shuffle.replication.factor):
        push EVERY block of this completed map — all reduce partitions,
        including the ones this worker owns — to ``buddy``'s replica
        store, so a hard kill of this worker degrades to a buddy fetch
        instead of a stage re-execution. Reuses the eager-push framing
        and integrity checksums; a failed replica push silently leaves
        that block at k=1 (stage retry still covers it). Returns blocks
        enqueued; the caller's drain covers them."""
        if self.mode != "MULTITHREADED":
            return 0
        origin = self.local_endpoint
        if not origin or not buddy or buddy == origin:
            return 0
        pusher = self._get_pusher()
        pushed = 0
        for block in sorted(self.host_store.blocks_for_map(shuffle_id,
                                                           map_id)):
            framed = self.host_store.get(block)
            if framed is None:
                continue
            with self._lock:
                rows = self._part_rows.get(block, 0)
            pusher.push(buddy, shuffle_id, block[2], map_id, rows,
                        framed, origin, who=who, replica=True)
            pushed += 1
        return pushed

    def publish_replica_manifest(self, shuffle_id: int, buddy: str,
                                 timeout_s: float = 30.0) -> bool:
        """After this shuffle's replica pushes drained: tell ``buddy``
        exactly which blocks a COMPLETE replica set of this origin
        contains ({reduce: (map ids...)}, read from the host store).
        The buddy only answers replica fetches for partitions where it
        holds every manifest block — so a silently dropped best-effort
        push degrades coverage to none (stage retry) instead of to a
        partial partition (wrong rows). Synchronous single attempt;
        False means the buddy never learned of these replicas."""
        if self.mode != "MULTITHREADED":
            return False
        origin = self.local_endpoint
        if not origin or not buddy or buddy == origin:
            return False
        with self._lock:
            nparts = self._registered.get(shuffle_id)
        if nparts is None or self.is_poisoned(shuffle_id):
            return False
        manifest = {
            rid: tuple(b[1] for b in self.host_store.blocks_for_reduce(
                shuffle_id, rid))
            for rid in range(nparts)}
        import pickle
        framed = integrity.wrap(pickle.dumps(manifest))
        from .transport import _MANIFEST_MAP_ID, _push_once
        try:
            return _push_once(buddy, shuffle_id, 0, _MANIFEST_MAP_ID,
                              0, framed, origin, timeout_s,
                              replica=True)
        except OSError:
            return False

    def migrate_blocks(self, target: str, deadline: float) -> List[int]:
        """Graceful-decommission block migration: replica-push every
        registered, non-poisoned shuffle's host-store blocks (this
        worker's own completed map output — received push segments need
        no migration, their origins stay authoritative) to ``target``,
        stopping at ``deadline`` (time.monotonic). Returns the shuffle
        ids migrated and emits one BlockMigrated event per shuffle; the
        caller must drain the pusher and then publish_replica_manifest
        for each returned sid — without the manifest the buddy will
        never vouch for (or serve) these replicas."""
        origin = self.local_endpoint
        if (self.mode != "MULTITHREADED" or not origin or not target
                or target == origin):
            return []
        from ..obs import events as _events
        pusher = self._get_pusher()
        migrated: List[int] = []
        with self._lock:
            registered = dict(self._registered)
        for sid, nparts in sorted(registered.items()):
            if self.is_poisoned(sid):
                continue
            moved = 0
            for rid in range(nparts):
                if time.monotonic() >= deadline:
                    break
                for block in self.host_store.blocks_for_reduce(sid, rid):
                    framed = self.host_store.get(block)
                    if framed is None:
                        continue
                    with self._lock:
                        rows = self._part_rows.get(block, 0)
                    pusher.push(target, sid, rid, block[1], rows,
                                framed, origin, who="decommission",
                                replica=True)
                    moved += 1
            if time.monotonic() >= deadline:
                break
            if moved:
                _events.emit("BlockMigrated", shuffle_id=sid,
                             blocks=moved, target=target, origin=origin)
            migrated.append(sid)
        return migrated

    def drain_pushes(self, timeout_s: float = 30.0) -> bool:
        """Block until every enqueued push acked, failed, or timed out
        — called before the stage barrier so a released reducer sees
        all successful pushes in its segment. False = timed out with
        pushes still in flight (harmless: readers snapshot + exclude,
        so a late push is simply ignored and its block pulls)."""
        if self._pusher is None:
            return True
        return self._pusher.drain(timeout_s)

    # --- write path ---
    def write_map_output(self, shuffle_id: int, map_id: int,
                         partitions: Sequence[ColumnarBatch],
                         local_ok: bool = False) -> int:
        """One map task's output: partitions[i] goes to reduce i.
        Returns serialized bytes written (0 in CACHE_ONLY mode).

        ``local_ok=True`` asserts every consumer of this shuffle runs in
        THIS process (driver-local session) — with the push locality
        bypass on, MULTITHREADED writes then hand the live batch through
        the device catalog (zero-copy local channel) instead of
        serializer+socket+deserializer, counted as bypassed bytes."""
        fault_point("shuffle.write", f"sid={shuffle_id};map={map_id};")
        from ..robustness.admission import check_current_query
        check_current_query()  # cancelled query: skip the whole write
        t0 = time.perf_counter_ns()
        bytes_before = self.write_metrics.bytes_written
        bypass = (local_ok and self.mode == "MULTITHREADED"
                  and self.push_enabled and self.local_bypass)
        bypassed_nb = 0
        futures = []
        local_rows: Dict[int, int] = {}
        local_bytes: Dict[int, int] = {}
        for reduce_id, batch in enumerate(partitions):
            if batch is None or int(batch.num_rows) == 0:
                continue
            local_rows[reduce_id] = int(batch.num_rows)
            block = (shuffle_id, map_id, reduce_id)
            if self.mode == "CACHE_ONLY" or bypass:
                from ..memory.spill import batch_nbytes
                nb = batch_nbytes(batch)
                local_bytes[reduce_id] = nb
                self.catalog.add(block, batch)
                self.write_metrics.rows_written += int(batch.num_rows)
                self.write_metrics.blocks_written += 1
                if bypass:
                    bypassed_nb += nb
            else:  # MULTITHREADED (MESH writes never reach here)
                futures.append((reduce_id, self._pool.submit(
                    self._serialize_one, block, batch)))
        for reduce_id, f in futures:
            local_bytes[reduce_id] = f.result()
        with self._lock:
            self.bypassed_bytes += bypassed_nb
            for reduce_id, rows in local_rows.items():
                key = (shuffle_id, map_id, reduce_id)
                tot = (shuffle_id, reduce_id)
                nb = local_bytes.get(reduce_id, 0)
                # running per-reduce sums: a replayed map replaces its
                # own prior contribution instead of double-counting
                self._reduce_rows[tot] = (self._reduce_rows.get(tot, 0)
                                          + rows
                                          - self._part_rows.get(key, 0))
                self._reduce_bytes[tot] = (
                    self._reduce_bytes.get(tot, 0) + nb
                    - self._part_bytes.get(key, 0))
                self._part_rows[key] = rows
                self._part_bytes[key] = nb
        dt_ns = time.perf_counter_ns() - t0
        self.write_metrics.write_time_ns += dt_ns
        wrote = self.write_metrics.bytes_written - bytes_before
        from ..obs import events as _events
        _events.emit("ShuffleWrite", shuffle_id=shuffle_id,
                     map_id=map_id, blocks=len(local_rows),
                     rows=sum(local_rows.values()), bytes=wrote,
                     write_time_ns=dt_ns,
                     reduce_rows={str(r): v
                                  for r, v in sorted(local_rows.items())},
                     reduce_bytes={str(r): v
                                   for r, v in sorted(local_bytes.items())})
        return wrote

    def _serialize_one(self, block: BlockId, batch: ColumnarBatch) -> int:
        data = serialize_batch(batch, compress=self.compress,
                               codec=self.codec)
        self.host_store.put(block, data)
        from ..obs import registry as _registry
        _registry.observe("shuffle_block_bytes", len(data), "bytes")
        with self._lock:  # writer pool threads race on the counters
            self.write_metrics.rows_written += int(batch.num_rows)
            self.write_metrics.blocks_written += 1
            self.write_metrics.bytes_written += len(data)
        return len(data)

    # --- read path ---
    def read_partition(self, shuffle_id: int, reduce_id: int,
                       map_mod=None) -> Iterator[ColumnarBatch]:
        """All map outputs for one reduce partition, in map order.
        ``map_mod=(s, S)`` keeps only blocks with map_id % S == s — a
        skewed reduce partition splits into S disjoint map slices."""
        fault_point("shuffle.read", f"sid={shuffle_id};reduce={reduce_id};")
        if self.is_poisoned(shuffle_id):
            raise integrity.DataCorruption(
                f"shuffle {shuffle_id} quarantined after a corrupt "
                f"block; partition {reduce_id} is incomplete")
        def keep(map_id: int) -> bool:
            return map_mod is None or map_id % map_mod[1] == map_mod[0]
        if self.mode == "CACHE_ONLY":
            for block in self.catalog.blocks_for_reduce(shuffle_id,
                                                        reduce_id):
                if keep(block[1]):
                    yield from self.catalog.get(block)
            return
        # zero-copy locality bypass: blocks the writer handed through
        # the device catalog (never serialized) serve directly
        for block in self.catalog.blocks_for_reduce(shuffle_id,
                                                    reduce_id):
            if keep(block[1]):
                yield from self.catalog.get(block)
        blocks = [b for b in self.host_store.blocks_for_reduce(
            shuffle_id, reduce_id) if keep(b[1])]
        futures = [self._pool.submit(self._deserialize_one, b)
                   for b in blocks]
        from ..robustness.admission import check_current_query
        for f in futures:
            # abort the fan-in between blocks when the consuming
            # query was cancelled or blew its deadline
            check_current_query()
            batch = f.result()
            if batch is not None:
                yield batch

    def _deserialize_one(self, block: BlockId) -> Optional[ColumnarBatch]:
        framed = self.host_store.get(block)
        if framed is None:
            return None
        if not self.verify_checksums:
            return deserialize_batch(integrity.strip(framed))
        try:
            data = integrity.unwrap(
                framed, what=f"shuffle block sid={block[0]} "
                             f"map={block[1]} reduce={block[2]}")
        except integrity.DataCorruption:
            # local read of a corrupt-at-rest block: quarantine and
            # surface — returning garbage rows is never an option
            self.quarantine_block(block, reason="local read")
            raise
        return deserialize_batch(data)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


_MANAGER: Optional[ShuffleManager] = None
_MANAGER_LOCK = threading.Lock()


def shuffle_manager() -> ShuffleManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is None:
            _MANAGER = ShuffleManager()
        return _MANAGER


def reset_shuffle_manager(conf: Optional[SrtConf] = None) -> ShuffleManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is not None:
            _MANAGER.shutdown()
        _MANAGER = ShuffleManager(conf)
        return _MANAGER


# ---------------------------------------------------------------------------
# heartbeat registry (RapidsShuffleHeartbeatManager role)
# ---------------------------------------------------------------------------

@dataclass
class ExecutorInfo:
    executor_id: str
    endpoint: str
    last_heartbeat: float = field(default_factory=time.monotonic)


class ShuffleHeartbeatManager:
    """Driver-side registry of live shuffle peers. In the reference this
    bootstraps UCX endpoint exchange (Plugin.scala:292-303); here it
    carries host:port endpoints for the DCN block-fetch path and lets
    the planner exclude dead peers."""

    def __init__(self, timeout_s: Optional[float] = None):
        if timeout_s is None:
            # standalone default from conf; the cluster driver passes
            # its own srt.cluster.heartbeatTimeoutSec through instead.
            # srt.shuffle.heartbeat.timeoutSec is a deprecated alias
            # that forwards to the same key.
            from ..conf import HEARTBEAT_TIMEOUT_S, active_conf
            timeout_s = active_conf().get(HEARTBEAT_TIMEOUT_S)
        self.timeout_s = timeout_s
        self._executors: Dict[str, ExecutorInfo] = {}
        #: every endpoint an executor EVER served from -> executor_id;
        #: a peer holding a stale endpoint resolves the executor's
        #: current one through this (fetch failover)
        self._aliases: Dict[str, str] = {}
        self._lock = threading.Lock()

    def register(self, executor_id: str, endpoint: str,
                 prior_endpoint: Optional[str] = None
                 ) -> List[ExecutorInfo]:
        """Returns the current peer list (what a new executor needs to
        open connections). ``prior_endpoint`` declares this executor
        the REPLACEMENT of whichever executor last served that
        endpoint (worker rejoin): the predecessor is dropped and every
        alias it ever held re-points at the replacement, so
        ``resolve(old_endpoint)`` reroutes in-flight fetches to the
        new incarnation."""
        with self._lock:
            if prior_endpoint is not None:
                old_eid = self._aliases.get(prior_endpoint)
                if old_eid is not None and old_eid != executor_id:
                    self._executors.pop(old_eid, None)
                    for ep, eid in list(self._aliases.items()):
                        if eid == old_eid:
                            self._aliases[ep] = executor_id
            self._executors[executor_id] = ExecutorInfo(executor_id,
                                                        endpoint)
            self._aliases[endpoint] = executor_id
            return [e for e in self._executors.values()
                    if e.executor_id != executor_id]

    def deregister(self, executor_id: str) -> None:
        """Forget a gracefully-decommissioned executor. Its aliases are
        kept (resolving them returns None until a replacement
        re-registers over one of them)."""
        with self._lock:
            self._executors.pop(executor_id, None)

    def owner_of(self, endpoint: str) -> Optional[str]:
        """Executor id that ever served ``endpoint`` (live or not) —
        lets the driver fence a rejoining worker's predecessor."""
        with self._lock:
            return self._aliases.get(endpoint)

    def heartbeat(self, executor_id: str,
                  endpoint: Optional[str] = None) -> bool:
        with self._lock:
            info = self._executors.get(executor_id)
            if info is None:
                return False  # unknown: executor must re-register
            info.last_heartbeat = time.monotonic()
            if endpoint and endpoint != info.endpoint:
                # shuffle server moved (restart on a new port): keep the
                # old endpoint as an alias so in-flight fetches fail over
                info.endpoint = endpoint
                self._aliases[endpoint] = executor_id
            return True

    def resolve(self, endpoint: str) -> Optional[str]:
        """Current endpoint of the live executor that served
        ``endpoint`` at any point — None when that executor is unknown
        or has gone silent past the timeout."""
        now = time.monotonic()
        with self._lock:
            eid = self._aliases.get(endpoint)
            info = self._executors.get(eid) if eid else None
            if info is None or now - info.last_heartbeat > self.timeout_s:
                return None
            return info.endpoint

    def live_executors(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [e.executor_id for e in self._executors.values()
                    if now - e.last_heartbeat <= self.timeout_s]

    def is_alive(self, executor_id: str) -> bool:
        """Heartbeat-based liveness — the slow-vs-dead discriminator
        speculation needs: a straggler still heartbeats (speculate), a
        dead worker does not (evict + stage retry instead)."""
        with self._lock:
            info = self._executors.get(executor_id)
            if info is None:
                return False
            return time.monotonic() - info.last_heartbeat <= self.timeout_s

    def expire_dead(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            dead = [eid for eid, e in self._executors.items()
                    if now - e.last_heartbeat > self.timeout_s]
            for eid in dead:
                del self._executors[eid]
            return dead


# ---------------------------------------------------------------------------
# map-output registry (MapOutputTracker role, stage-level recovery)
# ---------------------------------------------------------------------------

class MapOutputRegistry:
    """Driver-side record of which shuffles' map phases COMPLETED in
    the current job attempt (Spark's MapOutputTracker role, reduced to
    what stage-level recovery needs). Shuffles are keyed by their
    traversal POSITION in the physical plan — shuffle ids are fresh per
    attempt, positions are stable across re-plans of the same job —
    and a position is complete once its barrier released (every
    worker's map side wrote before any barrier reply goes out)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._complete: Dict[int, int] = {}  # pos -> shuffle_id
        #: exact per-(map, reduce) sizes reported by workers at barrier
        #: time: (shuffle_id, worker) -> {(map_id, reduce_id):
        #: (rows, bytes)} — the registry half of MapOutputStatistics
        self._map_stats: Dict[Tuple[int, int],
                              Dict[Tuple[int, int],
                                   Tuple[int, int]]] = {}
        #: first-result-wins commits under speculation:
        #: shuffle_id -> {logical_shard: (worker, (map_ids...))}
        self._commits: Dict[int, Dict[int, Tuple[int,
                                                 Tuple[int, ...]]]] = {}

    def start_attempt(self) -> None:
        with self._lock:
            self._complete.clear()
            self._map_stats.clear()
            self._commits.clear()

    # --- map-output statistics (exact sizes, reported at barriers) ---
    def record_map_stats(self, shuffle_id: int, worker: int,
                         detail: Dict[Tuple[int, int],
                                      Tuple[int, int]]) -> None:
        with self._lock:
            self._map_stats[(shuffle_id, worker)] = dict(detail or {})

    def map_output_statistics(self, shuffle_id: int,
                              num_partitions: int) -> MapOutputStatistics:
        """Driver-side merged view across every reporting worker,
        restricted to COMMITTED maps when speculation produced
        duplicates (first result wins; losers never count)."""
        with self._lock:
            commits = self._commits.get(shuffle_id)
            won: Optional[set] = None
            if commits:
                won = {(worker, mid)
                       for worker, mids in commits.values()
                       for mid in mids}
            detail: Dict[Tuple[int, int], Tuple[int, int]] = {}
            for (sid, worker), d in self._map_stats.items():
                if sid != shuffle_id:
                    continue
                for (mid, rid), v in d.items():
                    if won is not None and (worker, mid) not in won:
                        continue
                    detail[(mid, rid)] = v
        rows_by = [0] * num_partitions
        bytes_by = [0] * num_partitions
        for (_mid, rid), (rows, nbytes) in detail.items():
            if rid < num_partitions:
                rows_by[rid] += rows
                bytes_by[rid] += nbytes
        return MapOutputStatistics(shuffle_id, num_partitions, rows_by,
                                   bytes_by, detail)

    # --- first-result-wins commits (speculative execution dedup) ---
    def try_commit_maps(self, shuffle_id: int, logical_shard: int,
                        worker: int,
                        map_ids: Sequence[int]) -> Tuple[int,
                                                         Tuple[int, ...]]:
        """Commit ``worker`` as the producer of ``logical_shard``'s map
        outputs unless another worker already committed — the
        first-result-wins rule. Returns the WINNING (worker, map_ids),
        which is the caller's when it won the race."""
        with self._lock:
            by_shard = self._commits.setdefault(shuffle_id, {})
            cur = by_shard.get(logical_shard)
            if cur is None:
                cur = by_shard[logical_shard] = (worker, tuple(map_ids))
            return cur

    def committed_maps(self, shuffle_id: int) -> Dict[int,
                                                      Tuple[int,
                                                            Tuple[int,
                                                                  ...]]]:
        with self._lock:
            return dict(self._commits.get(shuffle_id, {}))

    def mark_complete(self, pos: int, shuffle_id: int) -> None:
        if pos < 0:
            return
        with self._lock:
            fresh = self._complete.get(pos) != shuffle_id
            self._complete[pos] = shuffle_id
        if fresh:
            # once per barrier release, not once per worker reply
            from ..obs import events as _events
            _events.emit("StageCompleted", position=pos,
                         shuffle_id=shuffle_id)

    def complete_positions(self) -> List[int]:
        with self._lock:
            return sorted(self._complete)
