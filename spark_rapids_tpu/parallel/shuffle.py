"""SPMD shuffle: hash-partition exchange as an ICI all-to-all.

The reference's shuffle is p2p-RPC-shaped: a catalog of device-resident
blocks served over UCX ActiveMessages with bounce buffers
(RapidsShuffleClient.scala:169, UCX.scala:104-115). A TPU pod's ICI is
SPMD-program-shaped, so shuffle is reformulated (SURVEY §7 hard-part #5)
as a collective: every shard packs its rows into a dense
``(num_shards, slot)`` layout by destination (partition.py), one
``lax.all_to_all`` swaps the blocks, and each shard flattens what it
received. XLA schedules the transfer over ICI links; no host round-trip,
no serialization — the columnar buffers themselves are the wire format
(strings travel as fixed-width byte lanes).

Sharded batches cross the shard_map boundary in **stacked** form: every
leaf gains a leading ``num_shards`` dim (``stack_shards``), the mesh
sharding splits that dim, and each shard squeezes its slice back to a
plain ColumnarBatch. This keeps ragged string buffers and the scalar
``num_rows`` well-defined per shard — a plain row-sharding of a string
column's (offsets, chars) pair would not be meaningful.

Not every hash exchange needs the collective at all: when the child's
``output_partitioning`` is already HashPartitioning on the same expr
sequence, rows are on their target shard and the mesh lowering skips
``shuffle_exchange`` entirely (the MESH face of the push-shuffle v2
locality bypass — ``plan/mesh_executor.py:_hash_colocated``, the
``MeshColocationBypass`` event, docs/SHUFFLE.md). The placement
contract that makes this sound: every exchange routes with
``pmod(murmur3(keys), num_shards)`` against the mesh size, so identical
key exprs imply identical placement.

``distributed_aggregate`` is the flagship distributed pipeline: local
partial aggregation, key-hash all-to-all of the *partial states* (far
smaller than raw rows — same motivation as the reference's partial-then-
merge split, GpuAggregateExec.scala:711), then a final local merge. Key
disjointness after the exchange makes shard-local merges globally correct.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax

from .. import shims as _shims
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar.vector import ColumnVector, ColumnarBatch, StringColumn
from .mesh import DATA_AXIS
from .partition import (PartitionedBatch, flatten_partitions,
                        hash_partition_ids, partition_batch,
                        string_from_padded)


def stack_shards(batches: Sequence[ColumnarBatch]):
    """Stack per-shard batches into one pytree with leading shard dim.

    All shards must share schema and capacities (pad to a common capacity
    bucket first). The result is placed with ``P("data")`` on the leading
    dim so each mesh shard holds exactly its own slice.
    """
    norm = [ColumnarBatch(b.columns, b.names,
                          jnp.asarray(b.num_rows, jnp.int32))
            for b in batches]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *norm)


def unstack_shards(stacked) -> List[ColumnarBatch]:
    """Host-side inverse of ``stack_shards``."""
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(n)]


def _squeeze_shard(stacked) -> ColumnarBatch:
    """Inside shard_map: drop the leading (now length-1) shard dim."""
    return jax.tree_util.tree_map(lambda x: x[0], stacked)


def _expand_shard(batch: ColumnarBatch):
    return jax.tree_util.tree_map(lambda x: x[None], batch)


def all_to_all_partitions(pb: PartitionedBatch,
                          axis: str = DATA_AXIS) -> PartitionedBatch:
    """Exchange partition blocks across the mesh axis (inside shard_map).

    Block p on shard s is sent to shard p; afterwards block p on shard s
    holds what shard p sent to s. Counts ride along so receivers know the
    live prefix of each block.
    """
    def x2x(a):
        return lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    cols = jax.tree_util.tree_map(x2x, pb.columns)
    counts = x2x(pb.counts)
    return PartitionedBatch(cols, pb.names, pb.dtypes, counts,
                            pb.slot_capacity)


def shuffle_exchange(batch: ColumnarBatch, key_names: Sequence[str],
                     num_shards: int,
                     slot_capacity: Optional[int] = None,
                     axis: str = DATA_AXIS) -> ColumnarBatch:
    """One shard's view of the shuffle: partition, all_to_all, flatten.

    Call inside ``shard_map``. Output capacity is
    ``num_shards * slot_capacity`` with rows compacted to a live prefix.
    """
    key_cols = [batch.column(n) for n in key_names]
    pids = hash_partition_ids(key_cols, num_shards)
    pb = partition_batch(batch, pids, num_shards, slot_capacity)
    recv = all_to_all_partitions(pb, axis)
    return flatten_partitions(recv)


def all_gather_batch(batch: ColumnarBatch, num_shards: int,
                     axis: str = DATA_AXIS) -> ColumnarBatch:
    """Gather every shard's live rows into one compacted batch.

    Inside shard_map. The broadcast-join build-side primitive: per-shard
    capacity C becomes one batch of capacity num_shards*C (the analogue of
    GpuBroadcastExchangeExec's host-collected broadcast batch,
    GpuBroadcastExchangeExec.scala:352 — here it stays on device and
    rides ICI).
    """
    cap = batch.capacity
    n = num_shards
    counts = lax.all_gather(jnp.asarray(batch.num_rows, jnp.int32), axis)
    pos = jnp.arange(n * cap, dtype=jnp.int32)
    src, within = pos // cap, pos % cap
    slot_valid = within < jnp.take(counts, src)
    order = jnp.argsort(~slot_valid, stable=True).astype(jnp.int32)
    keep = jnp.take(slot_valid, order)
    total = jnp.sum(counts).astype(jnp.int32)

    def ag(a):
        return lax.all_gather(a, axis, axis=0, tiled=True)

    cols = []
    for c in batch.columns:
        if isinstance(c, StringColumn):
            padded = jnp.take(ag(c.padded()), order, axis=0)
            lens = jnp.where(keep, jnp.take(ag(c.lengths()), order), 0)
            valid = keep & jnp.take(ag(c.validity), order)
            cols.append(string_from_padded(padded, lens, valid,
                                           char_capacity=n * c.char_capacity))
        else:
            from ..columnar.decimal128 import Decimal128Column
            valid = keep & jnp.take(ag(c.validity), order)
            if isinstance(c, Decimal128Column):
                hi = jnp.take(ag(c.hi), order)
                lo = jnp.take(ag(c.lo), order)
                cols.append(Decimal128Column(
                    jnp.where(valid, hi, jnp.zeros((), jnp.int64)),
                    jnp.where(valid, lo, jnp.zeros((), jnp.uint64)),
                    valid, c.dtype))
                continue
            data = jnp.take(ag(c.data), order)
            cols.append(ColumnVector(
                jnp.where(valid, data, jnp.zeros((), data.dtype)),
                valid, c.dtype))
    return ColumnarBatch(cols, batch.names, total)


def distributed_aggregate(agg_exec, mesh: Mesh,
                          slot_capacity: Optional[int] = None):
    """Build the jitted SPMD aggregate step for a HashAggregateExec.

    Returns ``step(stacked_batches) -> stacked result`` compiled over
    ``mesh``: each shard partial-aggregates its local rows, partial
    states are exchanged by key hash, and each shard merge-finalizes its
    disjoint key range. Unstacking and concatenating the result shards
    yields the global aggregate.
    """
    n = mesh.shape[DATA_AXIS]
    key_names = agg_exec._key_names

    def shard_step(stacked):
        batch = _squeeze_shard(stacked)
        partial_states = agg_exec._update(batch, jnp.int64(0))
        if not key_names:
            # Global aggregate: every shard's single partial row is
            # gathered everywhere; shard 0 reports the merged result.
            merged = all_gather_batch(partial_states, n)
            out = agg_exec._merge_finalize(merged)
            keep = lax.axis_index(DATA_AXIS) == 0
            out = ColumnarBatch(
                out.columns, out.names,
                jnp.where(keep, out.num_rows, 0).astype(jnp.int32))
        else:
            exchanged = shuffle_exchange(partial_states, key_names, n,
                                         slot_capacity, DATA_AXIS)
            out = agg_exec._merge_finalize(exchanged)
        return _expand_shard(out)

    return jax.jit(
        _shims.shard_map()(shard_step, mesh=mesh,
                      in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
                      check_vma=False))
