"""Scale-test data generation DSL.

Rebuild of the reference's datagen module (datagen/bigDataGen.scala +
ScaleTestDataGen.scala, SURVEY §2.8): declarative table specs with
per-column distributions, deterministic per-(table, column, chunk)
seeding so any chunk regenerates independently (the property the
reference's big-data gen is built around), chunked parquet output, and
canned TPC-H-shaped tables for benchmarks.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .columnar import dtypes as dt
from .plan.host_table import HostColumn, HostTable


@dataclass
class ColumnSpec:
    name: str
    dtype: dt.DType
    dist: str = "uniform"     # uniform | normal | zipf | seq | choice
    lo: float = 0
    hi: float = 100
    mean: float = 0.0
    std: float = 1.0
    alpha: float = 1.5        # zipf skew
    cardinality: int = 1000   # zipf/choice key space
    choices: Optional[List] = None
    null_prob: float = 0.0
    fmt: Optional[str] = None  # string format template, {} = value


@dataclass
class TableSpec:
    name: str
    columns: List[ColumnSpec]
    num_rows: int


def _gen_column(spec: ColumnSpec, table: str, chunk: int, start_row: int,
                n: int) -> HostColumn:
    # deterministic per (table, column, chunk): regenerate any chunk
    # without generating its predecessors. crc32, NOT builtin hash() —
    # hash() is randomized per process (PYTHONHASHSEED) and would make
    # distributed/re-run generation inconsistent.
    import zlib
    seed = zlib.crc32(f"{table}\x00{spec.name}\x00{chunk}".encode())
    rng = np.random.default_rng(seed)
    if spec.dist == "seq":
        vals = np.arange(start_row, start_row + n, dtype=np.int64)
    elif spec.dist == "uniform":
        if getattr(spec.dtype, "is_integral", False) or \
                isinstance(spec.dtype, (dt.DateType, dt.TimestampType)):
            vals = rng.integers(int(spec.lo), int(spec.hi) + 1, n)
        else:
            vals = rng.uniform(spec.lo, spec.hi, n)
    elif spec.dist == "normal":
        vals = rng.normal(spec.mean, spec.std, n)
    elif spec.dist == "zipf":
        # bounded zipf over [0, cardinality)
        raw = rng.zipf(spec.alpha, n)
        vals = (raw - 1) % spec.cardinality
    elif spec.dist == "choice":
        idx = rng.integers(0, len(spec.choices), n)
        vals = np.array([spec.choices[i] for i in idx], dtype=object)
    else:
        raise ValueError(spec.dist)

    mask = np.ones(n, bool)
    if spec.null_prob > 0:
        mask = rng.random(n) >= spec.null_prob

    t = spec.dtype
    if t == dt.STRING:
        fmt = spec.fmt or "{}"
        out = np.array([fmt.format(v) for v in vals], dtype=object)
        return HostColumn(out, mask, t)
    phys = np.dtype(t.physical)
    if isinstance(t, dt.DecimalType):
        out = (np.asarray(vals, np.float64) * 10 ** t.scale).astype(
            np.int64)
    else:
        out = np.asarray(vals).astype(phys)
    out = np.where(mask, out, np.zeros(1, phys))
    return HostColumn(out, mask, t)


def generate_chunk(spec: TableSpec, chunk: int,
                   chunk_rows: int) -> HostTable:
    start = chunk * chunk_rows
    n = min(chunk_rows, spec.num_rows - start)
    cols = [_gen_column(c, spec.name, chunk, start, n)
            for c in spec.columns]
    return HostTable(cols, [c.name for c in spec.columns])


def generate_table(session, spec: TableSpec, out_dir: str,
                   chunk_rows: int = 1 << 20) -> List[str]:
    """Write the table as chunked parquet; returns file paths."""
    from .io.arrow_convert import host_table_to_arrow
    import pyarrow.parquet as pq
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    n_chunks = -(-spec.num_rows // chunk_rows)
    for c in range(n_chunks):
        table = generate_chunk(spec, c, chunk_rows)
        path = os.path.join(out_dir, f"{spec.name}-{c:05d}.parquet")
        pq.write_table(host_table_to_arrow(table), path)
        paths.append(path)
    return paths


# --- canned benchmark tables (TPC-H shapes; BASELINE.md configs) -----------

def lineitem_spec(scale_rows: int) -> TableSpec:
    """The q6/q1 workhorse table."""
    return TableSpec("lineitem", [
        ColumnSpec("l_orderkey", dt.INT64, "zipf", cardinality=scale_rows // 4 + 1),
        ColumnSpec("l_partkey", dt.INT64, "uniform", lo=1, hi=200_000),
        ColumnSpec("l_quantity", dt.FLOAT64, "uniform", lo=1, hi=50),
        ColumnSpec("l_extendedprice", dt.FLOAT64, "uniform", lo=900,
                   hi=105_000),
        ColumnSpec("l_discount", dt.FLOAT64, "choice",
                   choices=[round(x * 0.01, 2) for x in range(11)]),
        ColumnSpec("l_tax", dt.FLOAT64, "choice",
                   choices=[round(x * 0.01, 2) for x in range(9)]),
        ColumnSpec("l_returnflag", dt.STRING, "choice",
                   choices=["A", "N", "R"]),
        ColumnSpec("l_linestatus", dt.STRING, "choice",
                   choices=["O", "F"]),
        ColumnSpec("l_shipdate", dt.DATE, "uniform", lo=8036, hi=10561),
    ], scale_rows)


def orders_spec(scale_rows: int) -> TableSpec:
    return TableSpec("orders", [
        ColumnSpec("o_orderkey", dt.INT64, "seq"),
        ColumnSpec("o_custkey", dt.INT64, "zipf", cardinality=150_000),
        ColumnSpec("o_totalprice", dt.FLOAT64, "uniform", lo=800,
                   hi=600_000),
        ColumnSpec("o_orderdate", dt.DATE, "uniform", lo=8036, hi=10561),
        ColumnSpec("o_orderpriority", dt.STRING, "choice",
                   choices=["1-URGENT", "2-HIGH", "3-MEDIUM",
                            "4-NOT SPECIFIED", "5-LOW"]),
        ColumnSpec("o_shippriority", dt.INT32, "choice", choices=[0]),
    ], scale_rows)
