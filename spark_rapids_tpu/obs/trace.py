"""Span tracer: query → stage → task → operator spans.

A minimal Dapper-style tracer over ``time.perf_counter_ns``. Spans
carry a kind (``query``/``stage``/``task``/``operator``), a parent
link, and free-form attributes; a finished tracer exports the whole
tree as Chrome-trace (catapult) JSON — loadable in ``chrome://tracing``
/ Perfetto, and parseable by ``tools/profile_report.py``.

Tracers are created per query by the session (``srt.eventLog.trace.
enabled``) and handed to operators through ``ExecContext.tracer``; the
disabled path is ``ctx.tracer is None`` — no span allocation, no
clock reads beyond what the metrics layer already pays.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class Span:
    """One finished (or in-flight) span. Timestamps are monotonic
    ``perf_counter_ns`` values, so durations are exact and spans from
    one process share a timeline; wall-clock anchoring lives in the
    tracer's anchor pair (exported as trace metadata), not per span."""

    __slots__ = ("name", "kind", "span_id", "parent_id", "t0_ns",
                 "t1_ns", "attrs", "tid")

    def __init__(self, name: str, kind: str, span_id: int,
                 parent_id: Optional[int], t0_ns: int,
                 attrs: Optional[dict], tid: int):
        self.name = name
        self.kind = kind
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_ns = t0_ns
        self.t1_ns: Optional[int] = None
        self.attrs = attrs
        self.tid = tid

    @property
    def duration_ns(self) -> int:
        return 0 if self.t1_ns is None else self.t1_ns - self.t0_ns

    def __repr__(self):
        return (f"Span({self.kind}:{self.name} id={self.span_id} "
                f"parent={self.parent_id} dur={self.duration_ns}ns)")


class _SpanScope:
    """Context manager returned by :meth:`Tracer.span`."""

    __slots__ = ("tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self.tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        self.tracer._push(self.span)
        return self.span

    def __exit__(self, *exc):
        try:
            self.tracer._pop(self.span)
        finally:
            self.tracer.end(self.span)
        return False


class Tracer:
    """Thread-safe span collector. One per traced query.

    Two usage styles:
    - ``with tracer.span("q", kind="query"): ...`` — pushes onto a
      thread-local stack so nested spans parent automatically;
    - ``s = tracer.begin(name, kind, parent=...); ...; tracer.end(s)``
      — explicit parentage for callers that already maintain their own
      stack (the exec layer's exclusive-time timer stack).

    Cross-process: the driver ships ``tracer.context()`` with each
    cluster job; a worker rebuilds a child tracer from it with
    :meth:`from_context`, so worker spans (a) share the driver's
    ``trace_id``, (b) default-parent under the driver's job span
    (``_remote_parent``), and (c) allocate span ids in a
    pid-namespaced range that cannot collide with other processes.
    Every tracer stamps a monotonic↔wall-clock anchor pair at
    construction; :func:`merge_chrome_traces` uses the anchors to
    clock-align per-process trace files onto one timeline.
    """

    def __init__(self, trace_id: Optional[str] = None,
                 remote_parent: Optional[int] = None):
        self.trace_id = trace_id or os.urandom(8).hex()
        self._remote_parent = remote_parent
        # paired clock reads: anchor_unix_s is the wall-clock time at
        # monotonic instant anchor_mono_ns (per-process alignment key)
        self.anchor_mono_ns = time.perf_counter_ns()
        self.anchor_unix_s = time.time()
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        # span ids are namespaced by pid so ids minted on different
        # processes of one trace never collide when merged
        self._id_base = (os.getpid() & 0x3FFFFF) << 32
        self._next_id = 1
        self._tls = threading.local()

    # --- cross-process context ---
    def context(self, span: Optional[Span] = None) -> dict:
        """Serializable trace context to ship with a remote job: the
        given span (or the calling thread's innermost open scope)
        becomes the remote side's default parent."""
        sid = span.span_id if span is not None else self.current_id()
        return {"trace_id": self.trace_id, "span_id": sid,
                "pid": os.getpid()}

    @classmethod
    def from_context(cls, ctx: Optional[dict]) -> "Tracer":
        """Child tracer parented under a remote span context."""
        if not ctx:
            return cls()
        return cls(trace_id=ctx.get("trace_id"),
                   remote_parent=ctx.get("span_id"))

    # --- explicit API ---
    def begin(self, name: str, kind: str = "span",
              parent: Optional[int] = None,
              attrs: Optional[dict] = None) -> Span:
        """Start a span. ``parent=None`` links to the calling thread's
        innermost open ``span()`` scope (the query span, usually), or
        to the remote parent on a worker-side tracer."""
        if parent is None:
            stack = getattr(self._tls, "stack", None)
            if stack:
                parent = stack[-1].span_id
            else:
                parent = self._remote_parent
        with self._lock:
            sid = self._id_base + self._next_id
            self._next_id += 1
        return Span(name, kind, sid, parent, time.perf_counter_ns(),
                    attrs, threading.get_ident())

    def end(self, span: Span) -> None:
        span.t1_ns = time.perf_counter_ns()
        with self._lock:
            self._spans.append(span)

    # --- scoped API ---
    def span(self, name: str, kind: str = "span",
             parent: Optional[int] = None,
             attrs: Optional[dict] = None) -> _SpanScope:
        return _SpanScope(self, self.begin(name, kind, parent, attrs))

    def _push(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack and span in stack:  # exception-skewed exit order
            stack.remove(span)

    def current_id(self) -> Optional[int]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1].span_id if stack else None

    def instant(self, name: str, attrs: Optional[dict] = None) -> None:
        """Zero-duration marker (Chrome-trace ``ph: i``)."""
        s = self.begin(name, kind="instant", attrs=attrs)
        s.t1_ns = s.t0_ns
        with self._lock:
            self._spans.append(s)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    # --- export ---
    def export_chrome_trace(self) -> str:
        """Chrome-trace (catapult) JSON object format. Every event
        carries the required ``ph``/``ts``/``pid`` fields; ``ts`` is
        microseconds (float) on the monotonic timeline."""
        pid = os.getpid()
        events: List[dict] = []
        for s in self.spans():
            args: Dict = {"span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            if s.attrs:
                args.update(s.attrs)
            if s.kind == "instant":
                events.append({"name": s.name, "cat": s.kind, "ph": "i",
                               "ts": s.t0_ns / 1e3, "pid": pid,
                               "tid": s.tid, "s": "t", "args": args})
                continue
            events.append({"name": s.name, "cat": s.kind, "ph": "X",
                           "ts": s.t0_ns / 1e3,
                           "dur": (s.t1_ns or s.t0_ns) / 1e3
                                  - s.t0_ns / 1e3,
                           "pid": pid, "tid": s.tid, "args": args})
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms",
                           "metadata": {
                               "trace_id": self.trace_id,
                               "pid": pid,
                               "anchor_mono_ns": self.anchor_mono_ns,
                               "anchor_unix_s": self.anchor_unix_s,
                               "remote_parent": self._remote_parent,
                           }})

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.export_chrome_trace())
        return path


def merge_chrome_traces(paths) -> dict:
    """Clock-align and merge per-process Chrome-trace files into one.

    Each file's events sit on that process's private monotonic
    timeline; its metadata anchor pair (``anchor_mono_ns`` at wall
    clock ``anchor_unix_s``) converts them to a shared wall-clock
    timeline: ``ts_wall_us = ts_us + anchor_unix_s*1e6 -
    anchor_mono_ns/1e3``. Events keep their originating ``pid`` so the
    merged view shows one lane per process. Returns the merged
    catapult object (``traceEvents`` sorted by aligned ts)."""
    events: List[dict] = []
    sources: List[dict] = []
    trace_ids = set()
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        meta = doc.get("metadata") or {}
        if meta.get("trace_id"):
            trace_ids.add(meta["trace_id"])
        offset_us = 0.0
        if "anchor_mono_ns" in meta and "anchor_unix_s" in meta:
            offset_us = (meta["anchor_unix_s"] * 1e6
                         - meta["anchor_mono_ns"] / 1e3)
        sources.append({"path": os.path.basename(str(path)),
                        "pid": meta.get("pid"),
                        "offset_us": offset_us,
                        "trace_id": meta.get("trace_id")})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + offset_us
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"trace_id": (sorted(trace_ids)[0]
                                      if len(trace_ids) == 1 else
                                      sorted(trace_ids)),
                         "sources": sources}}


def maybe_tracer(conf) -> Optional[Tracer]:
    """A fresh per-query tracer when ``srt.eventLog.trace.enabled`` is
    on, else None (the zero-overhead disabled path)."""
    from ..conf import TRACE_ENABLED
    if not conf.get(TRACE_ENABLED):
        return None
    return Tracer()
