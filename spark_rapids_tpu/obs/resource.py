"""Background resource sampler: periodic ResourceSample events.

A single daemon thread (per process, ``srt.obs.resource.intervalMs``)
snapshots cheap process-level gauges and emits them to the event log
so the offline profiler can correlate stalls with memory pressure:

- host RSS (``/proc/self/statm``, no psutil dependency);
- device memory in use (``jax.local_devices()[0].memory_stats()``,
  guarded — CPU backends usually return nothing);
- spill-pool occupancy (``memory/spill.py`` catalog stats — read only
  if the process already built a catalog, never instantiates one);
- shuffle fetch-pool queue depth (``parallel/transport.py``);
- live prefetch buffer bytes (``exec/pipeline.py``).

Zero-overhead contract: with the conf at its default (0) or the event
log off, :func:`configure_from_conf` is a no-op — no thread starts,
and nothing in the engine's hot path ever touches this module. The
sampler holds no references into the engine; every probe is a
module-global read guarded against absence.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def _device_bytes_in_use() -> int:
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        return int((stats or {}).get("bytes_in_use", 0))
    except Exception:
        return 0


def sample() -> dict:
    """One snapshot of every probe. Each probe degrades to 0/absent
    rather than raising — sampling must never hurt the engine."""
    s = {"rss_bytes": _rss_bytes(),
         "device_bytes_in_use": _device_bytes_in_use()}
    try:
        from ..memory import spill as _spill
        cat = _spill._CATALOG
        if cat is not None:
            s["spill"] = cat.stats()
    except Exception:
        pass
    try:
        from ..parallel import transport as _transport
        pool = _transport._POOL
        if pool is not None:
            s["fetch_queue_depth"] = pool._q.qsize()
    except Exception:
        pass
    try:
        from ..exec import pipeline as _pipeline
        s["prefetch_buffer_bytes"] = _pipeline.prefetch_buffer_bytes()
    except Exception:
        pass
    return s


class ResourceSampler:
    """Daemon sampling thread; emits one ResourceSample event per
    interval through ``obs.events.emit`` (so samples land in the same
    per-process JSONL as everything else)."""

    def __init__(self, interval_ms: int):
        self.interval_s = max(interval_ms, 1) / 1000.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="srt-resource-sampler", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _run(self) -> None:
        from . import events as _events
        while not self._stop.wait(self.interval_s):
            try:
                _events.emit("ResourceSample", **sample())
            except Exception:
                pass  # flight recorder, never fatal


# --- module-global sampler (the zero-overhead guard) ---
_SAMPLER: Optional[ResourceSampler] = None
_LOCK = threading.Lock()


def enabled() -> bool:
    return _SAMPLER is not None


def configure_from_conf(conf) -> None:
    """Start/stop the process sampler from a live conf — the same
    hand-off pattern as ``events.configure_from_conf`` (driver session
    and cluster workers call it after ``set_active_conf``). Starts a
    thread only when ``srt.obs.resource.intervalMs > 0`` AND the event
    log is on; otherwise tears down any running sampler."""
    global _SAMPLER
    from ..conf import EVENT_LOG_ENABLED, RESOURCE_SAMPLE_INTERVAL_MS
    try:
        interval_ms = int(conf.get(RESOURCE_SAMPLE_INTERVAL_MS) or 0)
        on = interval_ms > 0 and bool(conf.get(EVENT_LOG_ENABLED))
    except Exception:
        return
    with _LOCK:
        if on:
            if (_SAMPLER is not None and _SAMPLER.alive
                    and _SAMPLER.interval_s * 1000.0 == interval_ms):
                return
            if _SAMPLER is not None:
                _SAMPLER.stop()
            _SAMPLER = ResourceSampler(interval_ms)
            _SAMPLER.start()
        elif _SAMPLER is not None:
            _SAMPLER.stop()
            _SAMPLER = None


def shutdown() -> None:
    """Stop the sampler if one is running (tests, process exit)."""
    global _SAMPLER
    with _LOCK:
        if _SAMPLER is not None:
            _SAMPLER.stop()
            _SAMPLER = None
