"""Query-level observability: spans, event log, metrics registry.

The reference accelerator treats observability as a subsystem in its
own right — leveled ``GpuMetric`` accumulators on every operator
(GpuExec.scala:36-188), NVTX ranges (NvtxWithMetrics.scala), Spark's
event log consumed by an offline profiling tool. This package is the
TPU rebuild's counterpart, split the same way:

- :mod:`.trace` — Dapper-style spans (query → stage → task → operator)
  with monotonic timestamps, exportable as Chrome-trace (catapult)
  JSON.
- :mod:`.events` — a structured JSONL event log in the
  Spark-history-server mold (QueryStart/End, StageSubmitted/Completed,
  TaskEnd, SpillToHost/Disk, FetchFailed, RetryAttempt,
  CorruptionDetected, FaultInjected, ShuffleWrite...), emitted from
  the session, mesh executor, cluster runtime, shuffle manager, spill
  framework, retry framework, and fault harness.
- :mod:`.registry` — aggregation of the per-operator ``Metric``
  accumulators into per-query summaries, gated by ``srt.metrics.level``
  (ESSENTIAL/MODERATE/DEBUG), plus bounded log-bucketed histograms
  (task time, shuffle block size, fetch latency...) and a
  Prometheus-style text snapshot with p50/p90/p99.
- :mod:`.resource` — an optional background sampler
  (``srt.obs.resource.intervalMs``) recording RSS, device memory,
  spill/fetch/prefetch occupancy as periodic ResourceSample events.
- :mod:`.roofline` — the compile ledger (per-program trace/lower/
  compile wall time + XLA cost_analysis flops/bytes, fed by
  ``jit_registry``), conf-gated per-launch device-time sampling
  joined into achieved GB/s / GFLOP/s, one-time peak-bandwidth
  calibration, and per-query RooflineSummary events —
  ``tools/roofline_report.py`` ranks operators by
  roofline-gap x time-weight from these.

Design contract (same discipline as the unarmed ``fault_point`` sites):
**zero overhead when disabled.** Every hook threaded through the hot
paths is a module-global ``None`` check when no sink/tracer is
installed — no event sink is created, no span objects are allocated,
no per-batch work happens. ``tools/profile_report.py`` turns an event
log back into a per-query report offline.
"""

from . import events, registry, resource, roofline, trace  # noqa: F401
