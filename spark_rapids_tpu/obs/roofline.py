"""In-engine roofline observability: compile ledger + device-time join.

ROADMAP item 3's headline number (``kernel_hbm_util_est ~ 0.046``) is a
coarse offline estimate computed once per bench run; this module makes
the same quantity a first-class, per-program, per-query signal:

- **Compile ledger** — every shared-program miss in ``jit_registry``
  AOT-compiles through ``trace()/lower()/compile()`` and records the
  wall time of each phase plus XLA's ``cost_analysis()`` flops and
  bytes-accessed here, keyed by the structural program key and
  attributed to the owning module. Each compile emits a
  ``ProgramCompiled`` event when the event log is on.
- **Device-time sampling** — every Nth launch of a ledgered program
  (``srt.obs.roofline.sampleEvery``; 0 = off) is timed with a device
  sync and joined with the ledger's bytes/flops: achieved GB/s and
  GFLOP/s land in ``effective_gb_s``/``effective_gflop_s`` histograms
  (MetricsRegistry) and accumulate on the ledger entry. Between
  samples the cost is one counter increment per launch.
- **Per-query windows** — the session snapshots the ledger before a
  query and diffs after it, producing a ``RooflineSummary`` event and
  a ``roofline`` block on the query's registry record: per-program
  launches, extrapolated device busy time, achieved rates, and —
  when the peak is calibrated — roofline *utilization*.
- **Peak calibration** — ``srt.obs.roofline.calibrate`` runs the
  ``tools/roofline.py`` copy-probe denominator once in-engine, so
  utilization is achieved/measured-peak, not achieved/datasheet.

Graceful-degradation contract: ``cost_analysis()`` may be ``None`` or
missing keys (CPU backend, older jaxlib); the ledger records what it
can, rates involving missing quantities stay ``None``, and offline
reports print ``n/a``. Observability never raises into execution —
every hook here is wrapped so a failure degrades to "not measured".

Zero-overhead contract (same discipline as ``events``/``resource``):
with sampling off the per-launch hook is one attribute read and one
integer increment; with the event log off no events are built.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import events as _events
from . import registry as _registry

# --- process-global config (set by configure_from_conf) ---
_ENABLED = True        # srt.obs.roofline.enabled
_SAMPLE_EVERY = 0      # srt.obs.roofline.sampleEvery; 0 until configured
_LOCK = threading.RLock()

# --- the ledger: structural-key hash -> LedgerEntry, insertion order ---
_ENTRIES: Dict[str, "LedgerEntry"] = {}
_MAX_ENTRIES = 4096

# --- calibration state ---
_PEAK_GBS: Optional[float] = None
_PROBE_LAUNCHES = 0
_PROBE_ELEMS = 1 << 23  # 32MB f32: big enough to defeat caches, quick


class LedgerEntry:
    """Per-program record: compile phases, XLA cost, sampled launches.

    One entry per structural program key, shared by every launch of the
    registry wrapper that owns it. Counter mutation takes the entry
    lock — launches are hot but the critical section is a handful of
    integer adds.
    """

    __slots__ = ("key", "module", "label", "display",
                 "compiles", "trace_ns", "lower_ns", "compile_ns",
                 "flops", "bytes_accessed",
                 "launches", "sampled_launches", "sampled_ns",
                 "sampled_bytes", "sampled_flops", "lock")

    def __init__(self, key: str, module: str, label: str):
        self.key = key
        self.module = module
        self.label = label
        #: operator-facing name (e.g. "Fused[Scan->Filter->Agg]") set
        #: via jit_registry.annotate; defaults to the structural label
        self.display = label
        self.compiles = 0
        self.trace_ns = 0
        self.lower_ns = 0
        self.compile_ns = 0
        #: most recent compile's cost analysis; None = unavailable
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.launches = 0
        self.sampled_launches = 0
        self.sampled_ns = 0
        #: bytes/flops summed over sampled launches whose signature had
        #: a known cost analysis — the GB/s join numerators
        self.sampled_bytes = 0.0
        self.sampled_flops = 0.0
        self.lock = threading.Lock()

    def count_launch(self) -> None:
        with self.lock:
            self.launches += 1

    def as_dict(self) -> Dict[str, Any]:
        with self.lock:
            d = {
                "program": self.key, "module": self.module,
                "label": self.label, "display": self.display,
                "compiles": self.compiles, "trace_ns": self.trace_ns,
                "lower_ns": self.lower_ns, "compile_ns": self.compile_ns,
                "flops": self.flops, "bytes_accessed": self.bytes_accessed,
                "launches": self.launches,
                "sampled_launches": self.sampled_launches,
                "sampled_ns": self.sampled_ns,
                "sampled_bytes": self.sampled_bytes,
                "sampled_flops": self.sampled_flops,
            }
        return d


# --- config ---
def configure_from_conf(conf) -> None:
    """Refresh process-global roofline config from a live conf; runs
    the one-time peak probe when calibration is requested. Called by
    the session per query and by cluster workers after
    ``set_active_conf`` — same hand-off as ``events``/``resource``."""
    global _ENABLED, _SAMPLE_EVERY
    try:
        from ..conf import (ROOFLINE_CALIBRATE, ROOFLINE_ENABLED,
                            ROOFLINE_SAMPLE_EVERY)
        on = bool(conf.get(ROOFLINE_ENABLED))
        every = int(conf.get(ROOFLINE_SAMPLE_EVERY) or 0)
        calibrate = bool(conf.get(ROOFLINE_CALIBRATE))
    except Exception:
        return
    _ENABLED = on
    _SAMPLE_EVERY = every if on else 0
    if on and calibrate and _PEAK_GBS is None:
        _run_probe()


def enabled() -> bool:
    return _ENABLED


def sample_every() -> int:
    """Current sampling stride (0 = sampling off). Read per launch by
    the registry wrappers — a module-global int read."""
    return _SAMPLE_EVERY


def set_sample_every(every: int) -> None:
    """Direct override (tests, bench legs that force sampling)."""
    global _SAMPLE_EVERY
    _SAMPLE_EVERY = int(every)


def active() -> bool:
    """True when per-launch sampling (and so per-query summaries) is
    on."""
    return _ENABLED and _SAMPLE_EVERY > 0


# --- peak calibration ---
def _run_probe() -> None:
    """Measure peak copy bandwidth with a jitted read+write probe (the
    tools/roofline.py denominator, moved in-engine). Best of three,
    counted in ``probe_launches`` so tests can assert the conf gate.
    Never raises — on any failure the peak simply stays unknown."""
    global _PEAK_GBS, _PROBE_LAUNCHES
    try:
        import jax
        import jax.numpy as jnp
        n = _PROBE_ELEMS
        x = jnp.ones((n,), dtype=jnp.float32)
        f = jax.jit(lambda a: a * 1.0000001)
        best = None
        for _ in range(3):
            t0 = time.perf_counter_ns()
            jax.block_until_ready(f(x))
            dt = time.perf_counter_ns() - t0
            _PROBE_LAUNCHES += 1
            if best is None or dt < best:
                best = dt
        # first launch includes compile; with 3 reps the min is a
        # steady-state launch. read n*4 + write n*4 bytes.
        if best and best > 0:
            _PEAK_GBS = (2.0 * 4.0 * n) / best  # bytes/ns == GB/s
    except Exception:
        pass


def calibrated_peak() -> Optional[float]:
    """Measured peak copy bandwidth in GB/s, or None when the
    calibration probe has not run (srt.obs.roofline.calibrate off)."""
    return _PEAK_GBS


def set_peak(gbs: Optional[float]) -> None:
    """Inject a peak (tests; bench runs that already measured one)."""
    global _PEAK_GBS
    _PEAK_GBS = float(gbs) if gbs else None


def probe_launches() -> int:
    return _PROBE_LAUNCHES


# --- ledger writes (called from jit_registry) ---
def ensure_entry(key: str, module: str, label: str) -> LedgerEntry:
    with _LOCK:
        e = _ENTRIES.get(key)
        if e is None:
            while len(_ENTRIES) >= _MAX_ENTRIES:
                _ENTRIES.pop(next(iter(_ENTRIES)))
            e = _ENTRIES[key] = LedgerEntry(key, module, label)
        return e


def record_compile(entry: LedgerEntry, trace_ns: int, lower_ns: int,
                   compile_ns: int, flops: Optional[float],
                   bytes_accessed: Optional[float]) -> None:
    """Fold one AOT compile into the ledger and emit ProgramCompiled.
    ``flops``/``bytes_accessed`` are None when ``cost_analysis()`` was
    unavailable or partial — recorded as unknown, never fatal."""
    with entry.lock:
        entry.compiles += 1
        entry.trace_ns += int(trace_ns)
        entry.lower_ns += int(lower_ns)
        entry.compile_ns += int(compile_ns)
        if flops is not None:
            entry.flops = float(flops)
        if bytes_accessed is not None:
            entry.bytes_accessed = float(bytes_accessed)
    if _ENABLED and _events.enabled():
        _events.emit("ProgramCompiled", program=entry.key,
                     module=entry.module, label=entry.label,
                     display=entry.display, trace_ns=int(trace_ns),
                     lower_ns=int(lower_ns), compile_ns=int(compile_ns),
                     flops=flops, bytes_accessed=bytes_accessed,
                     compiles=entry.compiles)


def record_sample(entry: LedgerEntry, elapsed_ns: int,
                  bytes_accessed: Optional[float],
                  flops: Optional[float]) -> None:
    """Fold one synced launch measurement into the ledger and the
    effective-rate histograms. bytes/ns is numerically GB/s."""
    elapsed_ns = max(int(elapsed_ns), 1)
    with entry.lock:
        entry.sampled_launches += 1
        entry.sampled_ns += elapsed_ns
        if bytes_accessed is not None:
            entry.sampled_bytes += float(bytes_accessed)
        if flops is not None:
            entry.sampled_flops += float(flops)
    try:
        if bytes_accessed is not None:
            _registry.observe("effective_gb_s",
                              int(bytes_accessed / elapsed_ns), "GB/s")
        if flops is not None:
            _registry.observe("effective_gflop_s",
                              int(flops / elapsed_ns), "GFLOP/s")
    except Exception:
        pass


# --- reads ---
def snapshot() -> List[Dict[str, Any]]:
    """Consistent copy of every ledger entry (insertion order)."""
    with _LOCK:
        entries = list(_ENTRIES.values())
    return [e.as_dict() for e in entries]


def ledger_totals() -> Dict[str, Any]:
    """Per-module trace/lower/compile totals + program counts — the
    block bench embeds into BENCH_*.json for perf_gate's compile-time
    gate."""
    modules: Dict[str, Dict[str, Any]] = {}
    totals = {"programs": 0, "compiles": 0, "trace_ns": 0,
              "lower_ns": 0, "compile_ns": 0}
    for d in snapshot():
        m = modules.setdefault(d["module"],
                               {"programs": 0, "compiles": 0,
                                "trace_ns": 0, "lower_ns": 0,
                                "compile_ns": 0})
        for agg in (m, totals):
            agg["programs"] += 1
            agg["compiles"] += d["compiles"]
            agg["trace_ns"] += d["trace_ns"]
            agg["lower_ns"] += d["lower_ns"]
            agg["compile_ns"] += d["compile_ns"]
    totals["modules"] = modules
    return totals


# --- per-query window ---
_WINDOW_FIELDS = ("launches", "sampled_launches", "sampled_ns",
                  "sampled_bytes", "sampled_flops", "compiles",
                  "trace_ns", "lower_ns", "compile_ns")
#: cap on per-program rows carried by one RooflineSummary event
_SUMMARY_TOP = 24


class Window:
    """Ledger counter baseline taken at query start; ``finish`` diffs
    against the live ledger to produce the query's roofline summary.

    Counters are process-global, so under concurrent queries a window
    sees the union of everything launched while it was open — the same
    approximation the reference accepts for device-level metrics.
    """

    def __init__(self):
        self._base: Dict[str, tuple] = {
            d["program"]: tuple(d[f] for f in _WINDOW_FIELDS)
            for d in snapshot()}

    def finish(self, query_id: str) -> Optional[Dict[str, Any]]:
        try:
            return self._finish(query_id)
        except Exception:
            return None  # observability must never break the query

    def _finish(self, query_id: str) -> Optional[Dict[str, Any]]:
        progs: List[Dict[str, Any]] = []
        for d in snapshot():
            base = self._base.get(d["program"],
                                  (0,) * len(_WINDOW_FIELDS))
            delta = {f: d[f] - base[i]
                     for i, f in enumerate(_WINDOW_FIELDS)}
            if delta["launches"] <= 0 and delta["compiles"] <= 0:
                continue
            row: Dict[str, Any] = {
                "program": d["program"], "module": d["module"],
                "label": d["label"], "display": d["display"],
                "bytes_accessed": d["bytes_accessed"],
                "flops": d["flops"],
            }
            row.update(delta)
            # extrapolate device busy time from the sampled subset
            if delta["sampled_launches"] > 0:
                row["est_busy_ns"] = int(
                    delta["sampled_ns"] * delta["launches"]
                    / delta["sampled_launches"])
                if delta["sampled_bytes"] > 0:
                    row["gb_s"] = delta["sampled_bytes"] / \
                        delta["sampled_ns"]
                if delta["sampled_flops"] > 0:
                    row["gflop_s"] = delta["sampled_flops"] / \
                        delta["sampled_ns"]
            else:
                row["est_busy_ns"] = 0
            progs.append(row)
        if not progs:
            return None
        busy = sum(p["est_busy_ns"] for p in progs)
        attributed = sum(p["est_busy_ns"] for p in progs
                         if p.get("gb_s") is not None)
        s_ns = sum(p["sampled_ns"] for p in progs)
        s_bytes = sum(p["sampled_bytes"] for p in progs)
        s_flops = sum(p["sampled_flops"] for p in progs)
        peak = _PEAK_GBS
        gb_s = (s_bytes / s_ns) if s_ns > 0 and s_bytes > 0 else None
        summary: Dict[str, Any] = {
            "query_id": query_id,
            "device_busy_est_ns": busy,
            "attributed_busy_ns": attributed,
            "sampled_ns": s_ns,
            "gb_s": gb_s,
            "gflop_s": (s_flops / s_ns) if s_ns > 0 and s_flops > 0
            else None,
            "peak_gb_s": peak,
            "utilization": (gb_s / peak)
            if gb_s is not None and peak else None,
            "compiles": sum(p["compiles"] for p in progs),
            "compile_ns": sum(p["compile_ns"] for p in progs),
            "sample_every": _SAMPLE_EVERY,
        }
        progs.sort(key=lambda p: p["est_busy_ns"], reverse=True)
        summary["programs"] = progs[:_SUMMARY_TOP]
        if len(progs) > _SUMMARY_TOP:
            summary["programs_dropped"] = len(progs) - _SUMMARY_TOP
        if _ENABLED and _events.enabled():
            _events.emit("RooflineSummary", **summary)
        return summary


def window() -> Optional[Window]:
    """Open a per-query window, or None when sampling is off (the
    zero-overhead path: no snapshot, no per-query work)."""
    if not active():
        return None
    try:
        return Window()
    except Exception:
        return None


def reset() -> None:
    """Tests only: drop the ledger, calibration, and sampling config.
    Live registry wrappers are re-homed onto fresh entries so their
    post-reset launches stay visible (jit_registry holds the entry
    object, not the key)."""
    global _PEAK_GBS, _PROBE_LAUNCHES, _SAMPLE_EVERY, _ENABLED
    with _LOCK:
        _ENTRIES.clear()
    _PEAK_GBS = None
    _PROBE_LAUNCHES = 0
    _SAMPLE_EVERY = 0
    _ENABLED = True
    try:
        from .. import jit_registry
        jit_registry.rebind_ledger_entries()
    except Exception:
        pass
