"""Metrics registry: per-query summaries + Prometheus-style snapshot.

The exec layer already accumulates leveled ``Metric``s per operator
(``ExecContext.metrics: {exec_id: {name: Metric}}``); this module
aggregates them the way the reference accelerator's SQL UI does —
filtered by ``srt.metrics.level`` (ESSENTIAL < MODERATE < DEBUG),
rolled up per query, and kept in a bounded process-wide registry that
``bench.py`` and tests can snapshot or export as Prometheus text.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

LEVEL_ORDER = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}


def level_allows(conf_level: str, metric_level: str) -> bool:
    """True when a metric at ``metric_level`` should be reported under
    the configured ``conf_level`` (ESSENTIAL shows the least)."""
    want = LEVEL_ORDER.get(str(conf_level).upper(), 1)
    have = LEVEL_ORDER.get(str(metric_level).upper(), 1)
    return have <= want


def summarize_metrics(ctx_metrics: Dict[str, Dict[str, Any]],
                      level: str = "MODERATE") -> Dict[str, Dict[str, dict]]:
    """Flatten ``{exec_id: {name: Metric}}`` into plain dicts, keeping
    only metrics at or below the configured level."""
    out: Dict[str, Dict[str, dict]] = {}
    for exec_id, metrics in ctx_metrics.items():
        kept: Dict[str, dict] = {}
        for name, m in metrics.items():
            m_level = getattr(m, "level", "MODERATE")
            if not level_allows(level, m_level):
                continue
            kept[name] = {"value": getattr(m, "value", m),
                          "level": m_level,
                          "unit": getattr(m, "unit", "")}
        if kept:
            out[str(exec_id)] = kept
    return out


def query_totals(summary: Dict[str, Dict[str, dict]]) -> Dict[str, Any]:
    """Cross-operator totals for the headline numbers."""
    totals: Dict[str, Any] = {"opTimeNs": 0, "numOutputRows": 0,
                              "numOutputBatches": 0, "spilledBytes": 0,
                              "shuffleBytesWritten": 0}
    for metrics in summary.values():
        for name, rec in metrics.items():
            v = rec.get("value", 0)
            if not isinstance(v, (int, float)):
                continue
            if name == "opTime":
                totals["opTimeNs"] += v
            elif name in totals:
                totals[name] += v
    return totals


class MetricsRegistry:
    """Bounded process-wide record of completed queries plus running
    totals. Cheap enough to leave always-on: recording happens once
    per query, never per batch."""

    def __init__(self, max_queries: int = 64):
        self._lock = threading.Lock()
        self._queries: deque = deque(maxlen=max_queries)
        self._counters: Dict[str, float] = {
            "queries_total": 0,
            "queries_failed_total": 0,
            "op_time_ns_total": 0,
            "output_rows_total": 0,
            "output_batches_total": 0,
            "wall_time_ns_total": 0,
        }

    def record_query(self, query_id: str,
                     summary: Dict[str, Dict[str, dict]],
                     wall_ns: int = 0, status: str = "ok",
                     **extra: Any) -> Dict[str, Any]:
        totals = query_totals(summary)
        rec = {"query_id": query_id, "status": status,
               "wall_ns": wall_ns, "totals": totals,
               "operators": summary}
        rec.update(extra)
        with self._lock:
            self._queries.append(rec)
            self._counters["queries_total"] += 1
            if status != "ok":
                self._counters["queries_failed_total"] += 1
            self._counters["op_time_ns_total"] += totals["opTimeNs"]
            self._counters["output_rows_total"] += totals["numOutputRows"]
            self._counters["output_batches_total"] += \
                totals["numOutputBatches"]
            self._counters["wall_time_ns_total"] += wall_ns
        return rec

    def queries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._queries)

    def last_query(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._queries[-1] if self._queries else None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "queries": list(self._queries)}

    def prometheus_text(self) -> str:
        """Prometheus text exposition format of the running counters
        plus per-operator op-time of the most recent query."""
        lines: List[str] = []
        with self._lock:
            counters = dict(self._counters)
            last = self._queries[-1] if self._queries else None
        for name, value in sorted(counters.items()):
            metric = f"srt_{name}"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {value:g}")
        if last is not None:
            metric = "srt_last_query_op_time_ns"
            lines.append(f"# TYPE {metric} gauge")
            for exec_id, metrics in sorted(last["operators"].items()):
                rec = metrics.get("opTime")
                if rec is None:
                    continue
                lines.append(
                    f'{metric}{{exec_id="{exec_id}"}} '
                    f'{rec.get("value", 0):g}')
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._queries.clear()
            for k in self._counters:
                self._counters[k] = 0


_REGISTRY: Optional[MetricsRegistry] = None
_REG_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    global _REGISTRY
    with _REG_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY


def reset_registry() -> None:
    global _REGISTRY
    with _REG_LOCK:
        _REGISTRY = None
