"""Metrics registry: per-query summaries + Prometheus-style snapshot.

The exec layer already accumulates leveled ``Metric``s per operator
(``ExecContext.metrics: {exec_id: {name: Metric}}``); this module
aggregates them the way the reference accelerator's SQL UI does —
filtered by ``srt.metrics.level`` (ESSENTIAL < MODERATE < DEBUG),
rolled up per query, and kept in a bounded process-wide registry that
``bench.py`` and tests can snapshot or export as Prometheus text.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

LEVEL_ORDER = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}

#: quantiles reported for every histogram (summaries + Prometheus)
QUANTILES = (0.50, 0.90, 0.99)


def _escape_label(v: Any) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped inside the quotes."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Histogram:
    """Bounded log-bucketed histogram over non-negative integers.

    Bucket ``i`` holds values whose ``bit_length()`` is ``i`` — i.e.
    ``{0}`` for bucket 0 and ``[2^(i-1), 2^i - 1]`` for ``i >= 1`` —
    so at most ~65 buckets cover the full 64-bit range and the counts
    list grows lazily to the highest bucket actually hit. Quantile
    estimates take the containing bucket's upper bound clamped to the
    observed min/max, which is tight enough for p50/p90/p99 skew
    detection without per-value storage."""

    __slots__ = ("name", "unit", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self._counts: List[int] = []   # lazily grown, index = bit_length
        self._count = 0
        self._sum = 0
        self._min: Optional[int] = None
        self._max: Optional[int] = None
        self._lock = threading.Lock()

    def observe(self, value) -> None:
        v = int(value)
        if v < 0:
            v = 0
        i = v.bit_length()
        with self._lock:
            if i >= len(self._counts):
                self._counts.extend([0] * (i + 1 - len(self._counts)))
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> int:
        return self._sum

    def buckets(self) -> List[tuple]:
        """``[(le, cumulative_count), ...]`` with le the inclusive
        upper bound of each allocated bucket — already cumulative, as
        Prometheus histogram buckets require."""
        with self._lock:
            counts = list(self._counts)
        out: List[tuple] = []
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            le = 0 if i == 0 else (1 << i) - 1
            out.append((le, cum))
        return out

    def quantile(self, q: float) -> int:
        """Estimated q-quantile (0 < q <= 1)."""
        with self._lock:
            if self._count == 0:
                return 0
            rank = q * self._count
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank and c:
                    le = 0 if i == 0 else (1 << i) - 1
                    hi = min(le, self._max)
                    return max(hi, self._min)
            return self._max or 0

    def percentiles(self) -> Dict[str, int]:
        return {f"p{int(q * 100)}": self.quantile(q) for q in QUANTILES}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
        d: Dict[str, Any] = {"count": count, "sum": total,
                             "min": mn or 0, "max": mx or 0}
        if self.unit:
            d["unit"] = self.unit
        d.update(self.percentiles())
        return d


def level_allows(conf_level: str, metric_level: str) -> bool:
    """True when a metric at ``metric_level`` should be reported under
    the configured ``conf_level`` (ESSENTIAL shows the least)."""
    want = LEVEL_ORDER.get(str(conf_level).upper(), 1)
    have = LEVEL_ORDER.get(str(metric_level).upper(), 1)
    return have <= want


def summarize_metrics(ctx_metrics: Dict[str, Dict[str, Any]],
                      level: str = "MODERATE") -> Dict[str, Dict[str, dict]]:
    """Flatten ``{exec_id: {name: Metric}}`` into plain dicts, keeping
    only metrics at or below the configured level."""
    out: Dict[str, Dict[str, dict]] = {}
    for exec_id, metrics in ctx_metrics.items():
        kept: Dict[str, dict] = {}
        for name, m in metrics.items():
            m_level = getattr(m, "level", "MODERATE")
            if not level_allows(level, m_level):
                continue
            kept[name] = {"value": getattr(m, "value", m),
                          "level": m_level,
                          "unit": getattr(m, "unit", "")}
        if kept:
            out[str(exec_id)] = kept
    return out


def query_totals(summary: Dict[str, Dict[str, dict]]) -> Dict[str, Any]:
    """Cross-operator totals for the headline numbers."""
    totals: Dict[str, Any] = {"opTimeNs": 0, "numOutputRows": 0,
                              "numOutputBatches": 0, "spilledBytes": 0,
                              "shuffleBytesWritten": 0}
    for metrics in summary.values():
        for name, rec in metrics.items():
            v = rec.get("value", 0)
            if not isinstance(v, (int, float)):
                continue
            if name == "opTime":
                totals["opTimeNs"] += v
            elif name in totals:
                totals[name] += v
    return totals


class MetricsRegistry:
    """Bounded process-wide record of completed queries plus running
    totals. Cheap enough to leave always-on: recording happens once
    per query, never per batch."""

    def __init__(self, max_queries: int = 64, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._queries: deque = deque(maxlen=max_queries)
        self._hists: Dict[str, Histogram] = {}
        self._counters: Dict[str, float] = {
            "queries_total": 0,
            "queries_failed_total": 0,
            "op_time_ns_total": 0,
            "output_rows_total": 0,
            "output_batches_total": 0,
            "wall_time_ns_total": 0,
        }

    def observe(self, name: str, value, unit: str = "") -> None:
        """Record one sample into the named histogram (created on
        first use). A disabled registry drops the sample without
        allocating anything."""
        if not self.enabled:
            return
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram(name, unit))
        h.observe(value)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def histograms(self) -> Dict[str, Histogram]:
        with self._lock:
            return dict(self._hists)

    def record_query(self, query_id: str,
                     summary: Dict[str, Dict[str, dict]],
                     wall_ns: int = 0, status: str = "ok",
                     **extra: Any) -> Dict[str, Any]:
        totals = query_totals(summary)
        rec = {"query_id": query_id, "status": status,
               "wall_ns": wall_ns, "totals": totals,
               "operators": summary}
        rec.update(extra)
        with self._lock:
            hists = dict(self._hists)
        if hists:
            rec["quantiles"] = {n: h.snapshot() for n, h in hists.items()}
        with self._lock:
            self._queries.append(rec)
            self._counters["queries_total"] += 1
            if status != "ok":
                self._counters["queries_failed_total"] += 1
            self._counters["op_time_ns_total"] += totals["opTimeNs"]
            self._counters["output_rows_total"] += totals["numOutputRows"]
            self._counters["output_batches_total"] += \
                totals["numOutputBatches"]
            self._counters["wall_time_ns_total"] += wall_ns
        return rec

    def queries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._queries)

    def last_query(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._queries[-1] if self._queries else None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            hists = dict(self._hists)
            out = {"counters": dict(self._counters),
                   "queries": list(self._queries)}
        if hists:
            out["histograms"] = {n: h.snapshot()
                                 for n, h in hists.items()}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format: running counters,
        histograms (cumulative buckets, _sum/_count, and p50/p90/p99
        quantile gauges), and per-operator op-time of the most recent
        query. A disabled registry exposes nothing."""
        if not self.enabled:
            return ""
        lines: List[str] = []
        with self._lock:
            counters = dict(self._counters)
            hists = dict(self._hists)
            last = self._queries[-1] if self._queries else None
        for name, value in sorted(counters.items()):
            metric = f"srt_{name}"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {value:g}")
        for name in sorted(hists):
            h = hists[name]
            metric = f"srt_{name}"
            lines.append(f"# TYPE {metric} histogram")
            for le, cum in h.buckets():
                lines.append(f'{metric}_bucket{{le="{le}"}} {cum}')
            lines.append(f'{metric}_bucket{{le="+Inf"}} {h.count}')
            lines.append(f"{metric}_sum {h.sum}")
            lines.append(f"{metric}_count {h.count}")
            lines.append(f"# TYPE {metric}_quantile gauge")
            for q in QUANTILES:
                lines.append(
                    f'{metric}_quantile{{quantile="{q:g}"}} '
                    f'{h.quantile(q)}')
        if last is not None:
            metric = "srt_last_query_op_time_ns"
            lines.append(f"# TYPE {metric} gauge")
            for exec_id, metrics in sorted(last["operators"].items()):
                rec = metrics.get("opTime")
                if rec is None:
                    continue
                lines.append(
                    f'{metric}{{exec_id="{_escape_label(exec_id)}"}} '
                    f'{rec.get("value", 0):g}')
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._queries.clear()
            self._hists.clear()
            for k in self._counters:
                self._counters[k] = 0


_REGISTRY: Optional[MetricsRegistry] = None
_REG_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    global _REGISTRY
    with _REG_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY


def reset_registry() -> None:
    global _REGISTRY
    with _REG_LOCK:
        _REGISTRY = None


def observe(name: str, value, unit: str = "") -> None:
    """Module-level shortcut for histogram observation sites
    (task times, shuffle block sizes, fetch latencies...)."""
    registry().observe(name, value, unit)
