"""Structured JSONL event log, Spark-history-server style.

One writer per process appends one JSON object per line to
``events-<pid>.jsonl`` inside ``srt.eventLog.dir``. Event types mirror
the Spark history log (QueryStart/QueryEnd, StageSubmitted/
StageCompleted, TaskEnd with metrics) plus the robustness layer's
lifecycle (SpillToHost/SpillToDisk, FetchFailed, RetryAttempt,
FaultInjected, CorruptionDetected, ShuffleWrite...). The offline
``tools/profile_report.py`` reconstructs per-query behavior from these
files.

Zero-overhead contract: ``emit()`` is a module-global ``is None``
check when no sink is installed — the same discipline as the unarmed
``fault_point`` sites. ``configure_from_conf`` mirrors
``faults.arm_from_conf``: workers call it after ``set_active_conf`` so
a job conf shipped over the wire lights up logging on every process.

Emission must never break the engine: writer I/O errors are swallowed
(the event log is a best-effort flight recorder, not a transaction
log). Each line is flushed immediately so crash-kind faults
(``os._exit``) still leave their FaultInjected event on disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

# Known event types (informational; the log is schema-on-read).
EVENT_TYPES = (
    "QueryStart", "QueryEnd",
    "StageSubmitted", "StageCompleted",
    "TaskEnd",
    "SpillToHost", "SpillToDisk",
    "ShuffleWrite",
    "FetchFailed", "RetryAttempt",
    "FaultInjected", "CorruptionDetected",
    "WorkerEvicted",
    "ProgramCompiled", "RooflineSummary",
    "QueryAdmitted", "AdmissionQueued", "AdmissionRejected",
    "AdmissionAbandoned", "QueryCancelled", "DeadlineExceeded",
    "CrossQuerySpill", "PrefetchThreadLeak", "ClusterCancelBroadcast",
    "AdaptivePlanChanged", "SkewSplit", "SpeculativeTask",
    "WorkerDecommissioned", "BlockMigrated", "ZombieFenced",
    "ReplicaFetch", "RecoveryTimed",
    "DeltaCommit", "DeltaLogCheckpointed", "DeltaOrphanSwept",
    "StreamBatchCommitted", "StreamBatchSkipped", "StaleWriterFenced",
    "ServeSessionOpen", "ServeSessionClose", "ServeLoadShed",
    "ResultCacheHit", "ResultCacheMiss", "ResultCacheEvict",
    "ResultCacheInvalidate", "ResultCacheCorrupt",
)


class EventLogWriter:
    """Append-only JSONL sink. Thread-safe, flush-per-line, and
    silent on I/O failure — an event log must never take the query
    down with it.

    With ``max_bytes > 0`` (``srt.eventLog.maxBytes``) the file
    rotates once it exceeds the cap: the live file rolls to ``.1``,
    a previous ``.1`` to ``.2``, and an old ``.2`` is dropped —
    bounding a long-running/serving process to roughly three segments.
    Readers (``iter_log_files``) stitch ``.2``, ``.1``, live back in
    write order."""

    def __init__(self, log_dir: str, max_bytes: int = 0):
        self.log_dir = log_dir
        self.max_bytes = int(max_bytes or 0)
        self.path = os.path.join(log_dir, f"events-{os.getpid()}.jsonl")
        self._lock = threading.Lock()
        self._file = None
        self._size = 0
        self._broken = False

    def _rollover_locked(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        self._size = 0
        try:
            if os.path.exists(self.path + ".1"):
                os.replace(self.path + ".1", self.path + ".2")
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # keep appending to the oversized live file

    def emit(self, event: str, **fields: Any) -> None:
        rec: Dict[str, Any] = {"event": event, "ts": time.time(),
                               "pid": os.getpid()}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=str)
        except Exception:
            return
        with self._lock:
            if self._broken:
                return
            try:
                if self._file is None:
                    os.makedirs(self.log_dir, exist_ok=True)
                    self._file = open(self.path, "a")
                    self._size = self._file.tell()
                self._file.write(line + "\n")
                self._file.flush()
                self._size += len(line) + 1
                if self.max_bytes and self._size > self.max_bytes:
                    self._rollover_locked()
            except OSError:
                self._broken = True

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# --- module-global sink (the zero-overhead guard) ---
_SINK: Optional[EventLogWriter] = None
# True when the installed sink came from configure_from_conf, so a
# later disabled conf only tears down what conf management installed
# (manually installed test sinks survive interleaved sessions).
_CONF_MANAGED = False


def enabled() -> bool:
    return _SINK is not None


def emit(event: str, **fields: Any) -> None:
    sink = _SINK
    if sink is None:
        return
    sink.emit(event, **fields)


def install(sink: Optional[EventLogWriter]) -> None:
    """Install (or clear, with None) the process-wide sink."""
    global _SINK, _CONF_MANAGED
    old = _SINK
    _SINK = sink
    _CONF_MANAGED = False
    if old is not None and old is not sink:
        old.close()


def configure_from_conf(conf) -> None:
    """Install/refresh the sink from a live conf. Called by the
    session on the driver and by cluster workers right after
    ``set_active_conf`` — the same hand-off pattern as
    ``faults.arm_from_conf``."""
    global _SINK, _CONF_MANAGED
    from ..conf import (EVENT_LOG_DIR, EVENT_LOG_ENABLED,
                        EVENT_LOG_MAX_BYTES)
    try:
        on = bool(conf.get(EVENT_LOG_ENABLED))
        log_dir = conf.get(EVENT_LOG_DIR) or ""
        max_bytes = int(conf.get(EVENT_LOG_MAX_BYTES) or 0)
    except Exception:
        return
    if on:
        log_dir = log_dir or os.path.join(".", "srt-events")
        if (_SINK is not None and _SINK.log_dir == log_dir
                and _SINK.max_bytes == max_bytes):
            return  # already pointed at the right place
        old = _SINK
        _SINK = EventLogWriter(log_dir, max_bytes=max_bytes)
        _CONF_MANAGED = True
        if old is not None:
            old.close()
    elif _CONF_MANAGED:
        old = _SINK
        _SINK = None
        _CONF_MANAGED = False
        if old is not None:
            old.close()


def log_dir() -> Optional[str]:
    sink = _SINK
    return sink.log_dir if sink is not None else None


# --- reading side (profile_report, tests, chaos_check) ---
def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL file, skipping torn/garbage lines (a crashed
    writer may leave a partial final line)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "event" in rec:
                out.append(rec)
    return out


def _with_rolled(path: str) -> Iterator[str]:
    """Yield a log file's rolled segments oldest-first (``.2``, ``.1``)
    before the live file itself."""
    for suffix in (".2", ".1"):
        if os.path.exists(path + suffix):
            yield path + suffix
    if os.path.exists(path):
        yield path


def iter_log_files(path: str) -> Iterator[str]:
    """Yield event-log files under ``path`` (a file, or a dir holding
    ``events-*.jsonl`` from several processes), including rotation
    segments (``.2`` then ``.1`` then live, per process — write
    order)."""
    if os.path.isdir(path):
        # key on the BASE name so a process whose live file rolled
        # away (last emit crossed the cap, or crashed post-rollover)
        # still gets its .1/.2 segments read
        bases = set()
        for name in os.listdir(path):
            for suffix in (".jsonl", ".jsonl.1", ".jsonl.2"):
                if name.startswith("events-") and name.endswith(suffix):
                    bases.add(name[:len(name) - len(suffix)] + ".jsonl")
                    break
        for base in sorted(bases):
            yield from _with_rolled(os.path.join(path, base))
    else:
        yield from _with_rolled(path)


def read_all_events(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for f in iter_log_files(path):
        out.extend(read_events(f))
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out
