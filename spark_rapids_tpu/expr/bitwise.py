"""Bitwise expressions (sql-plugin/.../rapids/bitwise.scala surface):
and/or/xor/not and the shift family, plus bit interleaving for z-order
clustering (zorder/GpuInterleaveBits + spark-rapids-jni ZOrder,
SURVEY §2.5)."""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result, merged_validity


class _BitwiseBinary(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.promote(self.children[0].data_type(schema),
                          self.children[1].data_type(schema))

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        out_t = self.data_type(batch.schema())
        phys = out_t.physical
        data = self._op(a.data.astype(phys), b.data.astype(phys))
        return make_result(data, merged_validity(a, b), out_t)


class BitwiseAnd(_BitwiseBinary):
    def _op(self, a, b):
        return a & b


class BitwiseOr(_BitwiseBinary):
    def _op(self, a, b):
        return a | b


class BitwiseXor(_BitwiseBinary):
    def _op(self, a, b):
        return a ^ b


class BitwiseNot(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        return make_result(~c.data, c.validity, c.dtype)


class ShiftLeft(_BitwiseBinary):
    """shiftleft(x, n) — Java semantics: byte/short/int promote to INT,
    long stays LONG; n masked to the RESULT width (shifting in the
    narrow dtype with n >= its width is XLA-undefined)."""

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        return dt.INT64 if isinstance(t, dt.LongType) else dt.INT32

    def _operands(self, batch):
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        out_t = self.data_type(batch.schema())
        width = 64 if out_t == dt.INT64 else 32
        x = a.data.astype(out_t.physical)
        n = (b.data.astype(jnp.int32) & (width - 1)).astype(x.dtype)
        return x, n, merged_validity(a, b), out_t, width

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        x, n, validity, out_t, _ = self._operands(batch)
        return make_result(x << n, validity, out_t)


class ShiftRight(ShiftLeft):
    """Arithmetic (sign-extending) right shift."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        x, n, validity, out_t, _ = self._operands(batch)
        return make_result(x >> n, validity, out_t)


class ShiftRightUnsigned(ShiftLeft):
    """Logical right shift (>>> in Java). No 64-bit bitcasts on TPU
    (utils/bits.py constraint): arithmetic shift, then clear the
    sign-copied top bits."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        x, n, validity, out_t, width = self._operands(batch)
        shifted = x >> n
        one = jnp.asarray(1, x.dtype)
        neg_one = jnp.asarray(-1, x.dtype)
        mask = jnp.where(n > 0, (one << (width - n)) - 1, neg_one)
        return make_result(shifted & mask, validity, out_t)


class BitCount(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        x = c.data
        if x.dtype == jnp.bool_:
            return make_result(x.astype(jnp.int32), c.validity, dt.INT32)
        # popcount on the two 32-bit halves (no 64-bit bitcasts)
        if x.dtype == jnp.int64:
            lo = (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
            hi_arith = (x >> 32).astype(jnp.int64)
            hi = (hi_arith & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
            n = _popcount32(lo) + _popcount32(hi)
        else:
            n = _popcount32(x.astype(jnp.uint32))
        return make_result(n.astype(jnp.int32), c.validity, dt.INT32)


def _popcount32(x):
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


class InterleaveBits(Expression):
    """z-order key: bit-interleave up to 4 int32 columns into int64
    (zorder/GpuInterleaveBits; Delta OPTIMIZE ZORDER BY clustering).

    Values are offset to unsigned order first so negative numbers
    cluster correctly (the reference's ZOrder kernel does the same
    sign-flip normalization).
    """

    def __init__(self, *children: Expression):
        super().__init__(*children)
        if not 1 <= len(children) <= 4:
            raise TypeError("interleave_bits takes 1-4 columns")

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        cols = [c.eval(batch) for c in self.children]
        k = len(cols)
        bits_per = 63 // k
        parts = []
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        for c in cols:
            width = 64 if isinstance(c.dtype, dt.LongType) else 32
            x = c.data.astype(jnp.int64)
            # map to unsigned order within the SOURCE width, then take
            # the top bits_per bits of that width (int32 inputs must
            # normalize at 32 bits, not 64, or sign extension collapses
            # every value into two buckets)
            if width == 64:
                u = x ^ jnp.int64(-(2 ** 63))  # sign-bit flip, no overflow
            else:
                u = x + jnp.int64(2 ** (width - 1))  # [0, 2^width)
            u = (u >> (width - bits_per)) & jnp.int64(2 ** bits_per - 1)
            parts.append(u)
        out = jnp.zeros_like(parts[0])
        for bit in range(bits_per):
            for ci, p in enumerate(parts):
                src_bit = (p >> bit) & 1
                out = out | (src_bit << (bit * k + ci))
        return make_result(out, validity, dt.INT64)
