"""String expressions and string-side casts.

Reference surface: sql-plugin/.../rapids/stringFunctions.scala plus the
string halves of GpuCast.scala (spark-rapids-jni CastStrings). TPU has no
native variable-length support (SURVEY §7 hard-part #2), so every kernel
here works on one of two layouts:

- the flat offsets+chars layout for packing results, and
- the (capacity, pad_bucket) fixed-width padded view for per-character
  logic; the pad bucket is static so XLA sees fixed shapes.

LIKE is a vectorized dynamic program over the padded view — the pattern is
a plan-time constant so the DP unrolls at trace time into pure vector ops.
ASCII-only case mapping for upper/lower (documented divergence; full
Unicode mapping is a lookup-table kernel planned with the regex engine).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import Column, ColumnVector, ColumnarBatch, StringColumn
from .core import Expression, Schema, make_result, merged_validity


from ..columnar.vector import round_pow2 as _round_pow2


def pack_padded(padded, lens, validity, pad_bucket: int) -> StringColumn:
    """Build a StringColumn from a (capacity, W) byte matrix + lengths."""
    cap, w = padded.shape
    lens = jnp.where(validity, lens, 0).astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    nbytes = cap * w
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    row = jnp.searchsorted(offsets[1:], pos, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, cap - 1)
    within = pos - jnp.take(offsets, row_c)
    byte = padded[row_c, jnp.clip(within, 0, w - 1)]
    total = offsets[cap]
    chars = jnp.where(pos < total, byte, jnp.zeros((), jnp.uint8))
    return StringColumn(offsets, chars, validity, pad_bucket=pad_bucket)


class Length(Expression):
    """char_length — counts UTF-8 codepoints (not bytes), like Spark."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        padded = c.padded()
        # count bytes that are NOT UTF-8 continuation bytes (0b10xxxxxx)
        k = jnp.arange(c.pad_bucket)
        in_str = k[None, :] < c.lengths()[:, None]
        is_cont = (padded & 0xC0) == 0x80
        n = jnp.sum(in_str & ~is_cont, axis=1).astype(jnp.int32)
        return make_result(n, c.validity, dt.INT32)


class OctetLength(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        return make_result(c.lengths().astype(jnp.int32), c.validity, dt.INT32)


class _CaseMap(Expression):
    lo, hi, delta = 0, 0, 0

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        src = c.chars
        conv = (src >= self.lo) & (src <= self.hi)
        chars = jnp.where(conv, src + jnp.uint8(self.delta), src)
        return StringColumn(c.offsets, chars, c.validity, c.pad_bucket)


class Upper(_CaseMap):
    lo, hi, delta = ord("a"), ord("z"), -32 & 0xFF


class Lower(_CaseMap):
    lo, hi, delta = ord("A"), ord("Z"), 32


class Substring(Expression):
    """substring(str, pos, len) — 1-based pos; negative pos counts from end.

    Byte-based (exact for ASCII; Spark is codepoint-based — multi-byte
    offsets land with the regex/unicode work).
    """

    def __init__(self, child: Expression, pos: int, length: int = 1 << 30):
        super().__init__(child)
        self.pos = pos
        self.length = length

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        lens = c.lengths()
        if self.pos > 0:
            start = jnp.minimum(jnp.asarray(self.pos - 1, jnp.int32), lens)
        elif self.pos == 0:
            start = jnp.zeros_like(lens)
        else:
            start = jnp.maximum(lens + self.pos, 0)
        out_len = jnp.clip(jnp.minimum(jnp.asarray(self.length, jnp.int64),
                                       (lens - start).astype(jnp.int64)), 0, None)
        out_len = out_len.astype(jnp.int32)
        w = c.pad_bucket
        k = jnp.arange(w, dtype=jnp.int32)
        idx = c.offsets[:-1][:, None] + start[:, None] + k[None, :]
        padded = jnp.take(c.chars, jnp.clip(idx, 0, c.char_capacity - 1))
        padded = jnp.where(k[None, :] < out_len[:, None], padded, jnp.zeros((), jnp.uint8))
        return pack_padded(padded, out_len, c.validity, c.pad_bucket)


class Concat(Expression):
    """concat(...) — null if any input null (Spark concat semantics)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = merged_validity(*cols)
        w = sum(c.pad_bucket for c in cols)
        pads = [c.padded() for c in cols]
        lens = [c.lengths() for c in cols]
        total = sum(lens)
        # stack segments: write each input at its per-row offset
        out = jnp.zeros((batch.capacity, w), jnp.uint8)
        k = jnp.arange(w, dtype=jnp.int32)
        acc = jnp.zeros(batch.capacity, jnp.int32)
        for pad, ln, col in zip(pads, lens, cols):
            src_idx = k[None, :] - acc[:, None]
            in_range = (src_idx >= 0) & (src_idx < ln[:, None])
            gathered = jnp.take_along_axis(
                pad, jnp.clip(src_idx, 0, col.pad_bucket - 1), axis=1)
            out = jnp.where(in_range, gathered, out)
            acc = acc + ln
        return pack_padded(out, total, validity, _round_pow2(w))


class StartsWith(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def __init__(self, child: Expression, prefix: str):
        super().__init__(child)
        self.prefix = prefix

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        raw = np.frombuffer(self.prefix.encode("utf-8"), dtype=np.uint8)
        n = len(raw)
        if n == 0:
            return make_result(jnp.ones(batch.capacity, jnp.bool_), c.validity, dt.BOOL)
        padded = c.padded()
        if n > c.pad_bucket:
            return make_result(jnp.zeros(batch.capacity, jnp.bool_), c.validity, dt.BOOL)
        hit = jnp.all(padded[:, :n] == jnp.asarray(raw), axis=1) & (c.lengths() >= n)
        return make_result(hit, c.validity, dt.BOOL)


class EndsWith(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def __init__(self, child: Expression, suffix: str):
        super().__init__(child)
        self.suffix = suffix

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        raw = np.frombuffer(self.suffix.encode("utf-8"), dtype=np.uint8)
        n = len(raw)
        if n == 0:
            return make_result(jnp.ones(batch.capacity, jnp.bool_), c.validity, dt.BOOL)
        lens = c.lengths()
        start = lens - n
        k = jnp.arange(n, dtype=jnp.int32)
        idx = c.offsets[:-1][:, None] + start[:, None] + k[None, :]
        window = jnp.take(c.chars, jnp.clip(idx, 0, c.char_capacity - 1))
        hit = jnp.all(window == jnp.asarray(raw), axis=1) & (lens >= n)
        return make_result(hit, c.validity, dt.BOOL)


class Contains(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def __init__(self, child: Expression, needle: str):
        super().__init__(child)
        self.needle = needle

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        raw = np.frombuffer(self.needle.encode("utf-8"), dtype=np.uint8)
        n = len(raw)
        if n == 0:
            return make_result(jnp.ones(batch.capacity, jnp.bool_), c.validity, dt.BOOL)
        padded = c.padded()
        w = c.pad_bucket
        if n > w:
            return make_result(jnp.zeros(batch.capacity, jnp.bool_), c.validity, dt.BOOL)
        # sliding windows: for each start s, all(padded[:, s:s+n] == raw)
        hit = jnp.zeros(batch.capacity, jnp.bool_)
        lens = c.lengths()
        for s in range(w - n + 1):
            m = jnp.all(padded[:, s:s + n] == jnp.asarray(raw), axis=1) & (lens >= s + n)
            hit = hit | m
        return make_result(hit, c.validity, dt.BOOL)


class Like(Expression):
    """SQL LIKE with a constant pattern — vectorized DP over padded bytes.

    The reference transpiles LIKE to cuDF's regex (stringFunctions.scala);
    here the pattern is static at trace time, so the classic O(P*W) glob
    DP unrolls into P vector steps over the (capacity, W) view.
    """

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        super().__init__(child)
        self.pattern = pattern
        self.escape = escape

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def _tokens(self):
        toks = []
        i = 0
        p = self.pattern
        while i < len(p):
            ch = p[i]
            if ch == self.escape and i + 1 < len(p):
                toks.append(("lit", p[i + 1]))
                i += 2
            elif ch == "%":
                toks.append(("any", None))
                i += 1
            elif ch == "_":
                toks.append(("one", None))
                i += 1
            else:
                toks.append(("lit", ch))
                i += 1
        return toks

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        in_str = jnp.arange(w)[None, :] < lens[:, None]
        # dp[:, j] = pattern-so-far matches first j bytes
        dp = jnp.zeros((cap, w + 1), jnp.bool_).at[:, 0].set(True)
        for kind, ch in self._tokens():
            if kind == "any":
                dp = jnp.cumsum(dp, axis=1) > 0
            elif kind == "one":
                step = dp[:, :-1] & in_str
                dp = jnp.concatenate(
                    [jnp.zeros((cap, 1), jnp.bool_), step], axis=1)
            else:
                byte = ch.encode("utf-8")
                if len(byte) != 1:
                    raise TypeError("multi-byte LIKE literals not yet supported")
                eq = padded == jnp.uint8(byte[0])
                step = dp[:, :-1] & in_str & eq
                dp = jnp.concatenate(
                    [jnp.zeros((cap, 1), jnp.bool_), step], axis=1)
        hit = jnp.take_along_axis(dp, lens[:, None].astype(jnp.int32), axis=1)[:, 0]
        return make_result(hit, c.validity, dt.BOOL)


class StringTrim(Expression):
    side = "both"

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        k = jnp.arange(w, dtype=jnp.int32)
        in_str = k[None, :] < lens[:, None]
        is_space = (padded == jnp.uint8(32)) & in_str
        nonspace = in_str & ~is_space
        any_ns = jnp.any(nonspace, axis=1)
        first_ns = jnp.argmax(nonspace, axis=1).astype(jnp.int32)
        last_ns = (w - 1 - jnp.argmax(nonspace[:, ::-1], axis=1)).astype(jnp.int32)
        if self.side in ("both", "leading"):
            # all-space strings trim to empty: start lands at lens
            start = jnp.where(any_ns, first_ns, lens)
        else:
            start = jnp.zeros(cap, jnp.int32)
        if self.side in ("both", "trailing"):
            end = jnp.where(any_ns, last_ns + 1, 0)
        else:
            end = lens
        out_len = jnp.maximum(end - start, 0)
        idx = jnp.clip(start[:, None] + k[None, :], 0, w - 1)
        out = jnp.take_along_axis(padded, idx, axis=1)
        out = jnp.where(k[None, :] < out_len[:, None], out, jnp.zeros((), jnp.uint8))
        return pack_padded(out, out_len, c.validity, c.pad_bucket)


class StringTrimLeft(StringTrim):
    side = "leading"


class StringTrimRight(StringTrim):
    side = "trailing"


# ---------------------------------------------------------------------------
# Casts: string <-> other types (GpuCast.scala string halves)
# ---------------------------------------------------------------------------

_POW10 = [10 ** k for k in range(19)]


def _int_to_padded(mag, neg, width: int):
    """(cap, width) digit bytes for unsigned magnitudes + sign column."""
    ndig = jnp.ones_like(mag, dtype=jnp.int32)
    for k in range(1, 19):
        ndig = ndig + (mag >= jnp.uint64(_POW10[k])).astype(jnp.int32)
    ndig = ndig + (mag >= jnp.uint64(10 ** 19)).astype(jnp.int32)
    total = ndig + neg.astype(jnp.int32)
    p = jnp.arange(width, dtype=jnp.int32)
    di = p[None, :] - neg[:, None].astype(jnp.int32)  # digit index from left
    power = ndig[:, None] - 1 - di
    power_c = jnp.clip(power, 0, 19)
    pow10 = jnp.asarray([10 ** k for k in range(20)], jnp.uint64)[power_c]
    digit = (mag[:, None] // pow10) % jnp.uint64(10)
    byte = (jnp.uint8(48) + digit.astype(jnp.uint8))
    byte = jnp.where((di == -1)[:, :] | ((p[None, :] == 0) & neg[:, None]),
                     jnp.uint8(45), byte)  # '-'
    in_range = p[None, :] < total[:, None]
    return jnp.where(in_range, byte, jnp.zeros((), jnp.uint8)), total


def cast_to_string(c: ColumnVector) -> StringColumn:
    src = c.dtype
    cap = c.capacity
    if isinstance(src, dt.BooleanType):
        pad = jnp.zeros((cap, 8), jnp.uint8)
        t = np.frombuffer(b"true\0\0\0\0", np.uint8)
        f = np.frombuffer(b"false\0\0\0", np.uint8)
        pad = jnp.where(c.data[:, None], jnp.asarray(t)[None, :], jnp.asarray(f)[None, :])
        lens = jnp.where(c.data, 4, 5).astype(jnp.int32)
        return pack_padded(pad, lens, c.validity, 8)
    if src.is_integral or isinstance(src, dt.DecimalType):
        v = c.data.astype(jnp.int64)
        if isinstance(src, dt.DecimalType) and src.scale > 0:
            return _decimal_to_string(c)
        neg = v < 0
        mag = jnp.where(neg, (-(v.astype(jnp.uint64))), v.astype(jnp.uint64))
        padded, total = _int_to_padded(mag, neg, 21)
        return pack_padded(padded, total, c.validity, 32)
    if isinstance(src, dt.DateType):
        y, m, d = _civil_from_days(c.data.astype(jnp.int64))
        return _format_ymd(y, m, d, c.validity)
    if isinstance(src, dt.TimestampType):
        return _timestamp_to_string(c)
    raise TypeError(f"cast {src} -> string not supported on TPU")


def _decimal_to_string(c: ColumnVector) -> StringColumn:
    src: dt.DecimalType = c.dtype  # type: ignore[assignment]
    s = src.scale
    v = c.data.astype(jnp.int64)
    neg = v < 0
    mag = jnp.where(neg, -(v.astype(jnp.uint64)), v.astype(jnp.uint64))
    intpart = mag // jnp.uint64(_POW10[s])
    frac = mag % jnp.uint64(_POW10[s])
    ip, ip_len = _int_to_padded(intpart, neg, 21)
    # frac: fixed s digits
    p = jnp.arange(s, dtype=jnp.int32)
    pow10 = jnp.asarray([_POW10[k] for k in range(s)], jnp.uint64)[::-1]
    fdig = (frac[:, None] // pow10[None, :]) % jnp.uint64(10)
    fbytes = jnp.uint8(48) + fdig.astype(jnp.uint8)
    w = 21 + 1 + s
    out = jnp.zeros((c.capacity, w), jnp.uint8)
    out = out.at[:, :21].set(ip)
    k = jnp.arange(w, dtype=jnp.int32)
    dot_pos = ip_len
    out = jnp.where(k[None, :] == dot_pos[:, None], jnp.uint8(46), out)
    fidx = k[None, :] - dot_pos[:, None] - 1
    in_frac = (fidx >= 0) & (fidx < s)
    fval = jnp.take_along_axis(
        fbytes, jnp.clip(fidx, 0, s - 1), axis=1) if s else out
    out = jnp.where(in_frac, fval, out)
    total = ip_len + 1 + s
    return pack_padded(out, total, c.validity, _round_pow2(w))


def _civil_from_days(z):
    """Days-since-epoch -> (y, m, d); Hinnant's algorithm. jnp's //
    already floors (the original's `z - 146096` trick exists only to make
    C's truncating division floor), so plain floor-div is correct for
    negative days too."""
    z = z + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = y // 400  # floor division — no C-truncation correction needed
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _two_digits(v):
    return (jnp.uint8(48) + (v // 10).astype(jnp.uint8),
            jnp.uint8(48) + (v % 10).astype(jnp.uint8))


def _format_ymd(y, m, d, validity) -> StringColumn:
    cap = y.shape[0]
    out = jnp.zeros((cap, 16), jnp.uint8)
    yd = [(y // 1000) % 10, (y // 100) % 10, (y // 10) % 10, y % 10]
    for i, dig in enumerate(yd):
        out = out.at[:, i].set(jnp.uint8(48) + dig.astype(jnp.uint8))
    out = out.at[:, 4].set(jnp.uint8(45))
    m1, m2 = _two_digits(m)
    out = out.at[:, 5].set(m1).at[:, 6].set(m2)
    out = out.at[:, 7].set(jnp.uint8(45))
    d1, d2 = _two_digits(d)
    out = out.at[:, 8].set(d1).at[:, 9].set(d2)
    lens = jnp.full(cap, 10, jnp.int32)
    return pack_padded(out, lens, validity, 16)


def _timestamp_to_string(c: ColumnVector) -> StringColumn:
    us = c.data.astype(jnp.int64)
    days = us // 86_400_000_000
    rem = us - days * 86_400_000_000
    y, m, d = _civil_from_days(days)
    sec = rem // 1_000_000
    micro = rem % 1_000_000
    hh = sec // 3600
    mm = (sec % 3600) // 60
    ss = sec % 60
    cap = us.shape[0]
    out = jnp.zeros((cap, 32), jnp.uint8)
    yd = [(y // 1000) % 10, (y // 100) % 10, (y // 10) % 10, y % 10]
    for i, dig in enumerate(yd):
        out = out.at[:, i].set(jnp.uint8(48) + dig.astype(jnp.uint8))
    out = out.at[:, 4].set(jnp.uint8(45))
    a, b = _two_digits(m)
    out = out.at[:, 5].set(a).at[:, 6].set(b)
    out = out.at[:, 7].set(jnp.uint8(45))
    a, b = _two_digits(d)
    out = out.at[:, 8].set(a).at[:, 9].set(b)
    out = out.at[:, 10].set(jnp.uint8(32))
    a, b = _two_digits(hh)
    out = out.at[:, 11].set(a).at[:, 12].set(b)
    out = out.at[:, 13].set(jnp.uint8(58))
    a, b = _two_digits(mm)
    out = out.at[:, 14].set(a).at[:, 15].set(b)
    out = out.at[:, 16].set(jnp.uint8(58))
    a, b = _two_digits(ss)
    out = out.at[:, 17].set(a).at[:, 18].set(b)
    # fractional part: ".ffffff" trimmed of trailing zeros (Spark style)
    fdig = jnp.stack([(micro // p) % 10 for p in
                      [100000, 10000, 1000, 100, 10, 1]], axis=1)
    nz = fdig != 0
    any_frac = jnp.any(nz, axis=1)
    # position of last nonzero fractional digit
    last_nz = 5 - jnp.argmax(nz[:, ::-1], axis=1)
    frac_len = jnp.where(any_frac, last_nz + 1, 0).astype(jnp.int32)
    out = jnp.where((jnp.arange(32) == 19)[None, :] & any_frac[:, None],
                    jnp.uint8(46), out)
    k = jnp.arange(32, dtype=jnp.int32)
    fidx = k[None, :] - 20
    in_frac = (fidx >= 0) & (fidx < frac_len[:, None])
    fval = jnp.take_along_axis(fdig, jnp.clip(fidx, 0, 5), axis=1)
    out = jnp.where(in_frac, jnp.uint8(48) + fval.astype(jnp.uint8), out)
    lens = jnp.where(any_frac, 20 + frac_len, 19).astype(jnp.int32)
    return pack_padded(out, lens, c.validity, 32)


def cast_from_string(c: StringColumn, to: dt.DType) -> Column:
    padded = c.padded()
    lens = c.lengths()
    if to.is_integral:
        val, ok = _parse_int(padded, lens)
        # out-of-range for the TARGET width -> null (Spark castToInt:
        # UTF8String.toInt returns failure, never wraps)
        lo_b, hi_b = int(dt.min_value(to)), int(dt.max_value(to))
        ok = ok & (val >= lo_b) & (val <= hi_b)
        data = val.astype(to.physical)
        return make_result(data, c.validity & ok, to)
    if to.is_floating:
        val, ok = _parse_float(padded, lens)
        return make_result(val.astype(to.physical), c.validity & ok, to)
    if isinstance(to, dt.BooleanType):
        return _parse_bool(c, padded, lens)
    if isinstance(to, dt.DateType):
        val, ok = _parse_date(padded, lens)
        return make_result(val.astype(jnp.int32), c.validity & ok, to)
    if isinstance(to, dt.DecimalType):
        val, ok = _parse_float(padded, lens)
        scaled = val * (10.0 ** to.scale)
        unscaled = (jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)).astype(jnp.int64)
        ok = ok & (jnp.abs(unscaled) < 10 ** min(to.precision, 18))
        return make_result(unscaled, c.validity & ok, to)
    raise TypeError(f"cast string -> {to} not supported on TPU")


def _strip_bounds(padded, lens):
    """start/end after trimming ASCII whitespace."""
    cap, w = padded.shape
    k = jnp.arange(w, dtype=jnp.int32)
    in_str = k[None, :] < lens[:, None]
    is_sp = in_str & ((padded == 32) | (padded == 9) | (padded == 10) | (padded == 13))
    non_sp = in_str & ~is_sp
    any_c = jnp.any(non_sp, axis=1)
    start = jnp.where(any_c, jnp.argmax(non_sp, axis=1), 0).astype(jnp.int32)
    end = jnp.where(any_c, w - jnp.argmax(non_sp[:, ::-1], axis=1), 0).astype(jnp.int32)
    return start, end, any_c


def _parse_int(padded, lens):
    cap, w = padded.shape
    start, end, nonempty = _strip_bounds(padded, lens)
    k = jnp.arange(w, dtype=jnp.int32)
    first = jnp.take_along_axis(padded, start[:, None], axis=1)[:, 0]
    neg = first == 45
    has_sign = neg | (first == 43)
    dstart = start + has_sign.astype(jnp.int32)
    in_num = (k[None, :] >= dstart[:, None]) & (k[None, :] < end[:, None])
    digit = padded - jnp.uint8(48)
    is_digit = (padded >= 48) & (padded <= 57)
    # UTF8String.toLong accepts one '.' — the fraction (all digits)
    # truncates toward zero: '12.7' -> 12, '12.' -> 12
    dot_mask = in_num & (padded == 46)
    has_dot = jnp.any(dot_mask, axis=1)
    dot_pos = jnp.where(has_dot, jnp.argmax(dot_mask, axis=1),
                        end).astype(jnp.int32)
    int_zone = in_num & (k[None, :] < dot_pos[:, None])
    frac_zone = in_num & (k[None, :] > dot_pos[:, None])
    ok = nonempty & (dot_pos > dstart) \
        & (jnp.sum(dot_mask, axis=1) <= 1) \
        & jnp.all(~int_zone | is_digit, axis=1) \
        & jnp.all(~frac_zone | is_digit, axis=1)
    in_num = int_zone
    end = dot_pos
    val = jnp.zeros(cap, jnp.int64)
    for i in range(w):
        use = in_num[:, i]
        val = jnp.where(use, val * 10 + digit[:, i].astype(jnp.int64), val)
    # int64 wrap detection: significant digits (leading zeros don't
    # count) beyond 18 can exceed 2^63-1; a 19-digit wrap flips the
    # accumulated value negative, more digits always overflow.
    # Long.MIN ("-9223372036854775808") wraps to exactly MIN with the
    # negative sign applied, which IS representable — allow it.
    nonzero = in_num & (digit != 0) & is_digit
    any_sig = jnp.any(nonzero, axis=1)
    first_sig = jnp.where(any_sig, jnp.argmax(nonzero, axis=1),
                          end).astype(jnp.int32)
    sig = jnp.where(any_sig, end - first_sig, 0)
    wrapped = (sig == 19) & (val < 0)
    min_long = wrapped & neg & (val == jnp.int64(-2 ** 63))
    ok = ok & (sig <= 18) | (ok & (sig == 19) & (~wrapped | min_long))
    val = jnp.where(neg & ~min_long, -val, val)
    return val, ok


def _parse_float(padded, lens):
    """Parse [+-]digits[.digits][eE[+-]digits]. Close-to-strtod accuracy."""
    cap, w = padded.shape
    start, end, nonempty = _strip_bounds(padded, lens)
    k = jnp.arange(w, dtype=jnp.int32)[None, :]
    first = jnp.take_along_axis(padded, start[:, None], axis=1)[:, 0]
    neg = first == 45
    has_sign = neg | (first == 43)
    pos0 = start + has_sign.astype(jnp.int32)
    in_str = (k >= pos0[:, None]) & (k < end[:, None])
    is_digit = (padded >= 48) & (padded <= 57)
    is_dot = padded == 46
    is_e = (padded == 101) | (padded == 69)
    # exponent marker position (first e/E), dot position
    e_mask = in_str & is_e
    has_e = jnp.any(e_mask, axis=1)
    e_pos = jnp.where(has_e, jnp.argmax(e_mask, axis=1), end).astype(jnp.int32)
    dot_mask = in_str & is_dot & (k < e_pos[:, None])
    has_dot = jnp.any(dot_mask, axis=1)
    dot_pos = jnp.where(has_dot, jnp.argmax(dot_mask, axis=1), e_pos).astype(jnp.int32)
    # mantissa digits: positions in [pos0, e_pos) except the dot
    mant_zone = in_str & (k < e_pos[:, None]) & ~is_dot
    ok = nonempty & jnp.all(~mant_zone | is_digit, axis=1)
    ok = ok & (jnp.sum(dot_mask, axis=1) <= 1) & jnp.any(mant_zone & is_digit, axis=1)
    mant = jnp.zeros(cap, jnp.float64)
    ndig_after_dot = jnp.zeros(cap, jnp.int32)
    for i in range(w):
        use = mant_zone[:, i]
        mant = jnp.where(use, mant * 10 + (padded[:, i] - 48).astype(jnp.float64), mant)
        ndig_after_dot = ndig_after_dot + (
            use & (i > dot_pos) & has_dot).astype(jnp.int32)
    # exponent
    e_first_pos = e_pos + 1
    efirst = jnp.take_along_axis(padded, jnp.clip(e_first_pos, 0, w - 1)[:, None],
                                 axis=1)[:, 0]
    eneg = efirst == 45
    e_has_sign = eneg | (efirst == 43)
    e_dstart = e_first_pos + e_has_sign.astype(jnp.int32)
    e_zone = (k >= e_dstart[:, None]) & (k < end[:, None])
    ok = ok & jnp.where(has_e,
                        jnp.any(e_zone & is_digit, axis=1) &
                        jnp.all(~e_zone | is_digit, axis=1),
                        True)
    ev = jnp.zeros(cap, jnp.int32)
    for i in range(w):
        use = e_zone[:, i] & has_e
        ev = jnp.where(use, ev * 10 + (padded[:, i] - 48).astype(jnp.int32), ev)
    ev = jnp.where(eneg, -ev, ev)
    exp = ev - ndig_after_dot
    val = mant * jnp.power(10.0, exp.astype(jnp.float64))
    val = jnp.where(neg, -val, val)
    # special literals (Cast.processFloatingPointSpecialLiterals,
    # case-insensitive after trim): inf/infinity/nan with optional sign
    lowered = jnp.where((padded >= 65) & (padded <= 90), padded + 32,
                        padded)

    def _match_at(s: bytes, from_pos):
        arr = jnp.asarray(np.frombuffer(s, np.uint8))
        n = len(s)
        idx = from_pos[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
        got = jnp.take_along_axis(lowered, jnp.clip(idx, 0, w - 1),
                                  axis=1)
        return (end - from_pos == n) & jnp.all(got == arr[None, :],
                                               axis=1)

    is_inf = _match_at(b"inf", pos0) | _match_at(b"infinity", pos0)
    is_nan = _match_at(b"nan", pos0) & ~has_sign
    special = is_inf | is_nan
    inf_v = jnp.where(neg, -jnp.inf, jnp.inf)
    val = jnp.where(is_inf, inf_v, val)
    val = jnp.where(is_nan, jnp.nan, val)
    ok = ok | (nonempty & special)
    return val, ok


_TRUE_STRS = [b"true", b"t", b"yes", b"y", b"1"]
_FALSE_STRS = [b"false", b"f", b"no", b"n", b"0"]


def _parse_bool(c: StringColumn, padded, lens):
    lowered = jnp.where((padded >= 65) & (padded <= 90), padded + 32, padded)
    cap, w = lowered.shape

    def match(s: bytes):
        n = len(s)
        if n > w:
            return jnp.zeros(cap, jnp.bool_)
        return (lens == n) & jnp.all(
            lowered[:, :n] == jnp.asarray(np.frombuffer(s, np.uint8)), axis=1)

    t = jnp.zeros(cap, jnp.bool_)
    for s in _TRUE_STRS:
        t = t | match(s)
    f = jnp.zeros(cap, jnp.bool_)
    for s in _FALSE_STRS:
        f = f | match(s)
    return make_result(t, c.validity & (t | f), dt.BOOL)


def _parse_date(padded, lens):
    """Spark DateTimeUtils.stringToDate forms: ``yyyy``, ``yyyy-[m]m``,
    ``yyyy-[m]m-[d]d`` with an ignored trailing ``T…``/`` …`` time
    segment after a full date; whitespace-trimmed; REAL calendar
    validation (2019-02-29 -> null, no rollover)."""
    cap, w = padded.shape
    is_digit = (padded >= 48) & (padded <= 57)
    is_dash = padded == 45
    k = jnp.arange(w, dtype=jnp.int32)[None, :]
    start, end0, nonempty = _strip_bounds(padded, lens)
    # the date part ends at the first 'T' or ' ' inside the trimmed
    # region (Spark allows a trailing time segment)
    in_trim = (k >= start[:, None]) & (k < end0[:, None])
    t_mask = in_trim & ((padded == 84) | (padded == 32))
    has_t = jnp.any(t_mask, axis=1)
    end = jnp.where(has_t, jnp.argmax(t_mask, axis=1),
                    end0).astype(jnp.int32)
    in_str = (k >= start[:, None]) & (k < end[:, None])
    dash_mask = in_str & is_dash
    n_dash = jnp.sum(dash_mask, axis=1)
    first_dash = jnp.where(jnp.any(dash_mask, axis=1),
                           jnp.argmax(dash_mask, axis=1),
                           end).astype(jnp.int32)
    after = dash_mask & (k > first_dash[:, None])
    second_dash = jnp.where(jnp.any(after, axis=1),
                            jnp.argmax(after, axis=1),
                            end).astype(jnp.int32)

    def parse_span(lo, hi):
        v = jnp.zeros(cap, jnp.int32)
        good = jnp.ones(cap, jnp.bool_)
        for i in range(w):
            use = (i >= lo) & (i < hi)
            v = jnp.where(use, v * 10 + (padded[:, i] - 48).astype(jnp.int32), v)
            good = good & jnp.where(use, is_digit[:, i], True)
        return v, good

    y, gy = parse_span(start, first_dash)
    m, gm = parse_span(first_dash + 1, second_dash)
    d, gd = parse_span(second_dash + 1, end)
    ylen = first_dash - start
    mlen = second_dash - first_dash - 1
    dlen = end - second_dash - 1
    # segment-shape validity per dash count (year is 4 digits; month &
    # day 1-2; a time suffix needs a COMPLETE date before it)
    y_ok = gy & (ylen == 4)
    shape0 = (n_dash == 0) & y_ok & ~has_t
    shape1 = (n_dash == 1) & y_ok & gm & (mlen >= 1) & (mlen <= 2) \
        & ~has_t
    shape2 = (n_dash == 2) & y_ok & gm & gd & (mlen >= 1) & (mlen <= 2) \
        & (dlen >= 1) & (dlen <= 2)
    m = jnp.where(n_dash >= 1, m, 1)
    d = jnp.where(n_dash >= 2, d, 1)
    ok = nonempty & (shape0 | shape1 | shape2) & (m >= 1) & (m <= 12)
    # real month lengths (proleptic Gregorian leap rule)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    dim = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                      jnp.int32)
    max_d = jnp.take(dim, jnp.clip(m - 1, 0, 11))
    max_d = jnp.where((m == 2) & leap, 29, max_d)
    ok = ok & (d >= 1) & (d <= max_d)
    days = _days_from_civil(y.astype(jnp.int64), m.astype(jnp.int64),
                            d.astype(jnp.int64))
    return days, ok


# ---------------------------------------------------------------------------
# Extended string functions (stringFunctions.scala breadth)
# ---------------------------------------------------------------------------

class Reverse(Expression):
    """reverse(str) — per-row byte reversal (exact for ASCII)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        k = jnp.arange(w, dtype=jnp.int32)
        src = jnp.clip(lens[:, None] - 1 - k[None, :], 0, w - 1)
        out = jnp.take_along_axis(padded, src, axis=1)
        out = jnp.where(k[None, :] < lens[:, None], out,
                        jnp.zeros((), jnp.uint8))
        return pack_padded(out, lens, c.validity, c.pad_bucket)


class _Pad(Expression):
    left = True

    def __init__(self, child: Expression, length: int, pad: str = " "):
        super().__init__(child)
        self.length = length
        self.pad = pad.encode("utf-8") or b" "

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        tgt = self.length
        out_w = _round_pow2(max(tgt, 1))
        pad_arr = jnp.asarray(
            np.frombuffer(self.pad * ((tgt // len(self.pad)) + 1),
                          dtype=np.uint8)[:max(tgt, 1)])
        k = jnp.arange(out_w, dtype=jnp.int32)
        out_len = jnp.minimum(jnp.maximum(lens, tgt), tgt)
        # rows longer than tgt truncate to tgt (Spark lpad/rpad semantics)
        n_pad = jnp.maximum(tgt - lens, 0)
        if self.left:
            # pad bytes then string bytes
            from_pad = k[None, :] < n_pad[:, None]
            src_str = jnp.clip(k[None, :] - n_pad[:, None], 0, w - 1)
        else:
            from_pad = k[None, :] >= lens[:, None]
            src_str = jnp.clip(jnp.broadcast_to(k[None, :], (cap, out_w)),
                               0, w - 1)
        str_bytes = jnp.take_along_axis(
            padded, jnp.clip(src_str, 0, w - 1), axis=1) \
            if w else jnp.zeros((cap, out_w), jnp.uint8)
        pad_idx = jnp.where(self.left, k[None, :],
                            jnp.clip(k[None, :] - lens[:, None], 0,
                                     max(tgt - 1, 0)))
        pad_bytes = jnp.take(pad_arr, jnp.clip(pad_idx, 0,
                                               pad_arr.shape[0] - 1))
        out = jnp.where(from_pad, pad_bytes, str_bytes)
        out = jnp.where(k[None, :] < tgt, out, jnp.zeros((), jnp.uint8))
        return pack_padded(out, jnp.full(cap, tgt, jnp.int32) * 0 + tgt,
                           c.validity, out_w)


class Lpad(_Pad):
    left = True


class Rpad(_Pad):
    left = False


class InitCap(Expression):
    """initcap: first letter of each whitespace-separated word upper,
    rest lower (ASCII)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        lens = c.lengths()
        is_lower = (padded >= 97) & (padded <= 122)
        is_upper = (padded >= 65) & (padded <= 90)
        prev_space = jnp.concatenate(
            [jnp.ones((padded.shape[0], 1), jnp.bool_),
             padded[:, :-1] == 32], axis=1)
        upped = jnp.where(is_lower & prev_space, padded - 32, padded)
        lowed = jnp.where(is_upper & ~prev_space, upped + 32, upped)
        return pack_padded(lowed, lens, c.validity, c.pad_bucket)


class ConcatWs(Expression):
    """concat_ws(sep, ...) — skips nulls (unlike concat)."""

    def __init__(self, sep: str, *children: Expression):
        super().__init__(*children)
        self.sep = sep

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def nullable(self, schema: Schema) -> bool:
        return False

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        from .core import Literal
        cols = [c.eval(batch) for c in self.children]
        cols = [c if isinstance(c, StringColumn) else _as_string_col(c)
                for c in cols]
        sep_raw = np.frombuffer(self.sep.encode("utf-8"), dtype=np.uint8)
        cap = batch.capacity
        w_total = _round_pow2(sum(c.pad_bucket for c in cols) +
                              len(sep_raw) * max(len(cols) - 1, 0) + 1)
        out = jnp.zeros((cap, w_total), jnp.uint8)
        pos = jnp.zeros(cap, jnp.int32)
        k = jnp.arange(w_total, dtype=jnp.int32)
        first_done = jnp.zeros(cap, jnp.bool_)
        for c in cols:
            valid = c.validity
            # separator before this part (only between non-null parts)
            if len(sep_raw):
                put_sep = valid & first_done
                for si, sb in enumerate(sep_raw):
                    tgt = pos + si
                    mask = put_sep[:, None] & (k[None, :] == tgt[:, None])
                    out = jnp.where(mask, jnp.uint8(sb), out)
                pos = jnp.where(put_sep, pos + len(sep_raw), pos)
            p = c.padded()
            lens = c.lengths()
            wp = p.shape[1]
            idx = k[None, :] - pos[:, None]
            src = jnp.clip(idx, 0, wp - 1)
            bytes_ = jnp.take_along_axis(p, src, axis=1)
            write = valid[:, None] & (idx >= 0) & (idx < lens[:, None])
            out = jnp.where(write, bytes_, out)
            pos = jnp.where(valid, pos + lens, pos)
            first_done = first_done | valid
        live = batch.live_mask()
        return pack_padded(out, pos, live, w_total)


def _as_string_col(c):
    from .cast import cast_column
    return cast_column(c, dt.STRING)


class StringLocate(Expression):
    """locate/instr(substr in str) — 1-based position, 0 if absent."""

    def __init__(self, child: Expression, substr: str, start: int = 1):
        super().__init__(child)
        self.substr = substr
        self.start = start

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        raw = np.frombuffer(self.substr.encode("utf-8"), dtype=np.uint8)
        n = len(raw)
        cap = batch.capacity
        lens = c.lengths()
        if self.start <= 0:
            # Spark: locate(sub, str, 0) = 0 regardless of content
            return make_result(jnp.zeros(cap, jnp.int32), c.validity,
                               dt.INT32)
        if n == 0:
            pos = jnp.where(lens >= 0, jnp.int32(self.start), 0)
            return make_result(
                jnp.where(jnp.int32(self.start) <= lens + 1, pos, 0),
                c.validity, dt.INT32)
        padded = c.padded()
        w = c.pad_bucket
        first = jnp.zeros(cap, jnp.int32)
        found = jnp.zeros(cap, jnp.bool_)
        lo = max(self.start - 1, 0)
        for s in range(lo, max(w - n + 1, lo)):
            if s + n > w:
                break
            m = jnp.all(padded[:, s:s + n] == jnp.asarray(raw), axis=1) & \
                (lens >= s + n)
            first = jnp.where(m & ~found, jnp.int32(s + 1), first)
            found = found | m
        return make_result(first, c.validity, dt.INT32)


class StringRepeat(Expression):
    """repeat(str, n) with a plan-time constant n."""

    def __init__(self, child: Expression, n: int):
        super().__init__(child)
        self.n = max(int(n), 0)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        if self.n == 0:
            return pack_padded(jnp.zeros((cap, 1), jnp.uint8),
                               jnp.zeros(cap, jnp.int32), c.validity, 1)
        out_w = _round_pow2(w * self.n)
        k = jnp.arange(out_w, dtype=jnp.int32)
        safe_len = jnp.maximum(lens, 1)
        src = jnp.clip(k[None, :] % safe_len[:, None], 0, w - 1)
        out = jnp.take_along_axis(padded, src, axis=1)
        out_len = lens * self.n
        out = jnp.where(k[None, :] < out_len[:, None], out,
                        jnp.zeros((), jnp.uint8))
        return pack_padded(out, out_len, c.validity, out_w)


class StringReplace(Expression):
    """replace(str, search, replace) with constant search/replace.

    Non-overlapping leftmost matches; expansion-aware output width.
    """

    def __init__(self, child: Expression, search: str, replace: str = ""):
        super().__init__(child)
        if not search:
            raise TypeError("replace search string must be non-empty")
        self.search = np.frombuffer(search.encode("utf-8"), np.uint8)
        self.replace = np.frombuffer(replace.encode("utf-8"), np.uint8)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        ns, nr = len(self.search), len(self.replace)
        # candidate match starts (sliding equality)
        cand = jnp.zeros((cap, w), jnp.bool_)
        for s in range(0, max(w - ns + 1, 0)):
            m = jnp.all(padded[:, s:s + ns] == jnp.asarray(self.search),
                        axis=1) & (lens >= s + ns)
            cand = cand.at[:, s].set(m)
        # non-overlapping leftmost selection: scan with "blocked-until"
        def pick(carry, j_col):
            blocked_until, = carry
            j, col = j_col
            take = col & (j >= blocked_until)
            blocked_until = jnp.where(take, j + ns, blocked_until)
            return (blocked_until,), take
        import jax
        (_, ), takes = jax.lax.scan(
            pick, (jnp.zeros(cap, jnp.int32),),
            (jnp.arange(w, dtype=jnp.int32), cand.T))
        starts = takes.T  # (cap, w) selected match starts
        in_match = jnp.zeros((cap, w), jnp.bool_)
        for off in range(ns):
            rolled = jnp.roll(starts, off, axis=1)
            if off:
                rolled = rolled.at[:, :off].set(False)
            in_match = in_match | rolled
        # per input byte output contribution
        contrib = jnp.where(starts, nr,
                            jnp.where(in_match, 0, 1)).astype(jnp.int32)
        contrib = jnp.where(jnp.arange(w)[None, :] < lens[:, None],
                            contrib, 0)
        out_pos = jnp.cumsum(contrib, axis=1) - contrib  # exclusive
        out_len = jnp.sum(contrib, axis=1)
        grow = max(1, -(-nr // ns)) if ns else 1
        out_w = _round_pow2(max(w * grow, 1))
        out = jnp.zeros((cap, out_w), jnp.uint8)
        rows = jnp.arange(cap)[:, None]
        # literal (non-match) bytes — contrib==1 alone is NOT enough: a
        # match start also has contrib 1 when len(replace)==1
        lit_mask = (contrib == 1) & ~starts
        tgt = jnp.clip(out_pos, 0, out_w - 1)
        out = out.at[rows, tgt].max(
            jnp.where(lit_mask, padded[:, :w], 0))
        # replacement bytes
        for off in range(nr):
            tgt_r = jnp.clip(out_pos + off, 0, out_w - 1)
            out = out.at[rows, tgt_r].max(
                jnp.where(starts, jnp.uint8(self.replace[off]), 0))
        return pack_padded(out, out_len, c.validity, out_w)


class StringTranslate(Expression):
    """translate(str, from, to) — per-byte mapping (ASCII)."""

    def __init__(self, child: Expression, src: str, dst: str):
        super().__init__(child)
        table = np.arange(256, dtype=np.int16)
        delete = np.zeros(256, bool)
        for ch in dst:
            if ord(ch) > 127:
                raise TypeError("translate: non-ASCII unsupported on TPU")
        for i, ch in enumerate(src):
            b = ord(ch)
            if b > 127:
                raise TypeError("translate: non-ASCII unsupported on TPU")
            if i < len(dst):
                table[b] = ord(dst[i])
            else:
                delete[b] = True
        self.table = table.astype(np.uint8)
        self.delete = delete

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        padded = c.padded()
        cap, w = padded.shape
        lens = c.lengths()
        k = jnp.arange(w, dtype=jnp.int32)
        in_str = k[None, :] < lens[:, None]
        mapped = jnp.take(jnp.asarray(self.table),
                          padded.astype(jnp.int32))
        keep = in_str & ~jnp.take(jnp.asarray(self.delete),
                                  padded.astype(jnp.int32))
        # compact kept bytes to the row prefix
        new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        out_len = jnp.sum(keep, axis=1).astype(jnp.int32)
        out = jnp.zeros((cap, w), jnp.uint8)
        rows = jnp.arange(cap)[:, None]
        out = out.at[rows, jnp.clip(new_pos, 0, w - 1)].max(
            jnp.where(keep, mapped, 0))
        return pack_padded(out, out_len, c.validity, c.pad_bucket)
