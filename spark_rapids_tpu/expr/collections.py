"""Collection (array/struct) expressions on device.

Rebuild of the reference's complex-type expression surface (SURVEY §2.5:
collectionOperations.scala ~4k LoC, complexTypeCreator.scala,
complexTypeExtractors.scala). Device lowering rides the static
``pad_bucket`` lane view of ListColumn (columnar/nested.py
element_lanes) — each list kernel is a masked reduction/selection over a
dense ``(capacity, pad_bucket)`` block, which XLA fuses and vectorizes;
there is no per-row ragged loop.

Null semantics follow Spark:
- size(null) -> null, element access out of bounds -> null,
- array_contains: true if found; null if not found and the array has a
  null element (3-valued membership, like IN),
- array_min/max skip nulls; all-null/empty -> null,
- struct field access of a null struct -> null.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.nested import ListColumn, StructColumn
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               StringColumn, round_pow2)
from .core import Expression, Schema, make_result, merged_validity


def _element_type(expr: Expression, schema: Schema) -> dt.DType:
    t = expr.data_type(schema)
    if not isinstance(t, dt.ArrayType):
        raise TypeError(f"expected array input, got {t}")
    return t.element_type


class CreateArray(Expression):
    """array(e1, e2, ...) — fixed-width list per row
    (complexTypeCreator.scala GpuCreateArray)."""

    def __init__(self, *children: Expression):
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        from .conditional import _common_type
        et = _common_type([c.data_type(schema) for c in self.children])
        return dt.ArrayType(et)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        cols = [c.eval(batch) for c in self.children]
        k = len(cols)
        cap = batch.capacity
        child_dt = cols[0].dtype
        for c in cols[1:]:
            if c.dtype != child_dt:
                child_dt = dt.promote(child_dt, c.dtype)
        phys = child_dt.physical
        live = batch.live_mask()
        # interleave row-major: row i's elements at [i*k, (i+1)*k)
        vals = jnp.stack([c.data.astype(phys) for c in cols],
                         axis=1).reshape(cap * k)
        valid = jnp.stack([c.validity & live for c in cols],
                          axis=1).reshape(cap * k)
        child_cap = round_pow2(max(cap * k, 8))
        if child_cap > cap * k:
            vals = jnp.concatenate(
                [vals, jnp.zeros(child_cap - cap * k, phys)])
            valid = jnp.concatenate(
                [valid, jnp.zeros(child_cap - cap * k, jnp.bool_)])
        vals = jnp.where(valid, vals, jnp.zeros((), phys))
        child = ColumnVector(vals, valid, child_dt)
        offsets = jnp.arange(cap + 1, dtype=jnp.int32) * k
        # dead rows keep extents but validity=False; kernels mask on it
        return ListColumn(offsets, child, live, child_dt,
                          pad_bucket=round_pow2(max(k, 1)))

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class Size(Expression):
    """size(array) (collectionOperations.scala GpuSize); null -> null."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        _element_type(self.children[0], schema)
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        return make_result(lc.lengths().astype(jnp.int32), lc.validity,
                           dt.INT32)


class GetArrayItem(Expression):
    """arr[i] — zero-based element access (complexTypeExtractors.scala
    GpuGetArrayItem). Out of bounds / negative -> null."""

    def __init__(self, child: Expression, ordinal: Expression):
        super().__init__(child, ordinal)

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch):
        lc: ListColumn = self.children[0].eval(batch)
        idx = self.children[1].eval(batch)
        lens = lc.lengths()
        i = idx.data.astype(jnp.int32)
        in_bounds = (i >= 0) & (i < lens)
        ok = lc.validity & idx.validity & in_bounds
        src = jnp.clip(lc.offsets[:-1] + jnp.clip(i, 0), 0,
                       lc.child_capacity - 1)
        return lc.child.gather(src, ok)


class ElementAt(Expression):
    """element_at(arr, i) — 1-based; negative counts from the end
    (GpuElementAt)."""

    def __init__(self, child: Expression, ordinal: Expression):
        super().__init__(child, ordinal)

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.ArrayType):
            return t.element_type
        raise TypeError(f"element_at on {t}")

    def eval(self, batch: ColumnarBatch):
        lc: ListColumn = self.children[0].eval(batch)
        idx = self.children[1].eval(batch)
        lens = lc.lengths()
        i = idx.data.astype(jnp.int32)
        zero_based = jnp.where(i > 0, i - 1, lens + i)
        in_bounds = (zero_based >= 0) & (zero_based < lens) & (i != 0)
        ok = lc.validity & idx.validity & in_bounds
        src = jnp.clip(lc.offsets[:-1] + jnp.clip(zero_based, 0), 0,
                       lc.child_capacity - 1)
        return lc.child.gather(src, ok)


class ArrayContains(Expression):
    """array_contains(arr, v) with 3-valued membership
    (collectionOperations.scala GpuArrayContains)."""

    def __init__(self, child: Expression, value: Expression):
        super().__init__(child, value)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        needle = self.children[1].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        hit = elem_ok & (vals == needle.data[:, None])
        found = jnp.any(hit, axis=1)
        has_null_elem = jnp.any(lane_ok & ~elem_ok, axis=1)
        ok = lc.validity & needle.validity & (found | ~has_null_elem)
        return make_result(found, ok, dt.BOOL)


class _ArrayExtreme(Expression):
    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        et = lc.dtype.element_type
        fill = self._fill(vals.dtype, et)
        masked = jnp.where(elem_ok, vals, fill)
        out = self._reduce(masked, axis=1)
        any_elem = jnp.any(elem_ok, axis=1)
        return make_result(out, lc.validity & any_elem, et)


class ArrayMin(_ArrayExtreme):
    """array_min: nulls skipped (GpuArrayMin)."""

    def _fill(self, phys, et):
        return jnp.array(dt.max_value(et), phys)

    def _reduce(self, x, axis):
        return jnp.min(x, axis=axis)


class ArrayMax(_ArrayExtreme):
    """array_max: nulls skipped (GpuArrayMax)."""

    def _fill(self, phys, et):
        return jnp.array(dt.min_value(et), phys)

    def _reduce(self, x, axis):
        return jnp.max(x, axis=axis)


class SortArray(Expression):
    """sort_array(arr, asc) over primitive elements (GpuSortArray).
    Null elements first for asc, last for desc (Spark semantics)."""

    def __init__(self, child: Expression, ascending: bool = True):
        super().__init__(child)
        self.ascending = ascending

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        et = lc.dtype.element_type
        # order key: dead lanes always last; null elements first for
        # asc, last for desc (Spark sort_array semantics)
        null_cls = 1 if self.ascending else 2
        val_cls = 2 if self.ascending else 1
        cls = jnp.where(~lane_ok, jnp.int8(3),
                        jnp.where(~elem_ok, jnp.int8(null_cls),
                                  jnp.int8(val_cls)))
        # stable two-pass argsort: values then class
        order = jnp.argsort(vals, axis=1, stable=True,
                            descending=not self.ascending)
        cls_o = jnp.take_along_axis(cls, order, axis=1)
        order2 = jnp.argsort(cls_o, axis=1, stable=True)
        order = jnp.take_along_axis(order, order2, axis=1)
        new_vals = jnp.take_along_axis(vals, order, axis=1)
        new_ok = jnp.take_along_axis(elem_ok, order, axis=1)
        # repack lanes into a flat child with the original offsets
        cap, w = new_vals.shape
        starts = lc.offsets[:-1]
        lens = lc.lengths()
        child_cap = lc.child_capacity
        pos = jnp.arange(child_cap, dtype=jnp.int32)
        row = jnp.searchsorted(lc.offsets[1:], pos,
                               side="right").astype(jnp.int32)
        row_c = jnp.clip(row, 0, cap - 1)
        within = jnp.clip(pos - jnp.take(starts, row_c), 0, w - 1)
        data = new_vals[row_c, within]
        okv = new_ok[row_c, within] & (pos < lc.offsets[cap])
        data = jnp.where(okv, data, jnp.zeros((), data.dtype))
        child = ColumnVector(data, okv, et)
        return ListColumn(lc.offsets, child, lc.validity, et,
                          lc.pad_bucket)

    def __repr__(self):
        return (f"sort_array({self.children[0]!r}, "
                f"{'asc' if self.ascending else 'desc'})")


def _first_occurrence(vals, elem_ok, lane_ok):
    """(cap, W) bool: lane k is the FIRST occurrence of its value in
    its row (null elements count as one value). The per-row W x W
    equality triangle — W is the static pad bucket, so this stays a
    dense VPU op."""
    same = (vals[:, :, None] == vals[:, None, :])
    both_null = (~elem_ok[:, :, None] & lane_ok[:, :, None] &
                 ~elem_ok[:, None, :] & lane_ok[:, None, :])
    eq = (same & elem_ok[:, :, None] & elem_ok[:, None, :]) | both_null
    w = vals.shape[1]
    earlier = jnp.tril(jnp.ones((w, w), jnp.bool_), k=-1)
    dup = jnp.any(eq & earlier[None, :, :], axis=2)
    return lane_ok & ~dup


def _lanes_repack(lc: ListColumn, vals, keep, new_ok,
                  element_type: dt.DType) -> ListColumn:
    """Left-compact kept lanes into a fresh ListColumn (new lengths =
    per-row keep counts). Shared by every lane-filtering function."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    vals_c = jnp.take_along_axis(vals, order, axis=1)
    ok_c = jnp.take_along_axis(new_ok & keep, order, axis=1)
    lens = jnp.where(lc.validity,
                     jnp.sum(keep, axis=1, dtype=jnp.int32), 0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    from .higher_order import _lanes_to_list
    base = ListColumn(offsets, lc.child, lc.validity, element_type,
                      lc.pad_bucket)
    return _lanes_to_list(base, vals_c, ok_c, element_type,
                          offsets=offsets,
                          child_cap=lc.child_capacity)


class _LaneBinaryBase(Expression):
    """Shared typing for (array, array) -> ... functions."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__(left, right)

    def _elem_type(self, schema: Schema) -> dt.DType:
        lt = _element_type(self.children[0], schema)
        rt = _element_type(self.children[1], schema)
        if lt != rt:
            lt = dt.promote(lt, rt)
        return lt

    def _lanes2(self, batch):
        a: ListColumn = self.children[0].eval(batch)
        b: ListColumn = self.children[1].eval(batch)
        av, al, ao = a.element_lanes()
        bv, bl, bo = b.element_lanes()
        if av.dtype != bv.dtype:
            phys = jnp.promote_types(av.dtype, bv.dtype)
            av, bv = av.astype(phys), bv.astype(phys)
        return a, b, av, al, ao, bv, bl, bo


class ArrayDistinct(Expression):
    """array_distinct: first occurrence kept, order preserved
    (collectionOperations.scala GpuArrayDistinct role)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(_element_type(self.children[0], schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        keep = _first_occurrence(vals, elem_ok, lane_ok)
        return _lanes_repack(lc, vals, keep, elem_ok,
                             lc.dtype.element_type)


class ArrayUnion(_LaneBinaryBase):
    """array_union(a, b): distinct elements of a then b's unseen ones
    (GpuArrayUnion role)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(self._elem_type(schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        from ..columnar.vector import round_pow2
        a, b, av, al, ao, bv, bl, bo = self._lanes2(batch)
        vals = jnp.concatenate([av, bv], axis=1)
        lane_ok = jnp.concatenate([al, bl], axis=1)
        elem_ok = jnp.concatenate([ao, bo], axis=1)
        keep = _first_occurrence(vals, elem_ok, lane_ok)
        validity = a.validity & b.validity
        et = dt.promote(a.dtype.element_type, b.dtype.element_type) \
            if a.dtype.element_type != b.dtype.element_type \
            else a.dtype.element_type
        order = jnp.argsort(~keep, axis=1, stable=True)
        vals_c = jnp.take_along_axis(vals, order, axis=1)
        ok_c = jnp.take_along_axis(elem_ok & keep, order, axis=1)
        lens = jnp.where(validity,
                         jnp.sum(keep, axis=1, dtype=jnp.int32), 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        cap_needed = round_pow2(max(
            a.child_capacity + b.child_capacity, 8))
        from .higher_order import _lanes_to_list
        base = ListColumn(offsets, a.child, validity, et, vals.shape[1])
        return _lanes_to_list(base, vals_c, ok_c, et,
                              offsets=offsets, child_cap=cap_needed)


class _MembershipBinary(_LaneBinaryBase):
    """a's lanes tested for membership in b."""

    def _member(self, batch):
        a, b, av, al, ao, bv, bl, bo = self._lanes2(batch)
        hit = jnp.any(
            (av[:, :, None] == bv[:, None, :]) &
            ao[:, :, None] & bo[:, None, :], axis=2)
        a_null_in_b = jnp.any(bl & ~bo, axis=1)  # b has a null elem
        return a, b, av, al, ao, hit, a_null_in_b


class ArrayIntersect(_MembershipBinary):
    """array_intersect: distinct a-elements present in b
    (GpuArrayIntersect role; null kept when both sides have null)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(self._elem_type(schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        a, b, av, al, ao, hit, b_has_null = self._member(batch)
        first = _first_occurrence(av, ao, al)
        keep = first & ((ao & hit) |
                        (~ao & al & b_has_null[:, None]))
        out = _lanes_repack(a, av, keep, ao, a.dtype.element_type)
        return out.with_validity(a.validity & b.validity)


class ArrayExcept(_MembershipBinary):
    """array_except: distinct a-elements absent from b."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(self._elem_type(schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        a, b, av, al, ao, hit, b_has_null = self._member(batch)
        first = _first_occurrence(av, ao, al)
        keep = first & ((ao & ~hit) |
                        (~ao & al & ~b_has_null[:, None]))
        out = _lanes_repack(a, av, keep, ao, a.dtype.element_type)
        return out.with_validity(a.validity & b.validity)


class ArraysOverlap(_MembershipBinary):
    """arrays_overlap: true if a common non-null element exists; null
    when none found but either side holds a null element (3VL,
    GpuArraysOverlap)."""

    def data_type(self, schema: Schema) -> dt.DType:
        self._elem_type(schema)
        return dt.BOOL

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a, b, av, al, ao, bv, bl, bo = self._lanes2(batch)
        hit = jnp.any(
            (av[:, :, None] == bv[:, None, :]) &
            ao[:, :, None] & bo[:, None, :], axis=(1, 2))
        a_has_null = jnp.any(al & ~ao, axis=1)
        b_has_null = jnp.any(bl & ~bo, axis=1)
        both_nonempty = jnp.any(al, axis=1) & jnp.any(bl, axis=1)
        # Spark: no common element -> null iff both non-empty and
        # either side holds a null element; else false
        nullish = both_nonempty & (a_has_null | b_has_null)
        ok = a.validity & b.validity & (hit | ~nullish)
        return make_result(hit, ok, dt.BOOL)


class ArrayRemove(Expression):
    """array_remove(arr, v): drop elements equal to v; null elements
    stay (Spark semantics); null v -> null result."""

    def __init__(self, child: Expression, value: Expression):
        super().__init__(child, value)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(_element_type(self.children[0], schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        v = self.children[1].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        eq = elem_ok & (vals == v.data[:, None])
        keep = lane_ok & ~eq
        out = _lanes_repack(lc, vals, keep, elem_ok,
                            lc.dtype.element_type)
        return out.with_validity(lc.validity & v.validity)


class ArrayPosition(Expression):
    """array_position(arr, v): 1-based first index, 0 when absent
    (GpuArrayPosition); null inputs -> null."""

    def __init__(self, child: Expression, value: Expression):
        super().__init__(child, value)

    def data_type(self, schema: Schema) -> dt.DType:
        _element_type(self.children[0], schema)
        return dt.INT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        v = self.children[1].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        hit = elem_ok & (vals == v.data[:, None])
        found = jnp.any(hit, axis=1)
        first = jnp.argmax(hit, axis=1).astype(jnp.int64) + 1
        pos = jnp.where(found, first, jnp.int64(0))
        return make_result(pos, lc.validity & v.validity, dt.INT64)


class Slice(Expression):
    """slice(arr, start, length): 1-based; negative start counts from
    the end (GpuSlice). start=0 -> error in Spark; here -> null."""

    def __init__(self, child: Expression, start: Expression,
                 length: Expression):
        super().__init__(child, start, length)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(_element_type(self.children[0], schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        s = self.children[1].eval(batch)
        n = self.children[2].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        lens = lc.lengths()
        start = s.data.astype(jnp.int32)
        zero_based = jnp.where(start > 0, start - 1, lens + start)
        count = jnp.maximum(n.data.astype(jnp.int32), 0)
        k = jnp.arange(lc.pad_bucket, dtype=jnp.int32)[None, :]
        sel = (k >= zero_based[:, None]) & \
              (k < (zero_based + count)[:, None]) & lane_ok
        ok_in = (start != 0) & s.validity & n.validity & \
            (n.data >= 0)
        out = _lanes_repack(lc, vals, sel, elem_ok,
                            lc.dtype.element_type)
        return out.with_validity(lc.validity & ok_in)


class ArrayReverse(Expression):
    """reverse(array) — element order flipped per row (GpuReverse)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(_element_type(self.children[0], schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        lens = lc.lengths()
        k = jnp.arange(lc.pad_bucket, dtype=jnp.int32)[None, :]
        src = jnp.clip(lens[:, None] - 1 - k, 0, lc.pad_bucket - 1)
        rv = jnp.take_along_axis(vals, src, axis=1)
        rok = jnp.take_along_axis(elem_ok, src, axis=1) & lane_ok
        from .higher_order import _lanes_to_list
        return _lanes_to_list(lc, rv, rok, lc.dtype.element_type)


class ArrayRepeat(Expression):
    """array_repeat(v, n) with a LITERAL count (static shapes need a
    bound; dynamic counts fall back to CPU via the planner tag)."""

    def __init__(self, value: Expression, count: Expression):
        super().__init__(value, count)

    def _count(self):
        from .core import Literal
        c = self.children[1]
        return c.value if isinstance(c, Literal) else None

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(self.children[0].data_type(schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        from ..columnar.vector import round_pow2
        n = self._count()
        if n is None:
            raise RuntimeError("array_repeat with non-literal count "
                               "must run on CPU (planner tag)")
        n = max(int(n), 0)
        v = self.children[0].eval(batch)
        cap = batch.capacity
        live = batch.live_mask() & v.validity
        vals = jnp.broadcast_to(v.data[:, None], (cap, max(n, 1)))
        ok = jnp.broadcast_to((v.validity & live)[:, None],
                              (cap, max(n, 1)))
        if n == 0:
            ok = jnp.zeros_like(ok)
        lens = jnp.where(batch.live_mask(), jnp.int32(n), 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        child_cap = round_pow2(max(cap * max(n, 1), 8))
        from .higher_order import _lanes_to_list
        base = ListColumn(offsets, ColumnVector(
            jnp.zeros(child_cap, v.data.dtype),
            jnp.zeros(child_cap, jnp.bool_), v.dtype),
            batch.live_mask(), v.dtype, round_pow2(max(n, 1)))
        return _lanes_to_list(base, vals, ok, v.dtype,
                              offsets=offsets, child_cap=child_cap)


class _CpuOnlyCollection(Expression):
    """Collection functions whose device lowering needs ragged/nested
    lane shapes not yet built — the planner tags them CPU (the
    reference gates the same ops per-type via TypeSig); the CPU engine
    (plan/cpu_eval.py) carries execution."""

    def eval(self, batch: ColumnarBatch):
        raise RuntimeError(
            f"{type(self).__name__} must run on the CPU engine "
            "(planner tag)")


class Flatten(Expression):
    """flatten(array<array<T>>) -> array<T> (GpuFlattenArray).

    Device lane: the compact list-of-list layout makes this (almost) an
    offsets relabel — row i's flat length is the inner-offsets span of
    its outer extent; the child repacks with one ranges-gather. A NULL
    inner array nulls the whole result row (Spark semantics)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if not (isinstance(t, dt.ArrayType) and
                isinstance(t.element_type, dt.ArrayType)):
            raise TypeError(f"flatten of {t}")
        return t.element_type

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        outer = self.children[0].eval(batch)
        inner: ListColumn = outer.child
        cap = outer.capacity
        live = batch.live_mask()
        # any NULL inner array in the extent => NULL output row
        bad_pref = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum((~inner.validity).astype(jnp.int32))])
        o0 = outer.offsets[:-1]
        o1 = outer.offsets[1:]
        any_null = (jnp.take(bad_pref, o1) - jnp.take(bad_pref, o0)) > 0
        validity = outer.validity & live & ~any_null
        starts = jnp.take(inner.offsets, o0)
        lens = jnp.where(validity,
                         jnp.take(inner.offsets, o1) - starts, 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        child_cap = inner.child_capacity
        from ..columnar.vector import rows_from_offsets
        pos = jnp.arange(child_cap, dtype=jnp.int32)
        row_c = rows_from_offsets(offsets[:-1], lens, child_cap)
        within = pos - jnp.take(offsets, row_c)
        src = jnp.take(starts, row_c) + within
        elem_ok = pos < offsets[cap]
        child = inner.child.gather(
            jnp.clip(src, 0, child_cap - 1), elem_ok)
        return ListColumn(offsets, child, validity,
                          self.data_type(batch.schema()).element_type,
                          outer.pad_bucket * inner.pad_bucket)


class ArraysZip(Expression):
    """arrays_zip(a, b, ...) -> array<struct> (GpuArraysZip).

    Device lane: output length per row is the MAX input length; field j
    of element (row, pos) gathers input j's element when pos is in
    range, else null — one flat-position pass per field."""

    def __init__(self, *children: Expression):
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        fields = []
        for i, c in enumerate(self.children):
            t = c.data_type(schema)
            if not isinstance(t, dt.ArrayType):
                raise TypeError(f"arrays_zip of {t}")
            fields.append((str(i), t.element_type))
        return dt.ArrayType(dt.StructType(tuple(fields)))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        from ..columnar.nested import StructColumn
        from ..columnar.vector import round_pow2, rows_from_offsets
        lists = [c.eval(batch) for c in self.children]
        cap = batch.capacity
        live = batch.live_mask()
        validity = live
        for lc in lists:
            validity = validity & lc.validity  # Spark: any null -> null
        lens = jnp.zeros(cap, jnp.int32)
        for lc in lists:
            lens = jnp.maximum(lens, lc.lengths())
        lens = jnp.where(validity, lens, 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        # sum(max(len_i)) <= sum_i(total elements of input i): the sum
        # of child capacities is a hard bound on the zipped total
        child_cap = round_pow2(
            max(sum(lc.child_capacity for lc in lists), 8))
        pos = jnp.arange(child_cap, dtype=jnp.int32)
        row_c = rows_from_offsets(offsets[:-1], lens, child_cap)
        within = pos - jnp.take(offsets, row_c)
        elem_ok = pos < offsets[cap]
        fields = []
        ftypes = []
        for lc in lists:
            in_range = elem_ok & (within < jnp.take(lc.lengths(), row_c))
            src = jnp.take(lc.offsets[:-1], row_c) + within
            fields.append(lc.child.gather(
                jnp.clip(src, 0, lc.child_capacity - 1), in_range))
            ftypes.append(lc.dtype.element_type)
        st = dt.StructType(tuple((str(i), t)
                                 for i, t in enumerate(ftypes)))
        child = StructColumn(fields, elem_ok, st)
        return ListColumn(offsets, child, validity, st,
                          max(lc.pad_bucket for lc in lists))


class ArrayJoin(Expression):
    """array_join(array<string>, sep[, null_replacement])
    (GpuArrayJoin). Device lane: per-element effective byte extents
    (element bytes + separator except after the last kept element;
    null elements replaced or skipped per Spark), then one
    byte-position pass assembles the output chars."""

    def __init__(self, child: Expression, sep: str,
                 null_replacement: Optional[str] = None):
        super().__init__(child)
        self.sep = sep
        self.null_replacement = null_replacement

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if not (isinstance(t, dt.ArrayType) and
                t.element_type == dt.STRING):
            raise TypeError(f"array_join of {t}")
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        from ..columnar.vector import StringColumn, rows_from_offsets
        lc = self.children[0].eval(batch)
        sc: StringColumn = lc.child
        cap = lc.capacity
        ccap = lc.child_capacity
        live = batch.live_mask()
        validity = lc.validity & live
        sep = jnp.asarray(
            np.frombuffer(self.sep.encode(), np.uint8).copy())
        sep_len = sep.shape[0]
        repl = None
        if self.null_replacement is not None:
            repl = jnp.asarray(np.frombuffer(
                self.null_replacement.encode(), np.uint8).copy())
        # per ELEMENT: kept? effective byte length?
        epos = jnp.arange(ccap, dtype=jnp.int32)
        erow = rows_from_offsets(lc.offsets[:-1], lc.lengths(), ccap)
        e_in = (epos < lc.offsets[cap]) & jnp.take(validity, erow)
        e_valid = sc.validity & e_in
        if repl is None:
            kept = e_valid
            body_len = jnp.where(kept, sc.lengths(), 0)
        else:
            kept = e_in
            body_len = jnp.where(e_valid, sc.lengths(),
                                 jnp.int32(repl.shape[0]))
            body_len = jnp.where(kept, body_len, 0)
        # rank of kept element within its row + kept count per row
        kept_i = kept.astype(jnp.int32)
        kpref = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(kept_i)])
        row_base = jnp.take(kpref, jnp.take(lc.offsets[:-1], erow))
        krank = jnp.take(kpref, epos) - row_base     # rank among kept
        kcnt = (jnp.take(kpref, jnp.take(lc.offsets[1:], erow)) -
                row_base)
        is_last = kept & (krank == kcnt - 1)
        ext_len = jnp.where(kept, body_len +
                            jnp.where(is_last, 0, sep_len), 0)
        e_offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(ext_len, dtype=jnp.int32)])
        out_lens_pref = jnp.take(e_offsets, lc.offsets)
        out_lens = out_lens_pref[1:] - out_lens_pref[:-1]
        out_lens = jnp.where(validity, out_lens, 0)
        out_offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(out_lens, dtype=jnp.int32)])
        # assemble: one pass over output byte positions
        from ..columnar.vector import round_pow2
        nbytes = round_pow2(max(int(sc.char_capacity) +
                                (sep_len + (repl.shape[0] if repl is
                                            not None else 0)) *
                                max(ccap, 1), 128))
        bpos = jnp.arange(nbytes, dtype=jnp.int32)
        belem = rows_from_offsets(e_offsets[:-1], ext_len, nbytes)
        bwithin = bpos - jnp.take(e_offsets, belem)
        in_body = bwithin < jnp.take(body_len, belem)
        src_valid = jnp.take(e_valid, belem)
        body_src = jnp.take(sc.offsets[:-1], belem) + bwithin
        body_byte = jnp.take(sc.chars,
                             jnp.clip(body_src, 0, sc.char_capacity - 1))
        if repl is not None:
            rb = jnp.take(repl, jnp.clip(bwithin, 0,
                                         max(repl.shape[0] - 1, 0)))
            body_byte = jnp.where(src_valid, body_byte, rb)
        sep_byte = jnp.take(
            sep, jnp.clip(bwithin - jnp.take(body_len, belem), 0,
                          max(sep_len - 1, 0))) if sep_len else \
            jnp.zeros((), jnp.uint8)
        byte = jnp.where(in_body, body_byte, sep_byte)
        # remap element-space positions into compact output positions:
        # element extents are already contiguous in row order, so the
        # e_offsets space IS the output space restricted to live rows
        total = out_offsets[cap]
        chars = jnp.where(bpos < total, byte, jnp.zeros((), jnp.uint8))
        repl_len = int(repl.shape[0]) if repl is not None else 0
        per_elem = max(sc.pad_bucket, repl_len) + sep_len
        return StringColumn(out_offsets, chars, validity,
                            pad_bucket=round_pow2(
                                max(lc.pad_bucket * per_elem, 8)))


class ZipWith(Expression):
    """zip_with(a, b, (x, y) -> f) (higherOrderFunctions.scala
    GpuZipWith role). Device lane: both inputs lower to aligned
    (capacity, pad) element lanes (the shorter side's missing lanes
    bind as null), the lambda body evaluates over the lane batch, and
    the result repacks at max-length extents."""

    def __init__(self, left: Expression, right: Expression,
                 x_var, y_var, body: Expression):
        super().__init__(left, right, body)
        self.x_var = x_var
        self.y_var = y_var

    def references(self) -> set:
        refs = set()
        for c in self.children:
            refs |= c.references()
        return refs - {self.x_var.name, self.y_var.name}

    def data_type(self, schema: Schema) -> dt.DType:
        lt = self.children[0].data_type(schema)
        rt = self.children[1].data_type(schema)
        if not (isinstance(lt, dt.ArrayType) and
                isinstance(rt, dt.ArrayType)):
            raise TypeError("zip_with needs two arrays")
        self.x_var._dtype = lt.element_type
        self.y_var._dtype = rt.element_type
        return dt.ArrayType(self.children[2].data_type(schema))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        from ..columnar.vector import round_pow2
        from .higher_order import _lanes_to_list
        la = self.children[0].eval(batch)
        lb = self.children[1].eval(batch)
        self.data_type(batch.schema())  # bind lambda var dtypes
        cap = batch.capacity
        w = max(la.pad_bucket, lb.pad_bucket)
        live = batch.live_mask()
        validity = live & la.validity & lb.validity
        lens = jnp.where(validity,
                         jnp.maximum(la.lengths(), lb.lengths()), 0)

        def lanes(lc):
            vals, lane_ok, elem_ok = lc.element_lanes()
            if lc.pad_bucket < w:
                padm = ((0, 0), (0, w - lc.pad_bucket))
                vals = jnp.pad(vals, padm)
                elem_ok = jnp.pad(elem_ok, padm)
            return vals, elem_ok
        va, oa = lanes(la)
        vb, ob = lanes(lb)
        k = jnp.arange(w, dtype=jnp.int32)[None, :]
        lane_ok = (k < lens[:, None]) & validity[:, None]
        n = cap * w
        xcol = ColumnVector(va.reshape(n), (oa & lane_ok).reshape(n),
                            la.dtype.element_type)
        ycol = ColumnVector(vb.reshape(n), (ob & lane_ok).reshape(n),
                            lb.dtype.element_type)
        lane_batch = ColumnarBatch([xcol, ycol],
                                   [self.x_var.name, self.y_var.name],
                                   n)
        # outer column references inside the body
        from .higher_order import _outer_refs
        outer = _outer_refs(self.children[2],
                            (self.x_var, self.y_var))
        if outer:
            rows = jnp.repeat(jnp.arange(cap, dtype=jnp.int32), w)
            sub = batch.select([c for c in batch.names if c in outer])
            expanded = sub.gather(rows, batch.num_rows * w)
            lane_batch = ColumnarBatch(
                lane_batch.columns + expanded.columns,
                lane_batch.names + expanded.names, n)
        out = self.children[2].eval(lane_batch)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        child_cap = round_pow2(max(cap * w, 8))
        base = ListColumn(offsets, ColumnVector(
            jnp.zeros(child_cap, out.data.dtype),
            jnp.zeros(child_cap, jnp.bool_), out.dtype),
            validity, out.dtype, w)
        return _lanes_to_list(
            base, out.data.reshape(cap, w),
            (out.validity & lane_ok.reshape(n)).reshape(cap, w),
            out.dtype, offsets=offsets, child_cap=child_cap)


class MapConcat(_CpuOnlyCollection):
    """map_concat(m1, m2, ...) — later maps win duplicate keys
    (Spark 3.x LAST_WIN policy; GpuMapConcat)."""

    def __init__(self, *children: Expression):
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        ts_ = [c.data_type(schema) for c in self.children]
        for t in ts_:
            if not isinstance(t, dt.MapType):
                raise TypeError(f"map_concat of {t}")
        return ts_[0]


def zip_with(a, b, fn):
    from .core import _lit
    from .higher_order import LambdaVariable
    x, y = LambdaVariable(), LambdaVariable()
    return ZipWith(_lit(a), _lit(b), x, y, _lit(fn(x, y)))


class CreateNamedStruct(Expression):
    """named_struct(n1, v1, ...) (complexTypeCreator.scala
    GpuCreateNamedStruct)."""

    def __init__(self, names: Sequence[str], values: Sequence[Expression]):
        super().__init__(*values)
        self.names = list(names)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.StructType(tuple(
            (n, v.data_type(schema))
            for n, v in zip(self.names, self.children)))

    def eval(self, batch: ColumnarBatch) -> StructColumn:
        kids = [c.eval(batch) for c in self.children]
        live = batch.live_mask()
        st = dt.StructType(tuple(
            (n, k.dtype) for n, k in zip(self.names, kids)))
        return StructColumn(kids, live, st)

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}"
                          for n, v in zip(self.names, self.children))
        return f"named_struct({inner})"


class GetStructField(Expression):
    """struct.field access (complexTypeExtractors.scala
    GpuGetStructField)."""

    def __init__(self, child: Expression, field: str):
        super().__init__(child)
        self.field = field

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if not isinstance(t, dt.StructType):
            raise TypeError(f"field access on {t}")
        for n, ft in t.fields:
            if n == self.field:
                return ft
        raise KeyError(self.field)

    def eval(self, batch: ColumnarBatch):
        sc: StructColumn = self.children[0].eval(batch)
        child = sc.field(self.field)
        v = child.validity & sc.validity
        if isinstance(child, ColumnVector):
            return make_result(child.data, v, child.dtype)
        return child.with_validity(v)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"


class Explode(Expression):
    """Marker generator expression: one output row per array element
    (GpuExplode, GpuGenerateExec). Never evaluated row-wise — the
    planner rewrites a projection containing Explode into a Generate
    node (plan/logical.py)."""

    def __init__(self, child: Expression, outer: bool = False,
                 with_position: bool = False):
        super().__init__(child)
        self.outer = outer
        self.with_position = with_position

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch):
        raise RuntimeError("Explode must be planned as Generate, not "
                           "evaluated as a row expression")

    def __repr__(self):
        kind = "posexplode" if self.with_position else "explode"
        return f"{kind}{'_outer' if self.outer else ''}" \
            f"({self.children[0]!r})"


def explode(e) -> Explode:
    return Explode(e)


def explode_outer(e) -> Explode:
    return Explode(e, outer=True)


def posexplode(e) -> Explode:
    return Explode(e, with_position=True)


def array(*exprs) -> CreateArray:
    from .core import _lit
    return CreateArray(*[_lit(e) for e in exprs])


def struct(**kw) -> CreateNamedStruct:
    from .core import _lit
    return CreateNamedStruct(list(kw), [_lit(v) for v in kw.values()])
