"""Collection (array/struct) expressions on device.

Rebuild of the reference's complex-type expression surface (SURVEY §2.5:
collectionOperations.scala ~4k LoC, complexTypeCreator.scala,
complexTypeExtractors.scala). Device lowering rides the static
``pad_bucket`` lane view of ListColumn (columnar/nested.py
element_lanes) — each list kernel is a masked reduction/selection over a
dense ``(capacity, pad_bucket)`` block, which XLA fuses and vectorizes;
there is no per-row ragged loop.

Null semantics follow Spark:
- size(null) -> null, element access out of bounds -> null,
- array_contains: true if found; null if not found and the array has a
  null element (3-valued membership, like IN),
- array_min/max skip nulls; all-null/empty -> null,
- struct field access of a null struct -> null.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.nested import ListColumn, StructColumn
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               StringColumn, round_pow2)
from .core import Expression, Schema, make_result, merged_validity


def _element_type(expr: Expression, schema: Schema) -> dt.DType:
    t = expr.data_type(schema)
    if not isinstance(t, dt.ArrayType):
        raise TypeError(f"expected array input, got {t}")
    return t.element_type


class CreateArray(Expression):
    """array(e1, e2, ...) — fixed-width list per row
    (complexTypeCreator.scala GpuCreateArray)."""

    def __init__(self, *children: Expression):
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        from .conditional import _common_type
        et = _common_type([c.data_type(schema) for c in self.children])
        return dt.ArrayType(et)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        cols = [c.eval(batch) for c in self.children]
        k = len(cols)
        cap = batch.capacity
        child_dt = cols[0].dtype
        for c in cols[1:]:
            if c.dtype != child_dt:
                child_dt = dt.promote(child_dt, c.dtype)
        phys = child_dt.physical
        live = batch.live_mask()
        # interleave row-major: row i's elements at [i*k, (i+1)*k)
        vals = jnp.stack([c.data.astype(phys) for c in cols],
                         axis=1).reshape(cap * k)
        valid = jnp.stack([c.validity & live for c in cols],
                          axis=1).reshape(cap * k)
        child_cap = round_pow2(max(cap * k, 8))
        if child_cap > cap * k:
            vals = jnp.concatenate(
                [vals, jnp.zeros(child_cap - cap * k, phys)])
            valid = jnp.concatenate(
                [valid, jnp.zeros(child_cap - cap * k, jnp.bool_)])
        vals = jnp.where(valid, vals, jnp.zeros((), phys))
        child = ColumnVector(vals, valid, child_dt)
        offsets = jnp.arange(cap + 1, dtype=jnp.int32) * k
        # dead rows keep extents but validity=False; kernels mask on it
        return ListColumn(offsets, child, live, child_dt,
                          pad_bucket=round_pow2(max(k, 1)))

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class Size(Expression):
    """size(array) (collectionOperations.scala GpuSize); null -> null."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        _element_type(self.children[0], schema)
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        return make_result(lc.lengths().astype(jnp.int32), lc.validity,
                           dt.INT32)


class GetArrayItem(Expression):
    """arr[i] — zero-based element access (complexTypeExtractors.scala
    GpuGetArrayItem). Out of bounds / negative -> null."""

    def __init__(self, child: Expression, ordinal: Expression):
        super().__init__(child, ordinal)

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch):
        lc: ListColumn = self.children[0].eval(batch)
        idx = self.children[1].eval(batch)
        lens = lc.lengths()
        i = idx.data.astype(jnp.int32)
        in_bounds = (i >= 0) & (i < lens)
        ok = lc.validity & idx.validity & in_bounds
        src = jnp.clip(lc.offsets[:-1] + jnp.clip(i, 0), 0,
                       lc.child_capacity - 1)
        return lc.child.gather(src, ok)


class ElementAt(Expression):
    """element_at(arr, i) — 1-based; negative counts from the end
    (GpuElementAt)."""

    def __init__(self, child: Expression, ordinal: Expression):
        super().__init__(child, ordinal)

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.ArrayType):
            return t.element_type
        raise TypeError(f"element_at on {t}")

    def eval(self, batch: ColumnarBatch):
        lc: ListColumn = self.children[0].eval(batch)
        idx = self.children[1].eval(batch)
        lens = lc.lengths()
        i = idx.data.astype(jnp.int32)
        zero_based = jnp.where(i > 0, i - 1, lens + i)
        in_bounds = (zero_based >= 0) & (zero_based < lens) & (i != 0)
        ok = lc.validity & idx.validity & in_bounds
        src = jnp.clip(lc.offsets[:-1] + jnp.clip(zero_based, 0), 0,
                       lc.child_capacity - 1)
        return lc.child.gather(src, ok)


class ArrayContains(Expression):
    """array_contains(arr, v) with 3-valued membership
    (collectionOperations.scala GpuArrayContains)."""

    def __init__(self, child: Expression, value: Expression):
        super().__init__(child, value)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        needle = self.children[1].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        hit = elem_ok & (vals == needle.data[:, None])
        found = jnp.any(hit, axis=1)
        has_null_elem = jnp.any(lane_ok & ~elem_ok, axis=1)
        ok = lc.validity & needle.validity & (found | ~has_null_elem)
        return make_result(found, ok, dt.BOOL)


class _ArrayExtreme(Expression):
    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        et = lc.dtype.element_type
        fill = self._fill(vals.dtype, et)
        masked = jnp.where(elem_ok, vals, fill)
        out = self._reduce(masked, axis=1)
        any_elem = jnp.any(elem_ok, axis=1)
        return make_result(out, lc.validity & any_elem, et)


class ArrayMin(_ArrayExtreme):
    """array_min: nulls skipped (GpuArrayMin)."""

    def _fill(self, phys, et):
        return jnp.array(dt.max_value(et), phys)

    def _reduce(self, x, axis):
        return jnp.min(x, axis=axis)


class ArrayMax(_ArrayExtreme):
    """array_max: nulls skipped (GpuArrayMax)."""

    def _fill(self, phys, et):
        return jnp.array(dt.min_value(et), phys)

    def _reduce(self, x, axis):
        return jnp.max(x, axis=axis)


class SortArray(Expression):
    """sort_array(arr, asc) over primitive elements (GpuSortArray).
    Null elements first for asc, last for desc (Spark semantics)."""

    def __init__(self, child: Expression, ascending: bool = True):
        super().__init__(child)
        self.ascending = ascending

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        vals, lane_ok, elem_ok = lc.element_lanes()
        et = lc.dtype.element_type
        # order key: dead lanes always last; null elements first for
        # asc, last for desc (Spark sort_array semantics)
        null_cls = 1 if self.ascending else 2
        val_cls = 2 if self.ascending else 1
        cls = jnp.where(~lane_ok, jnp.int8(3),
                        jnp.where(~elem_ok, jnp.int8(null_cls),
                                  jnp.int8(val_cls)))
        # stable two-pass argsort: values then class
        order = jnp.argsort(vals, axis=1, stable=True,
                            descending=not self.ascending)
        cls_o = jnp.take_along_axis(cls, order, axis=1)
        order2 = jnp.argsort(cls_o, axis=1, stable=True)
        order = jnp.take_along_axis(order, order2, axis=1)
        new_vals = jnp.take_along_axis(vals, order, axis=1)
        new_ok = jnp.take_along_axis(elem_ok, order, axis=1)
        # repack lanes into a flat child with the original offsets
        cap, w = new_vals.shape
        starts = lc.offsets[:-1]
        lens = lc.lengths()
        child_cap = lc.child_capacity
        pos = jnp.arange(child_cap, dtype=jnp.int32)
        row = jnp.searchsorted(lc.offsets[1:], pos,
                               side="right").astype(jnp.int32)
        row_c = jnp.clip(row, 0, cap - 1)
        within = jnp.clip(pos - jnp.take(starts, row_c), 0, w - 1)
        data = new_vals[row_c, within]
        okv = new_ok[row_c, within] & (pos < lc.offsets[cap])
        data = jnp.where(okv, data, jnp.zeros((), data.dtype))
        child = ColumnVector(data, okv, et)
        return ListColumn(lc.offsets, child, lc.validity, et,
                          lc.pad_bucket)

    def __repr__(self):
        return (f"sort_array({self.children[0]!r}, "
                f"{'asc' if self.ascending else 'desc'})")


class CreateNamedStruct(Expression):
    """named_struct(n1, v1, ...) (complexTypeCreator.scala
    GpuCreateNamedStruct)."""

    def __init__(self, names: Sequence[str], values: Sequence[Expression]):
        super().__init__(*values)
        self.names = list(names)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.StructType(tuple(
            (n, v.data_type(schema))
            for n, v in zip(self.names, self.children)))

    def eval(self, batch: ColumnarBatch) -> StructColumn:
        kids = [c.eval(batch) for c in self.children]
        live = batch.live_mask()
        st = dt.StructType(tuple(
            (n, k.dtype) for n, k in zip(self.names, kids)))
        return StructColumn(kids, live, st)

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}"
                          for n, v in zip(self.names, self.children))
        return f"named_struct({inner})"


class GetStructField(Expression):
    """struct.field access (complexTypeExtractors.scala
    GpuGetStructField)."""

    def __init__(self, child: Expression, field: str):
        super().__init__(child)
        self.field = field

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if not isinstance(t, dt.StructType):
            raise TypeError(f"field access on {t}")
        for n, ft in t.fields:
            if n == self.field:
                return ft
        raise KeyError(self.field)

    def eval(self, batch: ColumnarBatch):
        sc: StructColumn = self.children[0].eval(batch)
        child = sc.field(self.field)
        v = child.validity & sc.validity
        if isinstance(child, ColumnVector):
            return make_result(child.data, v, child.dtype)
        return child.with_validity(v)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"


class Explode(Expression):
    """Marker generator expression: one output row per array element
    (GpuExplode, GpuGenerateExec). Never evaluated row-wise — the
    planner rewrites a projection containing Explode into a Generate
    node (plan/logical.py)."""

    def __init__(self, child: Expression, outer: bool = False,
                 with_position: bool = False):
        super().__init__(child)
        self.outer = outer
        self.with_position = with_position

    def data_type(self, schema: Schema) -> dt.DType:
        return _element_type(self.children[0], schema)

    def eval(self, batch: ColumnarBatch):
        raise RuntimeError("Explode must be planned as Generate, not "
                           "evaluated as a row expression")

    def __repr__(self):
        kind = "posexplode" if self.with_position else "explode"
        return f"{kind}{'_outer' if self.outer else ''}" \
            f"({self.children[0]!r})"


def explode(e) -> Explode:
    return Explode(e)


def explode_outer(e) -> Explode:
    return Explode(e, outer=True)


def posexplode(e) -> Explode:
    return Explode(e, with_position=True)


def array(*exprs) -> CreateArray:
    from .core import _lit
    return CreateArray(*[_lit(e) for e in exprs])


def struct(**kw) -> CreateNamedStruct:
    from .core import _lit
    return CreateNamedStruct(list(kw), [_lit(v) for v in kw.values()])
