"""Hash functions — bit-exact Spark Murmur3 (and xxhash64) on TPU.

Reference surface: sql-plugin/.../rapids/HashFunctions.scala + JNI Hash
kernels (murmur3 / xxhash64, SURVEY §2.5). Bit-exactness with Spark's
Murmur3_x86_32 matters because hash partitioning decides shuffle layout:
matching Spark means a CPU Spark job and this engine partition rows
identically. All arithmetic is wrapping uint32/uint64, which XLA gives us
natively on the VPU.

Null columns leave the running hash untouched (Spark semantics); the
default seed is 42 (HashPartitioning / Murmur3Hash expression).
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import Column, ColumnVector, ColumnarBatch, StringColumn
from ..utils import bits
from .core import Expression, Schema, make_result

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)


def _rotl32(x, r: int):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def _hash_int32(v_u32, seed_u32):
    return _fmix(_mix_h1(seed_u32, _mix_k1(v_u32)), 4)


def _hash_int64(v_u64, seed_u32):
    lo = (v_u64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (v_u64 >> 32).astype(jnp.uint32)
    h1 = _mix_h1(seed_u32, _mix_k1(lo))
    h1 = _mix_h1(h1, _mix_k1(hi))
    return _fmix(h1, 8)


def _normalize_float(data):
    """Spark: -0.0 hashes as 0.0, NaN as the canonical NaN bits."""
    data = jnp.where(data == 0.0, jnp.zeros((), data.dtype), data)
    canonical = jnp.asarray(float("nan"), data.dtype)
    return jnp.where(jnp.isnan(data), canonical, data)


def murmur3_column(col: Column, seed) -> jnp.ndarray:
    """uint32 per-row hash of one column; null rows return seed unchanged."""
    if isinstance(col, StringColumn):
        h = _murmur3_string(col, seed)
    else:
        d = col.data
        t = col.dtype
        if isinstance(t, dt.BooleanType):
            v = d.astype(jnp.uint32)  # Spark hashes booleans as int 1/0
            h = _hash_int32(v, seed)
        elif t in (dt.INT8, dt.INT16, dt.INT32) or isinstance(t, dt.DateType):
            v = d.astype(jnp.int64).astype(jnp.uint32)  # sign-extend then wrap
            h = _hash_int32(v, seed)
        elif t == dt.INT64 or isinstance(t, (dt.TimestampType, dt.DecimalType)):
            v = bits.i64_to_u64(d.astype(jnp.int64))
            h = _hash_int64(v, seed)
        elif t == dt.FLOAT32:
            v = bits.f32_bits_u32(_normalize_float(d))
            h = _hash_int32(v, seed)
        elif t == dt.FLOAT64:
            v = bits.f64_bits(_normalize_float(d))
            h = _hash_int64(v, seed)
        else:
            raise TypeError(f"murmur3 unsupported for {t}")
    return jnp.where(col.validity, h, seed)


def _murmur3_string(col: StringColumn, seed) -> jnp.ndarray:
    """Bit-exact Spark string murmur3, O(1) trace size.

    The mixing recurrence is sequential over 4-byte blocks, so it rides
    a single ``lax.scan`` over the word axis (one traced op regardless
    of the pad width W). A per-``b`` Python loop here previously issued
    W/4 distinctly-sliced ops — every eager call minted ~W fresh pjit
    cache entries and dominated wide-string exchange partitioning
    (q22-class NDS plans spent 30s+ hashing 8k rows)."""
    from jax import lax
    padded = col.padded()  # (cap, W) uint8, zero-padded
    cap, w = padded.shape
    lens = col.lengths()
    h1 = jnp.broadcast_to(seed, (cap,)).astype(jnp.uint32)
    nblocks = w // 4
    if nblocks:
        # all little-endian words at once: (cap, nblocks)
        p32 = padded[:, :nblocks * 4].astype(jnp.uint32) \
            .reshape(cap, nblocks, 4)
        words = (p32[:, :, 0] | (p32[:, :, 1] << 8)
                 | (p32[:, :, 2] << 16) | (p32[:, :, 3] << 24))
        use = lens[:, None] >= \
            (4 * jnp.arange(1, nblocks + 1, dtype=jnp.int32))

        def mix_block(h, word_use):
            word, u = word_use
            return jnp.where(u, _mix_h1(h, _mix_k1(word)), h), None

        h1, _ = lax.scan(mix_block, h1, (words.T, use.T))
    # tail: the <=3 trailing bytes, sign-extended, in byte order
    tail_start = (lens // 4) * 4
    for j in range(min(3, w)):
        idx = jnp.clip(tail_start + j, 0, w - 1)
        byte = jnp.take_along_axis(padded, idx[:, None], axis=1)[:, 0]
        byte = byte.astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        in_tail = (tail_start + j) < lens
        h1 = jnp.where(in_tail, _mix_h1(h1, _mix_k1(byte)), h1)
    return _fmix_dynamic(h1, lens)


def _fmix_dynamic(h1, lens):
    h1 = h1 ^ lens.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_row_hash(cols: Sequence[Column], seed: int = 42) -> jnp.ndarray:
    """Chained multi-column row hash (each column seeds the next), int32."""
    if not cols:
        raise ValueError("need at least one column")
    cap = cols[0].capacity
    h = jnp.full((cap,), seed, jnp.uint32)
    for c in cols:
        h = murmur3_column(c, h)
    return h.view(jnp.int32)  # 32-bit bitcast is TPU-native


class Murmur3Hash(Expression):
    """hash(...) expression — returns int32."""

    def __init__(self, *children: Expression, seed: int = 42):
        super().__init__(*children)
        self.seed = seed

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def nullable(self, schema: Schema) -> bool:
        return False

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        cols = [c.eval(batch) for c in self.children]
        h = murmur3_row_hash(cols, self.seed)
        return make_result(h.astype(jnp.int32), batch.live_mask(), dt.INT32)


# ---------------------------------------------------------------------------
# xxhash64 (Spark's XxHash64 expression; JNI Hash.xxhash64 in the reference)
# ---------------------------------------------------------------------------

_P1 = jnp.uint64(0x9E3779B185EBCA87)
_P2 = jnp.uint64(0xC2B2AE3D27D4EB4F)
_P3 = jnp.uint64(0x165667B19E3779F9)
_P4 = jnp.uint64(0x85EBCA77C2B2AE63)
_P5 = jnp.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r: int):
    return (x << r) | (x >> (64 - r))


def _xx_fmix(h):
    h = h ^ (h >> 33)
    h = h * _P2
    h = h ^ (h >> 29)
    h = h * _P3
    return h ^ (h >> 32)


def _xx_hash_long(v_u64, seed_u64):
    h = seed_u64 + _P5 + jnp.uint64(8)
    k = _rotl64(v_u64 * _P2, 31) * _P1
    h = h ^ k
    h = _rotl64(h, 27) * _P1 + _P4
    return _xx_fmix(h)


def _xx_hash_int(v_u32, seed_u64):
    """Spark XxHash64.hashInt: the 4-byte tail path of xxhash64."""
    h = seed_u64 + _P5 + jnp.uint64(4)
    h = h ^ (v_u32.astype(jnp.uint64) * _P1)
    h = _rotl64(h, 23) * _P2 + _P3
    return _xx_fmix(h)


def xxhash64_column(col: Column, seed) -> jnp.ndarray:
    if isinstance(col, StringColumn):
        raise TypeError("xxhash64 on strings lands with the regex/unicode work")
    d = col.data
    t = col.dtype
    if isinstance(t, dt.BooleanType):
        # Spark hashes booleans through hashInt(0/1)
        h = _xx_hash_int(d.astype(jnp.uint32), seed)
    elif t in (dt.INT8, dt.INT16, dt.INT32) or isinstance(t, dt.DateType):
        h = _xx_hash_int(d.astype(jnp.int64).astype(jnp.uint32), seed)
    elif t == dt.INT64 or isinstance(t, (dt.TimestampType, dt.DecimalType)):
        h = _xx_hash_long(bits.i64_to_u64(d.astype(jnp.int64)), seed)
    elif t == dt.FLOAT32:
        h = _xx_hash_int(bits.f32_bits_u32(_normalize_float(d)), seed)
    elif t == dt.FLOAT64:
        h = _xx_hash_long(bits.f64_bits(_normalize_float(d)), seed)
    else:
        raise TypeError(f"xxhash64 unsupported for {t}")
    return jnp.where(col.validity, h, seed)


class XxHash64(Expression):
    def __init__(self, *children: Expression, seed: int = 42):
        super().__init__(*children)
        self.seed = seed

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT64

    def nullable(self, schema: Schema) -> bool:
        return False

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        cols = [c.eval(batch) for c in self.children]
        h = jnp.full((batch.capacity,), self.seed, jnp.uint64)
        for c in cols:
            h = xxhash64_column(c, h)
        return make_result(bits.u64_to_i64(h), batch.live_mask(), dt.INT64)


class BloomFilterMightContain(Expression):
    """might_contain(bloom_filter, expr) over a host-built filter
    (GpuBloomFilterMightContain.scala). ``bits`` is the bool[num_bits]
    lane filter from ops/bloom.py build_bloom; null inputs yield null
    (Spark's contract), non-null inputs yield the probe result."""

    def __init__(self, child: Expression, bits):
        super().__init__(child)
        import numpy as _np
        self.bits = _np.asarray(bits, dtype=bool)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        from ..ops import bloom as B
        c = self.children[0].eval(batch)
        hit = B.might_contain(jnp.asarray(self.bits), [c])
        return make_result(hit, c.validity & batch.live_mask(), dt.BOOL)

    def __repr__(self):
        return f"might_contain(<{self.bits.shape[0]} bits>, " \
               f"{self.children[0]!r})"
