"""ANSI-mode plan rewrite + runtime guard helpers.

``srt.sql.ansi.enabled`` flows planner -> expression tree here:
``enable_ansi`` deep-clones an expression tree setting ``ansi=True`` on
every node that owns an ANSI lane (Cast, binary/unary arithmetic,
sum aggregates). An ansi-marked tree is EAGER (expr/misc.contains_eager
— operators evaluate it outside jit), so data-dependent Python raises
are legal: the guards below host-sync a traced error mask and raise the
Spark error types. This trades jit fusion for exact error semantics,
the same trade the reference makes by inserting device-side check
kernels per ANSI op (GpuOverrides.scala:1113-1122: AnsiAdd/Subtract...
wrap each arithmetic op with an overflow-check kernel launch).
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from . import errors as ERR


def _owns_ansi_lane(expr) -> bool:
    from .aggregates import Average, Sum
    from .arithmetic import Abs, BinaryArithmetic, UnaryMinus
    from .cast import Cast
    return isinstance(expr, (Cast, BinaryArithmetic, UnaryMinus, Abs,
                             Sum, Average))


def enable_ansi(expr):
    """Deep-cloned tree with ``ansi=True`` on every supported node."""
    clone = copy.copy(expr)
    clone.children = [enable_ansi(c) for c in expr.children]
    if _owns_ansi_lane(clone):
        clone.ansi = True
    return clone


def rewrite_plan(plan):
    """Clone a LOGICAL plan with every embedded expression ansi-marked.

    Generic over node fields: any Expression (or list/tuple of, or
    SortField-like holding .expr) found in ``vars(node)`` is rewritten;
    children recurse. Unknown containers are left alone — a field the
    walk misses simply keeps non-ANSI (null/wrap) semantics rather than
    corrupting the plan.
    """
    from .core import Expression

    def rw_val(v):
        if isinstance(v, Expression):
            return enable_ansi(v)
        if isinstance(v, list):
            return [rw_val(x) for x in v]
        if isinstance(v, tuple):
            return tuple(rw_val(x) for x in v)
        if hasattr(v, "expr") and isinstance(getattr(v, "expr", None),
                                             Expression):
            c = copy.copy(v)
            c.expr = enable_ansi(v.expr)
            return c
        return v

    node = copy.copy(plan)
    for k, v in vars(plan).items():
        if k == "children":
            continue
        setattr(node, k, rw_val(v))
    node.children = [rewrite_plan(c) for c in getattr(plan, "children", ())]
    return node


def guard(mask, exc: Exception) -> None:
    """Raise ``exc`` if any lane of ``mask`` is set.

    Must run OUTSIDE jit (ansi trees are eager); tracing through here
    is a wiring bug, failed loudly rather than silently dropping the
    check.
    """
    if isinstance(mask, jax.core.Tracer):
        raise AssertionError(
            "ANSI guard reached under trace — ansi expression was "
            "jitted; the operator must take the eager path")
    if bool(jnp.any(mask)):
        raise exc
