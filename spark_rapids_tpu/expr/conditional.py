"""Conditional expressions: IF / CASE WHEN / COALESCE / NULLIF / NVL.

Reference surface: sql-plugin/.../rapids/conditionalExpressions.scala and
nullExpressions.scala. On TPU these lower to jnp.where chains that XLA
fuses into the surrounding expression DAG — there is no lazy/short-circuit
evaluation on a vector machine, matching the reference's columnar
"evaluate all branches then select" semantics.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import Column, ColumnVector, ColumnarBatch, StringColumn
from .core import Expression, Schema, make_result


def _common_type(types: List[dt.DType]) -> dt.DType:
    out = types[0]
    for t in types[1:]:
        if t == dt.NULL:
            continue
        if out == dt.NULL:
            out = t
        elif out != t:
            out = dt.promote(out, t)
    return out


def _as_string(c: Column) -> StringColumn:
    """Coerce an all-null ColumnVector (e.g. Literal(None)) to a string
    column so string selects have two string operands."""
    if isinstance(c, StringColumn):
        return c
    cap = c.capacity
    return StringColumn(jnp.zeros(cap + 1, jnp.int32), jnp.zeros(128, jnp.uint8),
                        jnp.zeros(cap, jnp.bool_), pad_bucket=8)


def _select(cond, a: Column, b: Column, out_t: dt.DType) -> Column:
    """Row-wise select between two columns of the same logical type."""
    if isinstance(out_t, dt.StringType) or isinstance(a, StringColumn) \
            or isinstance(b, StringColumn):
        return _select_strings(cond, _as_string(a), _as_string(b))
    phys = out_t.physical
    data = jnp.where(cond, a.data.astype(phys), b.data.astype(phys))
    validity = jnp.where(cond, a.validity, b.validity)
    return make_result(data, validity, out_t)


def _select_strings(cond, a: StringColumn, b: StringColumn) -> StringColumn:
    """Select rebuilds offsets+chars by per-row extents (same pattern as
    StringColumn.gather)."""
    lens = jnp.where(cond, a.lengths(), b.lengths())
    validity = jnp.where(cond, a.validity, b.validity)
    lens = jnp.where(validity, lens, 0)
    cap = a.capacity
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    nbytes_cap = max(a.char_capacity, b.char_capacity)
    pos = jnp.arange(nbytes_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets[1:], pos, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, cap - 1)
    within = pos - jnp.take(new_offsets, row_c)
    a_src = jnp.take(a.offsets[:-1], row_c) + within
    b_src = jnp.take(b.offsets[:-1], row_c) + within
    a_byte = jnp.take(a.chars, jnp.clip(a_src, 0, a.char_capacity - 1))
    b_byte = jnp.take(b.chars, jnp.clip(b_src, 0, b.char_capacity - 1))
    byte = jnp.where(jnp.take(cond, row_c), a_byte, b_byte)
    total = new_offsets[cap]
    chars = jnp.where(pos < total, byte, jnp.zeros((), jnp.uint8))
    return StringColumn(new_offsets, chars, validity,
                        pad_bucket=max(a.pad_bucket, b.pad_bucket))


class If(Expression):
    """if(cond, a, b); null cond selects the else branch (Spark semantics)."""

    def data_type(self, schema: Schema) -> dt.DType:
        return _common_type([self.children[1].data_type(schema),
                             self.children[2].data_type(schema)])

    def eval(self, batch: ColumnarBatch) -> Column:
        cond = self.children[0].eval(batch)
        a = self.children[1].eval(batch)
        b = self.children[2].eval(batch)
        take_a = cond.data & cond.validity
        return _select(take_a, a, b, self.data_type(batch.schema()))


class CaseWhen(Expression):
    """CASE WHEN ... THEN ... [ELSE ...] END."""

    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 otherwise: Optional[Expression] = None):
        from .core import Literal
        self.branches = branches
        self.otherwise = otherwise if otherwise is not None else Literal(None)
        children = []
        for c, v in branches:
            children.extend([c, v])
        children.append(self.otherwise)
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        ts = [v.data_type(schema) for _, v in self.branches]
        ts.append(self.otherwise.data_type(schema))
        return _common_type(ts)

    def eval(self, batch: ColumnarBatch) -> Column:
        out_t = self.data_type(batch.schema())
        result = self.otherwise.eval(batch)
        # Build from the last branch backwards so the first matching WHEN wins.
        for cond_e, val_e in reversed(self.branches):
            cond = cond_e.eval(batch)
            val = val_e.eval(batch)
            result = _select(cond.data & cond.validity, val, result, out_t)
        return result


class Coalesce(Expression):
    """First non-null argument."""

    def data_type(self, schema: Schema) -> dt.DType:
        return _common_type([c.data_type(schema) for c in self.children])

    def eval(self, batch: ColumnarBatch) -> Column:
        out_t = self.data_type(batch.schema())
        result = self.children[-1].eval(batch)
        for e in reversed(self.children[:-1]):
            c = e.eval(batch)
            result = _select(c.validity, c, result, out_t)
        return result


class NullIf(Expression):
    """nullif(a, b): null when a == b else a."""

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> Column:
        from .predicates import EqualTo
        a = self.children[0].eval(batch)
        eq = EqualTo(self.children[0], self.children[1]).eval(batch)
        kill = eq.data & eq.validity
        return a.with_validity(a.validity & ~kill)


class Nvl(Coalesce):
    """nvl(a, b) == coalesce(a, b)."""


class Nvl2(Expression):
    """nvl2(a, b, c): b when a is not null else c."""

    def data_type(self, schema: Schema) -> dt.DType:
        return _common_type([self.children[1].data_type(schema),
                             self.children[2].data_type(schema)])

    def eval(self, batch: ColumnarBatch) -> Column:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        c = self.children[2].eval(batch)
        return _select(a.validity, b, c, self.data_type(batch.schema()))


def when(cond: Expression, value) -> "WhenBuilder":
    from .core import _lit
    return WhenBuilder([(cond, _lit(value))])


class WhenBuilder:
    """Fluent builder: when(c, v).when(c2, v2).otherwise(v3)."""

    def __init__(self, branches):
        self.branches = branches

    def when(self, cond: Expression, value) -> "WhenBuilder":
        from .core import _lit
        return WhenBuilder(self.branches + [(cond, _lit(value))])

    def otherwise(self, value) -> CaseWhen:
        from .core import _lit
        return CaseWhen(self.branches, _lit(value))

    def end(self) -> CaseWhen:
        return CaseWhen(self.branches)
