"""Aggregate functions.

Reference surface: sql-plugin/.../org/apache/spark/sql/rapids/aggregate/
(GpuSum, GpuCount, GpuMin/Max, GpuAverage, GpuM2-based stddev/variance,
first/last; SURVEY §2.5). The reference splits every aggregate into an
*update* phase (raw rows -> partial state) and a *merge* phase (partial
states -> final state) so partial aggregation can run before a shuffle
(AggHelper, GpuAggregateExec.scala:175). We keep exactly that split:

- ``update(gid, col, num_groups)``: segment-reduce raw rows into
  per-group partial-state columns (jnp scatter-reduce onto a static
  ``num_groups``-capacity state table — the TPU replacement for cuDF's
  hash groupby),
- ``merge(gid, states, num_groups)``: combine partial states,
- ``finalize(states)``: produce the output column.

States are plain dicts of ColumnVector so they flow through jit and the
shuffle serializer untouched.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import Column, ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result

State = Dict[str, ColumnVector]


def _seg_sum(values, gid, num_groups, dtype=None):
    out = jnp.zeros(num_groups, dtype or values.dtype)
    return out.at[gid].add(values)


def _seg_min(values, gid, num_groups, fill):
    out = jnp.full(num_groups, fill, values.dtype)
    return out.at[gid].min(values)


def _seg_max(values, gid, num_groups, fill):
    out = jnp.full(num_groups, fill, values.dtype)
    return out.at[gid].max(values)


def _phys_extreme(dtype, largest: bool):
    """Largest/smallest representable value of a jnp dtype (incl. bool)."""
    if dtype == jnp.bool_:
        return largest
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if largest else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if largest else info.min


class AggregateFunction(Expression):
    """Base; children[0] (if any) is the input expression."""

    name = "agg"
    #: True when update/merge require rows of a group to be CONTIGUOUS
    #: in key-sorted order (collect_list's offset-relabel invariant);
    #: the group kernel then skips the sort-free hash-claim fast path
    #: (ops/kernels.py _prelude_fast) and uses the exact sort.
    needs_sorted_groups = False
    #: ANSI mode flag (expr/ansi.enable_ansi); consumed by Sum/Average
    ansi = False

    def data_type(self, schema: Schema) -> dt.DType:
        raise NotImplementedError

    def over(self, spec):
        """Use as a window aggregate: sum(x).over(spec)."""
        from .window import WindowExpression
        return WindowExpression(self, spec)

    def state_schema(self, schema: Schema) -> List:
        """[(state_name, DType), ...] — the partial-aggregation buffer."""
        raise NotImplementedError

    def update(self, gid, col: Column, num_groups: int, live,
               row_offset=0, perm=None) -> State:
        """gid/col/live are key-sorted; ``perm`` maps sorted row -> original
        row index; ``row_offset`` is the stream-global position of the
        batch's row 0 (order-sensitive aggregates need both)."""
        raise NotImplementedError

    def merge(self, gid, states: State, num_groups: int) -> State:
        raise NotImplementedError

    def finalize(self, states: State) -> ColumnVector:
        raise NotImplementedError


def _sum_decimal_type(t: dt.DecimalType) -> dt.DecimalType:
    """Spark sum result: decimal(p+10, s) capped at MAX_PRECISION."""
    return dt.DecimalType(min(t.precision + 10, dt.DecimalType.MAX_PRECISION),
                          t.scale)


# a 128-bit segmented sum wrapped iff the true sum's magnitude exceeds
# 2^127 ~= 1.70e38; the float64 shadow sum detects that reliably at this
# guard (see Sum docstring)
_WRAP_GUARD = 1.6e38


class _Decimal128SumMixin:
    """Shared 128-bit decimal sum machinery (Sum / Average states).

    State: (hi, lo) segmented two's-complement sum (exact mod 2^128,
    columnar/decimal128.py seg_sum128) + a float64 shadow sum. A group
    whose shadow magnitude exceeds ~2^127 must have wrapped (or is far
    out of any decimal bound) -> overflow null, mirroring GpuSum's
    overflow handling on DECIMAL128 (aggregate/GpuSum-family,
    sql-plugin aggregate package)."""

    @staticmethod
    def _dec_update(gid, col, num_groups):
        from ..columnar import decimal128 as d128
        hi, lo = d128.limbs_of(col)
        sh, sl = d128.seg_sum128(hi, lo, gid, num_groups)
        approx = _seg_sum(d128.d128_to_f64(hi, lo), gid, num_groups,
                          jnp.float64)
        n = _seg_sum(col.validity.astype(jnp.int64), gid, num_groups)
        return {"sum_hi": sh, "sum_lo": sl.astype(jnp.int64),
                "approx": approx, "count": n}

    @staticmethod
    def _dec_merge(gid, states, num_groups):
        from ..columnar import decimal128 as d128
        hi = states["sum_hi"]
        lo = states["sum_lo"].astype(jnp.uint64)
        sh, sl = d128.seg_sum128(hi, lo, gid, num_groups)
        approx = _seg_sum(states["approx"], gid, num_groups)
        n = _seg_sum(states["count"], gid, num_groups)
        return {"sum_hi": sh, "sum_lo": sl.astype(jnp.int64),
                "approx": approx, "count": n}


class Sum(AggregateFunction, _Decimal128SumMixin):
    """Spark sum: long for integrals, double for floats, decimal widened
    to p+10 (two-limb accumulator when that exceeds long-backed range);
    empty/all-null group -> null; decimal overflow -> null (non-ANSI).

    ANSI mode (``ansi=True``, set by expr/ansi.enable_ansi): a long-sum
    wrap or decimal-sum overflow raises SparkArithmeticException.
    Wrap detection carries a float64 shadow sum — a wrapped int64 sum
    differs from its float64 shadow by ~k*2^64, far beyond the shadow's
    rounding error, so ``|approx - sum| > 2^62`` is decisive. The exec
    runs eagerly under ANSI (exec/aggregate.py), so finalize may raise.
    """

    name = "sum"

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            return _sum_decimal_type(t)
        if t.is_integral:
            return dt.INT64
        return dt.FLOAT64

    def _ansi_int(self, out_t) -> bool:
        return self.ansi and not isinstance(out_t, dt.DecimalType) \
            and out_t.is_integral

    def state_schema(self, schema: Schema) -> List:
        out_t = self.data_type(schema)
        if isinstance(out_t, dt.DecimalType) and out_t.is_wide:
            return [("sum_hi", dt.INT64), ("sum_lo", dt.INT64),
                    ("approx", dt.FLOAT64), ("count", dt.INT64)]
        if self._ansi_int(out_t):
            return [("sum", out_t), ("count", dt.INT64),
                    ("approx", dt.FLOAT64)]
        return [("sum", out_t), ("count", dt.INT64)]

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        out_t = self._out_t(col)
        if isinstance(out_t, dt.DecimalType) and out_t.is_wide:
            return self._dec_update(gid, col, num_groups)
        phys = out_t.physical
        vals = jnp.where(col.validity, col.data.astype(phys), jnp.zeros((), phys))
        s = _seg_sum(vals, gid, num_groups)
        n = _seg_sum(col.validity.astype(jnp.int64), gid, num_groups)
        if self._ansi_int(out_t):
            return {"sum": s, "count": n,
                    "approx": _seg_sum(vals.astype(jnp.float64), gid,
                                       num_groups, jnp.float64)}
        return {"sum": s, "count": n}

    def _out_t(self, col: Column) -> dt.DType:
        t = col.dtype
        if isinstance(t, dt.DecimalType):
            return _sum_decimal_type(t)
        if t.is_integral or isinstance(t, dt.BooleanType):
            return dt.INT64
        return dt.FLOAT64

    def merge(self, gid, states: State, num_groups: int) -> State:
        if "sum_hi" in states:
            return self._dec_merge(gid, states, num_groups)
        out = {"sum": _seg_sum(states["sum"], gid, num_groups),
               "count": _seg_sum(states["count"], gid, num_groups)}
        if "approx" in states:
            out["approx"] = _seg_sum(states["approx"], gid, num_groups,
                                     jnp.float64)
        return out

    def finalize(self, states: State) -> tuple:
        if "sum_hi" in states:
            hi = states["sum_hi"]
            lo = states["sum_lo"].astype(jnp.uint64)
            ok = (states["count"] > 0) & \
                (jnp.abs(states["approx"]) < _WRAP_GUARD)
            if self.ansi:
                from . import errors as ERR
                from .ansi import guard
                guard((states["count"] > 0) & ~ok,
                      ERR.SparkArithmeticException("Decimal sum overflow"))
            return (hi, lo), ok
        if self.ansi and "approx" in states:
            from . import errors as ERR
            from .ansi import guard
            diff = jnp.abs(states["approx"] -
                           states["sum"].astype(jnp.float64))
            guard((states["count"] > 0) & (diff > float(2 ** 62)),
                  ERR.SparkArithmeticException(ERR.overflow_message("long")))
        return states["sum"], states["count"] > 0


class Count(AggregateFunction):
    """count(x) — non-null count; count(*) via CountStar."""

    name = "count"

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT64

    def state_schema(self, schema: Schema) -> List:
        return [("count", dt.INT64)]

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        return {"count": _seg_sum((col.validity & live).astype(jnp.int64),
                                  gid, num_groups)}

    def merge(self, gid, states: State, num_groups: int) -> State:
        return {"count": _seg_sum(states["count"], gid, num_groups)}

    def finalize(self, states: State) -> tuple:
        return states["count"], jnp.ones_like(states["count"], jnp.bool_)


class CountStar(AggregateFunction):
    name = "count(*)"

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT64

    def state_schema(self, schema: Schema) -> List:
        return [("count", dt.INT64)]

    def update(self, gid, col, num_groups: int, live, **kw) -> State:
        return {"count": _seg_sum(live.astype(jnp.int64), gid, num_groups)}

    def merge(self, gid, states: State, num_groups: int) -> State:
        return {"count": _seg_sum(states["count"], gid, num_groups)}

    def finalize(self, states: State) -> tuple:
        return states["count"], jnp.ones_like(states["count"], jnp.bool_)


class _MinMaxBase(AggregateFunction):
    """Shared min/max; decimal128 inputs reduce lexicographically over
    (biased hi, lo) limb pairs (columnar/decimal128.py seg_minmax128);
    string inputs reduce via a global sort rank (the value's position
    in a stable sort is an order-isomorphic int64 key, so segmented
    min/max of ranks picks the right ROW and the string is gathered
    from it — no fixed-width encoding of the value in the state)."""

    largest = False

    @property
    def _key(self) -> str:
        return "max" if self.largest else "min"

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def state_schema(self, schema: Schema) -> List:
        t = self.data_type(schema)
        if isinstance(t, dt.DecimalType) and t.is_wide:
            return [(self._key + "_hi", dt.INT64),
                    (self._key + "_lo", dt.INT64), ("seen", dt.BOOL)]
        return [(self._key, t), ("seen", dt.BOOL)]

    def _string_reduce(self, gid, col, num_groups):
        from ..ops.kernels import sort_indices
        cap = col.capacity
        perm = sort_indices([col], [True], [False], col.validity)
        rank = jnp.zeros(cap, jnp.int32).at[perm].set(
            jnp.arange(cap, dtype=jnp.int32))
        if self.largest:
            keyed = jnp.where(col.validity, rank, jnp.int32(-1))
            sel = _seg_max(keyed, gid, num_groups, -1)
            found = sel >= 0
        else:
            big = jnp.int32(cap)
            keyed = jnp.where(col.validity, rank, big)
            sel = _seg_min(keyed, gid, num_groups, big)
            found = sel < big
        rows = jnp.take(perm, jnp.clip(sel, 0, cap - 1))
        out = col.gather(rows, found)
        return {self._key: out, "seen": found}

    def _wide_reduce(self, gid, hi, lo, valid, num_groups):
        from ..columnar import decimal128 as d128
        bh, bl = d128.seg_minmax128(hi, lo, valid, gid, num_groups,
                                    self.largest)
        seen = _seg_sum(valid.astype(jnp.int32), gid, num_groups) > 0
        return {self._key + "_hi": bh, self._key + "_lo":
                bl.astype(jnp.int64), "seen": seen}

    def _float_reduce(self, gid, data, valid, num_groups) -> State:
        """Spark float ordering: NaN is the GREATEST value. Plain
        scatter-min/max propagates NaN into every group it touches
        (XLA min(NaN, x) = NaN), which inverts the contract for min —
        reduce over non-NaN lanes and reinstate NaN only where the
        ordering demands it (any-NaN for max, all-NaN for min)."""
        fdt = data.dtype
        nan_mask = jnp.isnan(data)
        nan_v = jnp.asarray(jnp.nan, fdt)
        if self.largest:
            fill = jnp.asarray(-jnp.inf, fdt)
            vals = jnp.where(valid & ~nan_mask, data, fill)
            m = _seg_max(vals, gid, num_groups, fill)
            any_nan = _seg_sum((valid & nan_mask).astype(jnp.int32),
                               gid, num_groups) > 0
            out = jnp.where(any_nan, nan_v, m)
        else:
            fill = jnp.asarray(jnp.inf, fdt)
            vals = jnp.where(valid & ~nan_mask, data, fill)
            m = _seg_min(vals, gid, num_groups, fill)
            any_num = _seg_sum((valid & ~nan_mask).astype(jnp.int32),
                               gid, num_groups) > 0
            out = jnp.where(any_num, m, nan_v)
        seen = _seg_sum(valid.astype(jnp.int32), gid, num_groups) > 0
        return {self._key: out, "seen": seen}

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        from ..columnar.vector import StringColumn
        if isinstance(col, StringColumn):
            return self._string_reduce(gid, col, num_groups)
        if isinstance(col.dtype, dt.DecimalType) and col.dtype.is_wide:
            from ..columnar import decimal128 as d128
            hi, lo = d128.limbs_of(col)
            return self._wide_reduce(gid, hi, lo, col.validity, num_groups)
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            return self._float_reduce(gid, col.data, col.validity,
                                      num_groups)
        fill = dt.max_value(col.dtype) if not self.largest else \
            dt.min_value(col.dtype)
        vals = jnp.where(col.validity, col.data,
                         jnp.asarray(fill, col.data.dtype))
        red = _seg_max if self.largest else _seg_min
        return {self._key: red(vals, gid, num_groups, fill),
                "seen": _seg_sum(col.validity.astype(jnp.int32), gid,
                                 num_groups) > 0}

    def merge(self, gid, states: State, num_groups: int) -> State:
        from ..columnar.vector import StringColumn
        if isinstance(states.get(self._key), StringColumn):
            sc = states[self._key].with_validity(
                states[self._key].validity & states["seen"])
            return self._string_reduce(gid, sc, num_groups)
        if self._key + "_hi" in states:
            hi = states[self._key + "_hi"]
            lo = states[self._key + "_lo"].astype(jnp.uint64)
            return self._wide_reduce(gid, hi, lo, states["seen"],
                                     num_groups)
        if jnp.issubdtype(states[self._key].dtype, jnp.floating):
            # partial states may BE NaN (all-NaN groups): same ordering
            return self._float_reduce(gid, states[self._key],
                                      states["seen"], num_groups)
        fill = _phys_extreme(states[self._key].dtype,
                             largest=not self.largest)
        vals = jnp.where(states["seen"], states[self._key],
                         jnp.asarray(fill, states[self._key].dtype))
        red = _seg_max if self.largest else _seg_min
        return {self._key: red(vals, gid, num_groups, fill),
                "seen": _seg_sum(states["seen"].astype(jnp.int32), gid,
                                 num_groups) > 0}

    def finalize(self, states: State) -> tuple:
        from ..columnar.vector import StringColumn
        if isinstance(states.get(self._key), StringColumn):
            return states[self._key], states["seen"]
        if self._key + "_hi" in states:
            return (states[self._key + "_hi"],
                    states[self._key + "_lo"].astype(jnp.uint64)), \
                states["seen"]
        return states[self._key], states["seen"]


class Min(_MinMaxBase):
    name = "min"
    largest = False


class Max(_MinMaxBase):
    name = "max"
    largest = True


class Average(AggregateFunction, _Decimal128SumMixin):
    """avg — double result; decimal input yields the Spark decimal
    result type decimal(p+4, s+4) computed exactly: a 128-bit sum state
    divided by the count with HALF_UP at the +4 scale."""

    name = "avg"

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            return dt.adjust_decimal_precision(t.precision + 4, t.scale + 4)
        return dt.FLOAT64

    def state_schema(self, schema: Schema) -> List:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            # scale lift from the sum state (input scale) to the result
            # scale — +4 normally, less when adjustPrecisionScale trims
            # the result scale (never negative: adjusted scale >= s);
            # the sum buffer overflows at decimal(min(p+10,38)) like
            # Spark's Average sum attribute
            self._avg_up = self.data_type(schema).scale - t.scale
            self._sum_prec = _sum_decimal_type(t).precision
            return [("sum_hi", dt.INT64), ("sum_lo", dt.INT64),
                    ("approx", dt.FLOAT64), ("count", dt.INT64)]
        return [("sum", dt.FLOAT64), ("count", dt.INT64)]

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        if isinstance(col.dtype, dt.DecimalType):
            return self._dec_update(gid, col, num_groups)
        x = col.data.astype(jnp.float64)
        vals = jnp.where(col.validity, x, 0.0)
        return {"sum": _seg_sum(vals, gid, num_groups),
                "count": _seg_sum(col.validity.astype(jnp.int64), gid, num_groups)}

    def merge(self, gid, states: State, num_groups: int) -> State:
        if "sum_hi" in states:
            return self._dec_merge(gid, states, num_groups)
        return {"sum": _seg_sum(states["sum"], gid, num_groups),
                "count": _seg_sum(states["count"], gid, num_groups)}

    def finalize(self, states: State) -> tuple:
        n = states["count"]
        ok = n > 0
        if "sum_hi" in states:
            from ..columnar import decimal128 as d128
            hi = states["sum_hi"]
            lo = states["sum_lo"].astype(jnp.uint64)
            safe_n = jnp.where(ok, n, jnp.int64(1))
            nh, nl = d128.d128_from_i64(safe_n)
            # q = sum * 10^(result scale - input scale) / count, HALF_UP
            # (Spark Average.evaluateExpression on decimals); the lift is
            # cached by state_schema, which the exec always calls first
            qh, ql, ovf = d128.d128_div_exact(hi, lo, nh, nl,
                                              self._avg_up)
            had = ok
            ok = ok & ~ovf & (jnp.abs(states["approx"]) < _WRAP_GUARD) & \
                d128.d128_fits_precision(hi, lo, self._sum_prec)
            if self.ansi:
                from . import errors as ERR
                from .ansi import guard
                guard(had & ~ok, ERR.SparkArithmeticException(
                    "Decimal average overflow"))
            return (qh, ql), ok
        return states["sum"] / jnp.where(ok, n, 1).astype(jnp.float64), ok


class _M2Base(AggregateFunction):
    """Shared Welford/M2 machinery for variance & stddev (GpuM2)."""

    ddof = 1

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64

    def state_schema(self, schema: Schema) -> List:
        return [("n", dt.FLOAT64), ("avg", dt.FLOAT64), ("m2", dt.FLOAT64)]

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        x = jnp.where(col.validity, col.data.astype(jnp.float64), 0.0)
        n = _seg_sum(col.validity.astype(jnp.float64), gid, num_groups)
        s = _seg_sum(x, gid, num_groups)
        mean = s / jnp.where(n > 0, n, 1.0)
        dev = jnp.where(col.validity, x - mean[gid], 0.0)
        m2 = _seg_sum(dev * dev, gid, num_groups)
        return {"n": n, "avg": mean, "m2": m2}

    def merge(self, gid, states: State, num_groups: int) -> State:
        # Chan et al. parallel merge of (n, avg, M2)
        n = states["n"]
        navg = states["avg"]
        nm2 = states["m2"]
        n_tot = _seg_sum(n, gid, num_groups)
        s_tot = _seg_sum(n * navg, gid, num_groups)
        avg_tot = s_tot / jnp.where(n_tot > 0, n_tot, 1.0)
        delta = navg - avg_tot[gid]
        m2_tot = _seg_sum(nm2 + n * delta * delta, gid, num_groups)
        return {"n": n_tot, "avg": avg_tot, "m2": m2_tot}

    def _var(self, states: State):
        n = states["n"]
        denom = n - self.ddof
        ok = denom > 0
        return states["m2"] / jnp.where(ok, denom, 1.0), ok & (n > 0)


class VariancePop(_M2Base):
    name = "var_pop"
    ddof = 0

    def finalize(self, states: State) -> tuple:
        v, ok = self._var(states)
        return v, ok


class VarianceSamp(_M2Base):
    name = "var_samp"
    ddof = 1

    def finalize(self, states: State) -> tuple:
        v, ok = self._var(states)
        return v, ok


class StddevPop(_M2Base):
    name = "stddev_pop"
    ddof = 0

    def finalize(self, states: State) -> tuple:
        v, ok = self._var(states)
        return jnp.sqrt(v), ok


class StddevSamp(_M2Base):
    name = "stddev_samp"
    ddof = 1

    def finalize(self, states: State) -> tuple:
        v, ok = self._var(states)
        return jnp.sqrt(v), ok


class First(AggregateFunction):
    """first(x [, ignoreNulls]) — row order dependent, like the reference."""

    name = "first"

    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def state_schema(self, schema: Schema) -> List:
        return [("val", self.data_type(schema)), ("valid", dt.BOOL),
                ("pos", dt.INT64)]

    def update(self, gid, col: Column, num_groups: int, live,
               row_offset=0, perm=None, **kw) -> State:
        cap = col.capacity
        # sorted index for the in-batch pick (stable sort preserves
        # original order within a group), global position for the state
        idx = jnp.arange(cap, dtype=jnp.int64)
        eligible = live & (col.validity if self.ignore_nulls else jnp.ones_like(live))
        big = jnp.iinfo(jnp.int64).max
        keyed = jnp.where(eligible, idx, big)
        sel = _seg_min(keyed, gid, num_groups, big)
        found = sel < big
        take = jnp.clip(sel, 0, cap - 1)
        val = col.data[take]
        valid = col.validity[take] & found
        orig = (jnp.take(perm, take).astype(jnp.int64) if perm is not None
                else take)
        gpos = jnp.where(found, orig + row_offset, big)
        return {"val": jnp.where(found, val, jnp.zeros_like(val)),
                "valid": valid, "pos": gpos}

    def merge(self, gid, states: State, num_groups: int) -> State:
        cap = states["pos"].shape[0]
        big = jnp.iinfo(jnp.int64).max
        best = _seg_min(states["pos"], gid, num_groups, big)
        # pick the partial whose pos equals the winner
        is_best = states["pos"] == best[gid]
        idx = jnp.where(is_best, jnp.arange(cap), cap - 1)
        pick = _seg_min(idx.astype(jnp.int64), gid, num_groups, cap - 1)
        pick = jnp.clip(pick, 0, cap - 1)
        return {"val": states["val"][pick], "valid": states["valid"][pick] &
                (best < big), "pos": best}

    def finalize(self, states: State) -> tuple:
        return states["val"], states["valid"]


class Last(First):
    name = "last"

    def update(self, gid, col: Column, num_groups: int, live,
               row_offset=0, perm=None, **kw) -> State:
        cap = col.capacity
        idx = jnp.arange(cap, dtype=jnp.int64)
        eligible = live & (col.validity if self.ignore_nulls else jnp.ones_like(live))
        keyed = jnp.where(eligible, idx, jnp.int64(-1))
        sel = _seg_max(keyed, gid, num_groups, -1)
        found = sel >= 0
        take = jnp.clip(sel, 0, cap - 1)
        val = col.data[take]
        valid = col.validity[take] & found
        orig = (jnp.take(perm, take).astype(jnp.int64) if perm is not None
                else take)
        gpos = jnp.where(found, orig + row_offset, jnp.int64(-1))
        return {"val": jnp.where(found, val, jnp.zeros_like(val)),
                "valid": valid, "pos": gpos}

    def merge(self, gid, states: State, num_groups: int) -> State:
        cap = states["pos"].shape[0]
        best = _seg_max(states["pos"], gid, num_groups, -1)
        is_best = states["pos"] == best[gid]
        idx = jnp.where(is_best, jnp.arange(cap), 0)
        pick = _seg_max(idx.astype(jnp.int64), gid, num_groups, 0)
        pick = jnp.clip(pick, 0, cap - 1)
        return {"val": states["val"][pick], "valid": states["valid"][pick] &
                (best >= 0), "pos": best}


class CollectList(AggregateFunction):
    """collect_list — gathers group values into an array column, on
    device (aggregate/GpuCollectList via cuDF list aggregations in the
    reference). The sort-based group kernel hands update() key-sorted
    rows, so each group's values are CONTIGUOUS: the list state is just
    (cumulative group counts, compacted values) — a ListColumn whose
    child never exceeds the batch capacity. The merge pass relabels
    offsets the same way (group rows stay contiguous after the merge
    sort), so no per-element shuffling ever happens."""

    name = "collect_list"
    needs_sorted_groups = True

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(self.children[0].data_type(schema))

    def state_schema(self, schema: Schema) -> List:
        return [("list", self.data_type(schema))]

    def _elem_type(self, col: Column) -> dt.DType:
        return col.dtype

    def _build_state(self, gid, col, num_groups, eligible):
        """(counts per group, values compacted in current row order) ->
        ListColumn state."""
        from ..columnar.nested import ListColumn
        cap = col.capacity
        counts = _seg_sum(eligible.astype(jnp.int32), gid, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
        order = jnp.argsort(~eligible, stable=True).astype(jnp.int32)
        n = jnp.sum(eligible).astype(jnp.int32)
        from ..columnar.vector import live_mask
        child = col.gather(order, live_mask(cap, n))
        return ListColumn(offsets, child,
                          jnp.ones(num_groups, jnp.bool_),
                          self._elem_type(col))

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        # nulls are dropped (Spark collect_list/collect_set semantics)
        return {"list": self._build_state(gid, col, num_groups,
                                          col.validity & live)}

    def merge(self, gid, states: State, num_groups: int) -> State:
        from ..columnar.nested import ListColumn
        lc: "ListColumn" = states["list"]
        lens = jnp.where(lc.validity, lc.lengths(), 0)
        counts = _seg_sum(lens.astype(jnp.int32), gid, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
        # rows of one group are contiguous in merge-sorted order, and
        # gather() repacked the child row-major: relabeling offsets IS
        # the concatenation
        return {"list": ListColumn(offsets, lc.child,
                                   jnp.ones(num_groups, jnp.bool_),
                                   lc.dtype.element_type)}

    def finalize(self, states: State):
        lc = states["list"]
        return lc, jnp.ones(lc.capacity, jnp.bool_)


class CollectSet(CollectList):
    """collect_set — like collect_list but value-deduplicated; output
    order is value-sorted (Spark leaves set order undefined)."""

    name = "collect_set"

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        from ..columnar.vector import ColumnVector
        from ..ops import kernels as K
        eligible = col.validity & live
        gcol = ColumnVector(gid.astype(jnp.int32), eligible, dt.INT32)
        perm = K.sort_indices([gcol, col], [True, True], [True, True],
                              eligible)
        g_s = jnp.take(gid, perm)
        col_s = col.gather(perm, jnp.take(eligible, perm))
        dup = K._adjacent_equal(col_s) & \
            jnp.concatenate([jnp.zeros(1, jnp.bool_), g_s[1:] == g_s[:-1]])
        elig_s = jnp.take(eligible, perm) & ~dup
        return {"list": self._build_state(g_s, col_s, num_groups, elig_s)}

    def merge(self, gid, states: State, num_groups: int) -> State:
        from ..columnar.nested import ListColumn
        from ..columnar.vector import ColumnVector
        from ..ops import kernels as K
        lc: "ListColumn" = states["list"]
        merged = super().merge(gid, states, num_groups)["list"]
        # element-level dedupe: flatten (egid, value), sort, drop
        # adjacent duplicates, rebuild counts
        child = merged.child
        ccap = child.capacity
        pos = jnp.arange(ccap, dtype=jnp.int32)
        total = merged.offsets[num_groups]
        alive = pos < total
        egid = jnp.searchsorted(merged.offsets[1:], pos,
                                side="right").astype(jnp.int32)
        gcol = ColumnVector(egid, alive, dt.INT32)
        cv = child.with_validity(child.validity & alive) \
            if hasattr(child, "with_validity") else child
        perm = K.sort_indices([gcol, cv], [True, True], [True, True],
                              alive)
        g_s = jnp.take(egid, perm)
        c_s = cv.gather(perm, jnp.take(alive, perm))
        dup = K._adjacent_equal(c_s) & \
            jnp.concatenate([jnp.zeros(1, jnp.bool_), g_s[1:] == g_s[:-1]])
        keep = jnp.take(alive, perm) & ~dup
        counts = _seg_sum(keep.astype(jnp.int32), g_s, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
        order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
        from ..columnar.vector import live_mask
        n = jnp.sum(keep).astype(jnp.int32)
        new_child = c_s.gather(order, live_mask(ccap, n))
        return {"list": ListColumn(offsets, new_child,
                                   jnp.ones(num_groups, jnp.bool_),
                                   merged.dtype.element_type)}


class Percentile(AggregateFunction):
    """percentile(col, p) — exact, linear interpolation (Spark
    semantics). Not decomposable into fixed-width partial states, so
    CPU-only for now (the reference's GPU approx_percentile uses
    t-digest sketches; that is the planned device path)."""

    name = "percentile"
    needs_sorted_groups = True

    def __init__(self, child: Expression, percentage: float):
        super().__init__(child)
        if not 0.0 <= percentage <= 1.0:
            raise ValueError("percentage must be in [0, 1]")
        self.percentage = percentage

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64


class ApproxPercentile(AggregateFunction):
    """approx_percentile(col, p[, accuracy]) on device via a t-digest
    style centroid sketch (GpuApproximatePercentile + cuDF t-digest in
    the reference; SURVEY §2.5 aggregate exprs).

    State per group: up to K (mean, weight) centroids held as a pair of
    ListColumn states, built with the same compact-contiguous layout as
    collect_list. The update pass buckets each group's value-sorted rows
    into K equi-quantile ranges (uniform scale function — the reference
    marks approx_percentile incompat vs CPU Spark for the same reason:
    sketch results are approximate); the merge pass concatenates
    centroid lists and re-compresses by weighted quantile position;
    finalize picks the first centroid whose cumulative weight reaches
    p * N. Rank error is bounded by ~W/K per merge level.
    """

    name = "approx_percentile"
    needs_sorted_groups = True

    def __init__(self, child: Expression, percentage, accuracy: int = 10000):
        super().__init__(child)
        self.is_array = isinstance(percentage, (list, tuple))
        pcts = list(percentage) if self.is_array else [percentage]
        for p in pcts:
            if not 0.0 <= p <= 1.0:
                raise ValueError("percentage must be in [0, 1]")
        self.percentages = pcts
        self.accuracy = accuracy
        # centroid budget: enough for ~1/K rank resolution, bounded so
        # states stay cheap (Spark's accuracy=1/err maps the same idea
        # onto Greenwald-Khanna summary size)
        self.K = int(min(512, max(32, accuracy // 64)))

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(dt.FLOAT64) if self.is_array else dt.FLOAT64

    def state_schema(self, schema: Schema) -> List:
        return [("means", dt.ArrayType(dt.FLOAT64)),
                ("weights", dt.ArrayType(dt.FLOAT64))]

    @staticmethod
    def _centroid_lists(g_s, e_s, v_s, w_s, bucket, cap, num_groups):
        """Rows sorted by (group, value), eligible first: collapse
        (group, bucket) runs into centroids and pack them as per-group
        lists. Returns (means ListColumn, weights ListColumn)."""
        from ..columnar.nested import ListColumn
        from ..columnar.vector import live_mask
        idx = jnp.arange(cap, dtype=jnp.int32)
        prev_g = jnp.concatenate([jnp.full(1, -1, g_s.dtype), g_s[:-1]])
        prev_b = jnp.concatenate([jnp.full(1, -1, bucket.dtype),
                                  bucket[:-1]])
        boundary = e_s & ((idx == 0) | (g_s != prev_g) |
                          (bucket != prev_b))
        cid = jnp.maximum(jnp.cumsum(boundary.astype(jnp.int32)) - 1, 0)
        wsum = _seg_sum(jnp.where(e_s, w_s, 0.0), cid, cap)
        mwsum = _seg_sum(jnp.where(e_s, v_s * w_s, 0.0), cid, cap)
        mean = mwsum / jnp.maximum(wsum, 1e-300)
        n_cent = jnp.sum(boundary).astype(jnp.int32)
        child_live = live_mask(cap, n_cent)
        means_child = ColumnVector(jnp.where(child_live, mean, 0.0),
                                   child_live, dt.FLOAT64)
        w_child = ColumnVector(jnp.where(child_live, wsum, 0.0),
                               child_live, dt.FLOAT64)
        cpg = _seg_sum(boundary.astype(jnp.int32), g_s, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(cpg, dtype=jnp.int32)])
        ones = jnp.ones(num_groups, jnp.bool_)
        return (ListColumn(offsets, means_child, ones, dt.FLOAT64),
                ListColumn(offsets, w_child, ones, dt.FLOAT64))

    def update(self, gid, col: Column, num_groups: int, live,
               **kw) -> State:
        from ..columnar.vector import ColumnVector as CV
        from ..ops import kernels as K_
        cap = col.capacity
        elig = col.validity & live
        v64 = col.data.astype(jnp.float64)
        vcol = CV(v64, elig, dt.FLOAT64)
        gcol = CV(gid.astype(jnp.int32), elig, dt.INT32)
        perm = K_.sort_indices([gcol, vcol], [True, True], [True, True],
                               elig)
        g_s = jnp.take(gid, perm)
        e_s = jnp.take(elig, perm)
        v_s = jnp.take(v64, perm)
        idx = jnp.arange(cap, dtype=jnp.int32)
        counts = _seg_sum(e_s.astype(jnp.int32), g_s, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts,
                                                 dtype=jnp.int32)])
        rank = idx - jnp.take(offsets, g_s)
        n_g = jnp.maximum(jnp.take(counts, g_s), 1)
        bucket = (rank.astype(jnp.int64) * self.K) // n_g.astype(jnp.int64)
        means, weights = self._centroid_lists(
            g_s, e_s, v_s, jnp.ones(cap, jnp.float64),
            bucket.astype(jnp.int32), cap, num_groups)
        return {"means": means, "weights": weights}

    def merge(self, gid, states: State, num_groups: int) -> State:
        from ..columnar.vector import ColumnVector as CV
        from ..ops import kernels as K_
        means, weights = states["means"], states["weights"]
        cap = means.capacity
        # 1. concat per group by offset relabel (collect_list merge
        #    invariant: child stays row-major compact after gather)
        lens = jnp.where(means.validity, means.lengths(), 0)
        counts = _seg_sum(lens.astype(jnp.int32), gid, num_groups)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts,
                                                 dtype=jnp.int32)])
        m_child, w_child = means.child, weights.child
        ccap = m_child.capacity
        pos = jnp.arange(ccap, dtype=jnp.int32)
        total = offsets[num_groups]
        alive = pos < total
        egid = jnp.clip(jnp.searchsorted(offsets[1:], pos,
                                         side="right"), 0,
                        num_groups - 1).astype(jnp.int32)
        # 2. sort centroids by (group, mean)
        gcol = CV(egid, alive, dt.INT32)
        mcol = CV(m_child.data, alive, dt.FLOAT64)
        permc = K_.sort_indices([gcol, mcol], [True, True], [True, True],
                                alive)
        g_c = jnp.take(egid, permc)
        a_c = jnp.take(alive, permc)
        m_c = jnp.take(m_child.data, permc)
        w_c = jnp.where(a_c, jnp.take(w_child.data, permc), 0.0)
        # 3. weighted equi-quantile re-bucketing
        W_g = _seg_sum(w_c, g_c, num_groups)
        cnt_g = _seg_sum(a_c.astype(jnp.int32), g_c, num_groups)
        offs2 = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(cnt_g,
                                                 dtype=jnp.int32)])
        cum = jnp.cumsum(w_c)
        cwx = cum - w_c  # exclusive prefix
        start = jnp.take(offs2, g_c)
        base = jnp.take(jnp.concatenate([jnp.zeros(1, jnp.float64),
                                         cum]), start)
        mid = (cwx - base) + w_c * 0.5
        Wrow = jnp.maximum(jnp.take(W_g, g_c), 1e-300)
        bucket = jnp.clip((mid / Wrow * self.K).astype(jnp.int32),
                          0, self.K - 1)
        means2, weights2 = self._centroid_lists(
            g_c, a_c, m_c, w_c, bucket, ccap, num_groups)
        return {"means": means2, "weights": weights2}

    def finalize(self, states: State):
        means, weights = states["means"], states["weights"]
        cap = means.capacity
        m_child, w_child = means.child, weights.child
        ccap = m_child.capacity
        pos = jnp.arange(ccap, dtype=jnp.int32)
        offsets = means.offsets
        total = offsets[cap]
        alive = pos < total
        egid = jnp.clip(jnp.searchsorted(offsets[1:], pos, side="right"),
                        0, cap - 1).astype(jnp.int32)
        w = jnp.where(alive, w_child.data, 0.0)
        W_g = _seg_sum(w, egid, cap)
        cum = jnp.cumsum(w)
        base = jnp.take(jnp.concatenate(
            [jnp.zeros(1, jnp.float64), jnp.cumsum(W_g)[:-1]]), egid)
        cw_in = cum - base  # inclusive cumulative weight within group
        outs = []
        for p in self.percentages:
            t = jnp.take(W_g, egid) * p
            cand = alive & (cw_in >= t - 1e-9)
            selpos = _seg_min(jnp.where(cand, pos, ccap), egid, cap,
                              ccap)
            val = jnp.take(m_child.data,
                           jnp.clip(selpos, 0, max(ccap - 1, 0)))
            outs.append(jnp.where(selpos < ccap, val, 0.0))
        ok = W_g > 0
        if not self.is_array:
            return outs[0], ok
        from ..columnar.nested import ListColumn
        P = len(self.percentages)
        # null groups carry ZERO-length extents (ListColumn invariant),
        # so compact the per-group value rows to the ok-group prefix
        stacked = jnp.stack(outs, axis=1)  # (cap, P)
        order = jnp.argsort(~ok, stable=True)
        gathered = jnp.take(stacked, order, axis=0).reshape(cap * P)
        n_ok = jnp.sum(ok).astype(jnp.int32)
        child_live = jnp.arange(cap * P, dtype=jnp.int32) < n_ok * P
        child = ColumnVector(jnp.where(child_live, gathered, 0.0),
                             child_live, dt.FLOAT64)
        out_offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(jnp.where(ok, P, 0).astype(jnp.int32))])
        return ListColumn(out_offsets, child, ok, dt.FLOAT64), ok
