"""Regex on TPU: transpiler + vectorized NFA simulation.

Rebuild of the reference's regex stack (RegexParser.scala, 1996 LoC,
``transpile:713`` + RegexComplexityEstimator.scala, SURVEY §2.5). The
reference translates Java regex syntax into cuDF's regex dialect,
rejecting what cuDF can't run (those expressions fall back to CPU). Here
the target isn't another regex engine but a **Thompson NFA executed as
vector ops**: parse the (Java-flavored) pattern, build an NFA, close
over epsilon moves, and simulate all rows simultaneously over the
padded byte view:

    active:(cap, S) bool ->
    step j: next[:, t] = OR_s active[:, s] & class_hits[class(s,t), :]
    closure: next = next @ closure_matrix   (bool matmul -> MXU)

S (state count) is pattern-sized and static, so the whole match unrolls
into one fused XLA kernel; cost is O(W * |transitions|) vector ops.

Supported: literals, escapes (\\d \\D \\w \\W \\s \\S \\t \\n \\r \\.),
char classes incl. ranges and negation, ``.``, ``*`` ``+`` ``?``
``{m}`` ``{m,}`` ``{m,n}``, alternation, (non-)capturing groups for
grouping, anchors ``^`` ``$``, lazy quantifiers (same language for
containment testing). Rejected -> TypeError -> planner falls back to
CPU (python ``re``), mirroring the reference's transpile-or-fallback
contract: backreferences, lookaround, \\p classes, named groups, inline
flags, word boundaries.

Byte-level semantics: matching operates on UTF-8 bytes; multi-byte
literals work, but char classes/dot over non-ASCII are rejected.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnarBatch, StringColumn
from .core import Expression, Schema, make_result


class RegexUnsupported(TypeError):
    """Pattern uses a construct the TPU engine can't run (falls back)."""


# ---------------------------------------------------------------------------
# parser -> AST
# ---------------------------------------------------------------------------

class _Node:
    pass


class _Lit(_Node):  # a byte-set (one consumed byte)
    def __init__(self, byteset: np.ndarray):
        self.byteset = byteset  # (256,) bool


class _Cat(_Node):
    def __init__(self, parts):
        self.parts = parts


class _Alt(_Node):
    def __init__(self, options):
        self.options = options


class _Rep(_Node):
    def __init__(self, child, lo: int, hi: Optional[int]):
        self.child = child
        self.lo = lo
        self.hi = hi  # None = unbounded


_MAX_REP = 32  # {m,n} expansion bound (complexity estimator role)


def _class_of(chars: str) -> np.ndarray:
    b = np.zeros(256, bool)
    for c in chars:
        b[ord(c)] = True
    return b


_D = np.zeros(256, bool)
_D[ord("0"):ord("9") + 1] = True
_W = _class_of("_")
_W[ord("a"):ord("z") + 1] = True
_W[ord("A"):ord("Z") + 1] = True
_W[ord("0"):ord("9") + 1] = True
_S = _class_of(" \t\n\r\f\v")
_DOT = np.ones(256, bool)
_DOT[ord("\n")] = False
_ANY = np.ones(256, bool)

_ESCAPE_CLASSES = {"d": _D, "D": ~_D, "w": _W, "W": ~_W, "s": _S,
                   "S": ~_S}
_ESCAPE_LITERALS = {"t": "\t", "n": "\n", "r": "\r", "f": "\f",
                    "a": "\a", "e": "\x1b", "0": "\0"}


def _rng(lo: str, hi: str) -> np.ndarray:
    out = np.zeros(256, bool)
    out[ord(lo):ord(hi) + 1] = True
    return out


_POSIX_CLASSES = {
    "Lower": _rng("a", "z"),
    "Upper": _rng("A", "Z"),
    "Alpha": _rng("a", "z") | _rng("A", "Z"),
    "Digit": _rng("0", "9"),
    "Alnum": _rng("a", "z") | _rng("A", "Z") | _rng("0", "9"),
    "XDigit": _rng("0", "9") | _rng("a", "f") | _rng("A", "F"),
    "Space": _class_of(" \t\n\x0b\f\r"),
    "Punct": _class_of("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~"),
    "Print": _rng(" ", "~"),
    "Graph": _rng("!", "~"),
    "Blank": _class_of(" \t"),
    "Cntrl": _rng("\x00", "\x1f") | _class_of("\x7f"),
    "ASCII": _rng("\x00", "\x7f"),
    # the Unicode names java also accepts, ASCII interpretation
    "L": _rng("a", "z") | _rng("A", "Z"),
    "N": _rng("0", "9"),
    "Nd": _rng("0", "9"),
}


class _Group(_Node):
    """Capturing group (index is 1-based like Java)."""

    def __init__(self, child: _Node, idx: int):
        self.child = child
        self.idx = idx


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False
        self.n_groups = 0
        self.has_alternation = False
        self.has_lazy = False

    def fail(self, why: str):
        raise RegexUnsupported(
            f"regex {self.p!r} at {self.i}: {why}")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> _Node:
        if self.peek() == "^":
            self.next()
            self.anchored_start = True
        node = self.alternation(top=True)
        if self.i < len(self.p):
            self.fail("unbalanced ')'")
        # Anchors are simulation-global here, but a top-level alternation
        # scopes them per branch in Java ('a|b$' anchors only 'b') —
        # reject the combination so those patterns fall back to CPU
        # instead of silently matching wrong rows.
        if (self.anchored_start or self.anchored_end) and \
                isinstance(node, _Alt):
            self.fail("anchors with top-level alternation")
        return node

    def alternation(self, top: bool = False) -> _Node:
        options = [self.sequence(top)]
        while self.peek() == "|":
            self.next()
            options.append(self.sequence(top))
        if len(options) > 1:
            self.has_alternation = True
            return _Alt(options)
        return options[0]

    def sequence(self, top: bool = False) -> _Node:
        parts: List[_Node] = []
        while True:
            ch = self.peek()
            if ch is None or ch == "|" or ch == ")":
                break
            if ch == "$":
                # only valid as the final char of the whole pattern
                if top and self.i == len(self.p) - 1:
                    self.next()
                    self.anchored_end = True
                    break
                self.fail("'$' only supported at pattern end")
            parts.append(self.quantified())
        return _Cat(parts)

    def quantified(self) -> _Node:
        atom = self.atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.next()
                atom = _Rep(atom, 0, None)
            elif ch == "+":
                self.next()
                atom = _Rep(atom, 1, None)
            elif ch == "?":
                self.next()
                atom = _Rep(atom, 0, 1)
            elif ch == "{":
                atom = self.bounded_rep(atom)
            else:
                break
            if self.peek() == "?":  # lazy: same language for matching
                self.next()
                self.has_lazy = True
        return atom

    def bounded_rep(self, atom: _Node) -> _Node:
        j = self.p.find("}", self.i)
        if j < 0:
            self.fail("unterminated {")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        try:
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s.strip() else None
            else:
                lo = hi = int(body)
        except ValueError:
            self.fail(f"bad repetition {{{body}}}")
        if lo > _MAX_REP or (hi is not None and hi > _MAX_REP):
            self.fail(f"repetition bound > {_MAX_REP} (state blow-up)")
        return _Rep(atom, lo, hi)

    def atom(self) -> _Node:
        ch = self.next()
        if ch == "(":
            capturing = True
            if self.peek() == "?":
                self.next()
                nxt = self.peek()
                if nxt == ":":
                    self.next()
                    capturing = False
                elif nxt == "<" or nxt == "P":
                    # named group (Java (?<name>...) / python (?P<name>)):
                    # captures by POSITION like Spark's regexp_extract —
                    # the name is only syntax
                    if nxt == "P":
                        self.next()
                    if self.peek() != "<":
                        self.fail("lookaround not supported")
                    self.next()
                    if self.peek() in ("=", "!"):
                        self.fail("lookbehind not supported")
                    while self.peek() not in (">", None):
                        self.next()
                    if self.peek() != ">":
                        self.fail("unterminated group name")
                    self.next()
                else:
                    self.fail("lookaround not supported")
            if capturing:
                self.n_groups += 1
                idx = self.n_groups
            node = self.alternation()
            if self.peek() != ")":
                self.fail("unbalanced '('")
            self.next()
            return _Group(node, idx) if capturing else node
        if ch == "[":
            return _Lit(self.char_class())
        if ch == ".":
            return _Lit(_DOT)
        if ch == "\\":
            return _Lit(self.escape())
        if ch in "*+?{":
            self.fail(f"dangling quantifier {ch!r}")
        if ch == "^":
            self.fail("'^' only supported at pattern start")
        raw = ch.encode("utf-8")
        if len(raw) == 1:
            return _Lit(_class_of(ch))
        # multi-byte literal char: a concatenation of its bytes
        return _Cat([_Lit(_byte_class(b)) for b in raw])

    def escape(self) -> np.ndarray:
        ch = self.next()
        if ch in _ESCAPE_CLASSES:
            return _ESCAPE_CLASSES[ch].copy()
        if ch in _ESCAPE_LITERALS:
            return _class_of(_ESCAPE_LITERALS[ch])
        if ch in "pP":
            # \p{Name} POSIX/ASCII classes (the reference transpiler's
            # supported subset, RegexParser.scala): byte classes over
            # the ASCII range, \P = complement
            if self.peek() != "{":
                self.fail(f"\\{ch} needs {{Name}}")
            self.next()
            name = ""
            while self.peek() not in ("}", None):
                name += self.next()
            if self.peek() != "}":
                self.fail("unterminated \\p{")
            self.next()
            cls = _POSIX_CLASSES.get(name)
            if cls is None:
                self.fail(f"\\p{{{name}}} not supported")
            return ~cls if ch == "P" else cls.copy()
        if ch in "bBAzZGk123456789":
            self.fail(f"\\{ch} not supported")
        if ch == "x":
            hex2 = self.p[self.i:self.i + 2]
            self.i += 2
            return _byte_class(int(hex2, 16))
        return _class_of(ch)  # escaped metachar

    def char_class(self) -> np.ndarray:
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        out = np.zeros(256, bool)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.fail("unterminated [")
            if ch == "]" and not first:
                self.next()
                break
            first = False
            ch = self.next()
            if ch == "\\":
                cls = self.escape()
                out |= cls
                continue
            if ord(ch) > 127:
                self.fail("non-ASCII in char class")
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] not in "]":
                self.next()
                hi = self.next()
                if hi == "\\":
                    self.fail("range to escape unsupported")
                if ord(hi) > 127:
                    self.fail("non-ASCII in char class")
                out[ord(ch):ord(hi) + 1] = True
            else:
                out[ord(ch)] = True
        return ~out if negate else out


def _byte_class(b: int) -> np.ndarray:
    out = np.zeros(256, bool)
    out[b] = True
    return out


# ---------------------------------------------------------------------------
# AST -> NFA (Thompson) -> closed transition relation
# ---------------------------------------------------------------------------

_MAX_STATES = 128


class CompiledRegex:
    """Epsilon-free NFA + metadata, ready for vector simulation."""

    def __init__(self, pattern: str):
        # \b at the pattern EDGES compiles to boundary conditions on
        # seed/accept positions in the vector simulation (interior \b
        # still rejects -> CPU fallback, matching transpile-or-fallback)
        self.word_start = False
        self.word_end = False
        body = pattern
        if body.startswith(r"\b"):
            self.word_start = True
            body = body[2:]
        if body.endswith("b"):
            k = 0
            j = len(body) - 2
            while j >= 0 and body[j] == "\\":
                k += 1
                j -= 1
            if k % 2 == 1:  # odd backslashes: the final 'b' is \b
                self.word_end = True
                body = body[:-2]
        if (self.word_start or self.word_end) and not body:
            raise RegexUnsupported(f"regex {pattern!r}: bare \\b")
        parser = _Parser(body)
        ast = parser.parse()
        if (self.word_start or self.word_end) and isinstance(ast, _Alt):
            # like anchors: Java scopes an edge \b per branch under a
            # top-level alternation; our flags are simulation-global
            raise RegexUnsupported(
                f"regex {pattern!r}: \\b with top-level alternation")
        self.pattern = pattern
        self.ast = ast
        self.anchored_start = parser.anchored_start
        self.anchored_end = parser.anchored_end
        self.n_groups = parser.n_groups
        self.has_alternation = parser.has_alternation
        self.has_lazy = parser.has_lazy

        # Thompson build over epsilon edges
        self.eps: List[Set[int]] = [set()]
        self.byte_edges: List[Tuple[int, int, np.ndarray]] = []
        start = self._new_state()
        accept = self._build(ast, start)
        self.n_states = len(self.eps)
        if self.n_states > _MAX_STATES:
            raise RegexUnsupported(
                f"regex {pattern!r}: {self.n_states} NFA states > "
                f"{_MAX_STATES}")
        self.start = start
        self.accept = accept

        # epsilon closure (S,S) bool: closure[i,j] = j reachable from i
        S = self.n_states
        closure = np.eye(S, dtype=bool)
        for s in range(S):
            stack = [s]
            while stack:
                t = stack.pop()
                for u in self.eps[t]:
                    if not closure[s, u]:
                        closure[s, u] = True
                        stack.append(u)
        self.closure = closure
        # dedupe byte classes
        classes: List[np.ndarray] = []
        trans: List[Tuple[int, int, int]] = []  # (from, class_id, to)
        for (f, t, bs) in self.byte_edges:
            for cid, c in enumerate(classes):
                if np.array_equal(c, bs):
                    break
            else:
                cid = len(classes)
                classes.append(bs)
            trans.append((f, cid, t))
        self.classes = np.stack(classes) if classes else \
            np.zeros((0, 256), bool)
        self.transitions = trans
        self.start_set = closure[start]  # (S,) bool

    def _new_state(self) -> int:
        self.eps.append(set())
        return len(self.eps) - 1

    def _build(self, node: _Node, entry: int) -> int:
        """Wire node's NFA from `entry`; return its exit state."""
        if isinstance(node, _Lit):
            out = self._new_state()
            self.byte_edges.append((entry, out, node.byteset))
            return out
        if isinstance(node, _Cat):
            cur = entry
            for p in node.parts:
                cur = self._build(p, cur)
            return cur
        if isinstance(node, _Alt):
            out = self._new_state()
            for opt in node.options:
                fork = self._new_state()
                self.eps[entry].add(fork)
                end = self._build(opt, fork)
                self.eps[end].add(out)
            return out
        if isinstance(node, _Group):
            return self._build(node.child, entry)
        if isinstance(node, _Rep):
            cur = entry
            for _ in range(node.lo):
                cur = self._build(node.child, cur)
            if node.hi is None:
                # loop: child from cur back to cur (after >= lo copies)
                loop_in = self._new_state()
                self.eps[cur].add(loop_in)
                end = self._build(node.child, loop_in)
                self.eps[end].add(loop_in)
                return loop_in
            out = self._new_state()
            self.eps[cur].add(out)
            for _ in range(node.hi - node.lo):
                cur = self._build(node.child, cur)
                self.eps[cur].add(out)
            return out
        raise AssertionError(type(node))


_COMPILE_CACHE: Dict[str, CompiledRegex] = {}


def transpile(pattern: str) -> CompiledRegex:
    """Parse+compile or raise RegexUnsupported (the planner's fallback
    signal — the reference's ``RegexParser.transpile`` contract)."""
    if pattern not in _COMPILE_CACHE:
        _COMPILE_CACHE[pattern] = CompiledRegex(pattern)
    return _COMPILE_CACHE[pattern]


# ---------------------------------------------------------------------------
# vectorized simulation
# ---------------------------------------------------------------------------

def _simulate(rx: CompiledRegex, col: StringColumn):
    """(cap,) bool: does each row's string contain/match the pattern."""
    import jax.numpy as jnp
    padded = col.padded()          # (cap, W) uint8
    cap, W = padded.shape
    lens = col.lengths()
    closure = jnp.asarray(rx.closure)          # (S, S)
    start_set = jnp.asarray(rx.start_set)      # (S,)
    classes = jnp.asarray(rx.classes)          # (C, 256)
    accept = rx.accept

    # wordness lanes for \b edge conditions: a seed at position p is a
    # boundary iff wordness(s[p-1]) != wordness(s[p]) (virtual non-word
    # outside the string); a match END at c is a boundary iff
    # wordness(s[c-1]) != wordness(s[c])
    if rx.word_start or rx.word_end:
        b = padded
        isw = (((b >= ord("a")) & (b <= ord("z"))) |
               ((b >= ord("A")) & (b <= ord("Z"))) |
               ((b >= ord("0")) & (b <= ord("9"))) |
               (b == ord("_")))
        isw = isw & (jnp.arange(W)[None, :] < lens[:, None])

    active = jnp.broadcast_to(start_set, (cap, rx.n_states))
    if rx.word_start:
        # seeding at position 0: boundary iff the first byte is word
        active = active & (isw[:, 0][:, None] if W else
                           jnp.zeros((cap, 1), jnp.bool_))
    # empty-prefix accept (0 bytes consumed)
    matched = active[:, accept] & (
        (lens == 0) if rx.anchored_end else jnp.ones(cap, jnp.bool_))
    for j in range(W):
        byte = padded[:, j].astype(jnp.int32)          # (cap,)
        hit = classes[:, byte] if rx.classes.shape[0] else \
            jnp.zeros((0, cap), jnp.bool_)             # (C, cap)
        nxt = jnp.zeros((cap, rx.n_states), jnp.bool_)
        for (f, cid, t) in rx.transitions:
            nxt = nxt.at[:, t].set(
                nxt[:, t] | (active[:, f] & hit[cid]))
        in_str = j < lens
        # epsilon closure as a bool matmul (float lanes ride the MXU)
        nxt = ((nxt.astype(jnp.float32) @ closure.astype(jnp.float32))
               > 0) & in_str[:, None]
        if not rx.anchored_start:
            # unanchored search: re-seed the start states at every
            # position (match may begin anywhere)
            seed_ok = in_str
            if rx.word_start:
                nxt_w = isw[:, j + 1] if j + 1 < W else \
                    jnp.zeros(cap, jnp.bool_)
                seed_ok = seed_ok & (nxt_w != isw[:, j])
            nxt = nxt | (start_set[None, :] & seed_ok[:, None])
        active = nxt
        consumed = j + 1
        at_end = consumed == lens
        ok = at_end if rx.anchored_end else (consumed <= lens)
        if rx.word_end:
            nxt_w = isw[:, j + 1] if j + 1 < W else \
                jnp.zeros(cap, jnp.bool_)
            ok = ok & (isw[:, j] != nxt_w)
        matched = matched | (active[:, accept] & ok)
    return matched


# ---------------------------------------------------------------------------
# match spans + capture extraction + replace (submatch machinery)
#
# The reference transpiles extract/replace onto cuDF's capture-aware
# regex engine (RegexParser.scala:713 + cudf extract_re / replace_re).
# The TPU design avoids per-thread backtracking entirely:
#
#   starts[p]  : one reversed-NFA pass over the reversed padded view
#                marks every position where SOME match begins,
#   p*         : leftmost such position (Java's leftmost rule),
#   q*         : one anchored forward pass seeded at p* takes the
#                LAST position where accept is active (longest match),
#   groups     : for top-level-group patterns, each group boundary is
#                max(forward-reachable prefix ends ∩ backward-feasible
#                suffix starts) — the greedy split point.
#
# Leftmost-longest equals Java's leftmost-greedy for the patterns the
# tagging admits (alternation-free, lazy-free); anything else falls
# back to CPU `re`, mirroring transpile-or-fallback.
# ---------------------------------------------------------------------------

def _reverse_ast(node: _Node) -> _Node:
    if isinstance(node, _Lit):
        return node
    if isinstance(node, _Cat):
        return _Cat([_reverse_ast(p) for p in reversed(node.parts)])
    if isinstance(node, _Alt):
        return _Alt([_reverse_ast(o) for o in node.options])
    if isinstance(node, _Rep):
        return _Rep(_reverse_ast(node.child), node.lo, node.hi)
    if isinstance(node, _Group):
        return _Group(_reverse_ast(node.child), node.idx)
    raise AssertionError(type(node))


class _SubAutomaton:
    """Epsilon-closed NFA for an AST fragment (no anchors)."""

    def __init__(self, ast: _Node):
        self.eps: List[Set[int]] = [set()]
        self.byte_edges: List[Tuple[int, int, np.ndarray]] = []
        start = self._new_state()
        accept = self._build(ast, start)
        self.n_states = len(self.eps)
        if self.n_states > _MAX_STATES:
            raise RegexUnsupported(
                f"sub-automaton: {self.n_states} states > {_MAX_STATES}")
        S = self.n_states
        closure = np.eye(S, dtype=bool)
        for s in range(S):
            stack = [s]
            while stack:
                t = stack.pop()
                for u in self.eps[t]:
                    if not closure[s, u]:
                        closure[s, u] = True
                        stack.append(u)
        self.closure = closure
        classes: List[np.ndarray] = []
        trans: List[Tuple[int, int, int]] = []
        for (f, t, bs) in self.byte_edges:
            for cid, c in enumerate(classes):
                if np.array_equal(c, bs):
                    break
            else:
                cid = len(classes)
                classes.append(bs)
            trans.append((f, cid, t))
        self.classes = np.stack(classes) if classes else \
            np.zeros((0, 256), bool)
        self.transitions = trans
        self.start = start
        self.accept = accept
        self.start_set = closure[start]

    _new_state = CompiledRegex._new_state
    _build = CompiledRegex._build


def _step(auto, active, byte):
    """One NFA byte step + epsilon closure. active:(cap,S)."""
    import jax.numpy as jnp
    cap = active.shape[0]
    classes = jnp.asarray(auto.classes)
    hit = classes[:, byte] if auto.classes.shape[0] else \
        jnp.zeros((0, cap), jnp.bool_)
    nxt = jnp.zeros_like(active)
    for (f, cid, t) in auto.transitions:
        nxt = nxt.at[:, t].set(nxt[:, t] | (active[:, f] & hit[cid]))
    closure = jnp.asarray(auto.closure)
    return (nxt.astype(jnp.float32) @ closure.astype(jnp.float32)) > 0


def _find_starts(rx_rev: _SubAutomaton, padded, lens,
                 end_anchored: bool = False):
    """(cap, W+1) bool: a match of the ORIGINAL pattern starts at p.

    Runs the reversed automaton right-to-left: a reversed match ending
    at p (scanning leftward) is an original match starting at p. With
    ``end_anchored`` the reversed run is seeded only at the string end,
    so only matches ending exactly at len count."""
    import jax.numpy as jnp
    cap, W = padded.shape
    starts = jnp.zeros((cap, W + 1), jnp.bool_)
    start_set = jnp.asarray(rx_rev.start_set)
    active = jnp.zeros((cap, rx_rev.n_states), jnp.bool_)
    # scan j = W-1 .. 0; position p consumes bytes p..q-1, so after
    # consuming byte j the reversed run has reached position j
    acc = rx_rev.accept
    empty_ok = bool(rx_rev.start_set[acc])
    for j in range(W - 1, -1, -1):
        in_str = j < lens
        seed = (j + 1 == lens) if end_anchored else in_str
        active = active | (start_set[None, :] & seed[:, None])
        byte = padded[:, j].astype(jnp.int32)
        active = _step(rx_rev, active, byte) & in_str[:, None]
        starts = starts.at[:, j].set(active[:, acc])
    pos = jnp.arange(W + 1, dtype=jnp.int32)
    if empty_ok:
        # the empty match starts at its own end position too
        if end_anchored:
            starts = starts | (pos[None, :] == lens[:, None])
        else:
            starts = starts | (pos[None, :] <= lens[:, None])
    return starts


def _forward_reach(auto: _SubAutomaton, padded, lens, seed_pos):
    """(cap, W+1) bool: positions where `auto` can END, having started
    exactly at per-row position seed_pos. reach[:, j] == accept active
    after consuming bytes seed_pos..j-1."""
    import jax.numpy as jnp
    cap, W = padded.shape
    start_set = jnp.asarray(auto.start_set)
    acc = auto.accept
    reach = jnp.zeros((cap, W + 1), jnp.bool_)
    active = jnp.zeros((cap, auto.n_states), jnp.bool_)
    seeded0 = seed_pos == 0
    active = active | (start_set[None, :] & seeded0[:, None])
    reach = reach.at[:, 0].set(active[:, acc])
    for j in range(W):
        in_str = j < lens
        byte = padded[:, j].astype(jnp.int32)
        active = _step(auto, active, byte) & in_str[:, None]
        seeded = seed_pos == (j + 1)
        active = active | (start_set[None, :] & seeded[:, None])
        reach = reach.at[:, j + 1].set(active[:, acc])
    return reach


def _backward_reach(auto_rev: _SubAutomaton, padded, lens, end_pos):
    """(cap, W+1) bool: positions p from which `auto` (given reversed)
    can match ending exactly at per-row end_pos."""
    import jax.numpy as jnp
    cap, W = padded.shape
    start_set = jnp.asarray(auto_rev.start_set)
    acc = auto_rev.accept
    reach = jnp.zeros((cap, W + 1), jnp.bool_)
    active = jnp.zeros((cap, auto_rev.n_states), jnp.bool_)
    seeded_end = end_pos == W
    active = active | (start_set[None, :] & seeded_end[:, None])
    reach = reach.at[:, W].set(active[:, acc])
    for j in range(W - 1, -1, -1):
        byte = padded[:, j].astype(jnp.int32)
        active = _step(auto_rev, active, byte)
        seeded = end_pos == j
        active = active | (start_set[None, :] & seeded[:, None])
        reach = reach.at[:, j].set(active[:, acc])
    return reach


def _leftmost(mask, limit):
    """Per-row smallest index with mask true (W+1 when none)."""
    import jax.numpy as jnp
    cap, W1 = mask.shape
    pos = jnp.arange(W1, dtype=jnp.int32)
    big = jnp.int32(W1)
    cand = jnp.where(mask & (pos[None, :] <= limit[:, None]), pos[None, :],
                     big)
    return jnp.min(cand, axis=1)


def _rightmost(mask, limit):
    """Per-row largest index <= limit with mask true (-1 when none)."""
    import jax.numpy as jnp
    cap, W1 = mask.shape
    pos = jnp.arange(W1, dtype=jnp.int32)
    cand = jnp.where(mask & (pos[None, :] <= limit[:, None]), pos[None, :],
                     jnp.int32(-1))
    return jnp.max(cand, axis=1)


def _cached_autos(rx: CompiledRegex):
    """(forward, reversed) sub-automatons, built once per pattern."""
    if not hasattr(rx, "_fwd_auto"):
        rx._fwd_auto = _SubAutomaton(rx.ast)
        rx._rev_auto = _SubAutomaton(_reverse_ast(rx.ast))
    return rx._fwd_auto, rx._rev_auto


def first_match_span(rx: CompiledRegex, col: StringColumn):
    """(found, start, end) of the leftmost-longest match per row."""
    import jax.numpy as jnp
    if rx.word_start or rx.word_end:
        # \b is lowered only in the boolean simulation (RLike); span
        # machinery (extract/replace) falls back to CPU
        raise RegexUnsupported(
            f"regex {rx.pattern!r}: \\b spans not lowered")
    padded = col.padded()
    lens = col.lengths()
    fwd, rev = _cached_autos(rx)
    starts = _find_starts(rev, padded, lens,
                          end_anchored=rx.anchored_end)
    if rx.anchored_start:
        starts = starts & (jnp.arange(starts.shape[1],
                                      dtype=jnp.int32)[None, :] == 0)
    p = _leftmost(starts, lens)
    found = p <= lens
    p_safe = jnp.where(found, p, 0)
    ends = _forward_reach(fwd, padded, lens, p_safe)
    if rx.anchored_end:
        ends = ends & (jnp.arange(ends.shape[1],
                                  dtype=jnp.int32)[None, :] ==
                       lens[:, None])
    q = _rightmost(ends, lens)
    found = found & (q >= 0)
    return found, p_safe, jnp.where(found, q, 0)


def _top_level_segments(rx: CompiledRegex):
    """Split the pattern into top-level segments for group boundary
    resolution; every capturing group must be a direct child of the
    top-level concatenation. Returns [(ast, group_idx|None)]."""
    ast = rx.ast
    parts = ast.parts if isinstance(ast, _Cat) else [ast]
    segs = []
    for part in parts:
        if isinstance(part, _Group):
            if _contains_group(part.child):
                raise RegexUnsupported("nested capture groups")
            segs.append((part.child, part.idx))
        else:
            if _contains_group(part):
                raise RegexUnsupported(
                    "capture group under quantifier/alternation")
            segs.append((part, None))
    return segs


def _contains_group(node: _Node) -> bool:
    if isinstance(node, _Group):
        return True
    if isinstance(node, _Cat):
        return any(_contains_group(p) for p in node.parts)
    if isinstance(node, _Alt):
        return any(_contains_group(o) for o in node.options)
    if isinstance(node, _Rep):
        return _contains_group(node.child)
    return False


def extract_group_spans(rx: CompiledRegex, col: StringColumn,
                        group: int):
    """(found, g_start, g_end) for capture group ``group`` of the
    leftmost-longest match (greedy segment splits)."""
    import jax.numpy as jnp
    found, p, q = first_match_span(rx, col)
    if group == 0:
        return found, p, q
    segs = _top_level_segments(rx)
    padded = col.padded()
    lens = col.lengths()
    # boundary[i] = split position after segment i; boundary[-1] = p,
    # boundary[len-1] = q. Greedy: each segment takes the largest split
    # where the remaining suffix still matches ending at q.
    target = None
    for i, (_, gidx) in enumerate(segs):
        if gidx == group:
            target = i
    if target is None:
        raise RegexUnsupported(f"group {group} not found")
    if not hasattr(rx, "_seg_autos"):
        rx._seg_autos = {}
    bound = p
    g_start = p
    for i, (seg_ast, gidx) in enumerate(segs):
        if i not in rx._seg_autos:
            suffix_parts = [a for a, _ in segs[i + 1:]]
            rx._seg_autos[i] = (
                _SubAutomaton(seg_ast),
                _SubAutomaton(_reverse_ast(_Cat(suffix_parts))))
        seg_auto, suffix_rev = rx._seg_autos[i]
        prefix_reach = _forward_reach(seg_auto, padded, lens, bound)
        feasible = _backward_reach(suffix_rev, padded, lens, q)
        nxt = _rightmost(prefix_reach & feasible, lens)
        nxt = jnp.where(found, jnp.maximum(nxt, 0).astype(jnp.int32),
                        jnp.int32(0))
        if gidx == group:
            g_start = bound
            return found, g_start, nxt
        bound = nxt
    raise AssertionError("unreached")


class RLike(Expression):
    """rlike / regexp_like: unanchored regex search (GpuRLike)."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__(child)
        self.pattern = pattern
        self._rx: Optional[CompiledRegex] = None

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def compiled(self) -> CompiledRegex:
        if self._rx is None:
            self._rx = transpile(self.pattern)
        return self._rx

    def eval(self, batch: ColumnarBatch):
        c = self.children[0].eval(batch)
        hit = _simulate(self.compiled(), c)
        return make_result(hit, c.validity, dt.BOOL)

    def __repr__(self):
        return f"{self.children[0]!r} RLIKE {self.pattern!r}"


def check_submatch_supported(pattern: str, group: int = 0) -> CompiledRegex:
    """Plan-time gate for device extract/replace: the span machinery is
    leftmost-LONGEST, which equals Java's leftmost-greedy only without
    alternation or lazy quantifiers; capture groups must sit directly in
    the top-level concatenation. Raises RegexUnsupported -> CPU."""
    rx = transpile(pattern)
    if rx.word_start or rx.word_end:
        # \b lowers only in the boolean simulation (RLike); span
        # machinery must fall back at PLAN time, not raise mid-query
        raise RegexUnsupported(
            f"regex {pattern!r}: \\b in extract/replace falls back")
    if rx.has_alternation:
        raise RegexUnsupported(
            f"regex {pattern!r}: alternation changes leftmost-greedy vs "
            "leftmost-longest; extract/replace falls back")
    if rx.has_lazy:
        raise RegexUnsupported(
            f"regex {pattern!r}: lazy quantifiers in extract/replace "
            "fall back")
    if group > 0:
        _top_level_segments(rx)  # raises for nested/quantified groups
        if group > rx.n_groups:
            raise RegexUnsupported(
                f"regex {pattern!r} has no group {group}")
    return rx


def _substring_from_spans(col: StringColumn, found, start, end):
    """Row substrings s[start:end] as a new StringColumn (empty when not
    found — Spark regexp_extract's no-match result is '')."""
    import jax.numpy as jnp
    padded = col.padded()
    cap, W = padded.shape
    out_len = jnp.where(found, end - start, 0).astype(jnp.int32)
    k = jnp.arange(W, dtype=jnp.int32)
    src = start[:, None] + k[None, :]
    out = jnp.where(k[None, :] < out_len[:, None],
                    jnp.take_along_axis(
                        padded, jnp.clip(src, 0, W - 1), axis=1),
                    jnp.zeros((), jnp.uint8))
    from .strings import pack_padded
    return pack_padded(out, out_len, col.validity, W)


class RegExpExtract(Expression):
    """regexp_extract(str, pattern, group): device capture extraction
    via span finding + greedy segment splits (see module header). The
    tagging pass admits only patterns check_submatch_supported accepts;
    others run on CPU `re` (transpile-or-fallback,
    RegexParser.scala:713 + cuDF extract_re in the reference)."""

    def __init__(self, child: Expression, pattern: str, group: int = 1):
        super().__init__(child)
        self.pattern = pattern
        self.group = group

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        c = self.children[0].eval(batch)
        rx = check_submatch_supported(self.pattern, self.group)
        found, gs, ge = extract_group_spans(rx, c, self.group)
        return _substring_from_spans(c, found, gs, ge)


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement): replaces every
    non-overlapping leftmost match (Java replaceAll, including empty
    matches). One reversed pass finds all match starts; a while_loop
    selects matches left to right (each iteration resolves one match
    per row via an anchored forward pass), then the output assembles
    with the same contribution-scatter StringReplace uses."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__(child)
        self.pattern = pattern
        self.replacement = replacement
        if "$" in replacement or "\\" in replacement:
            # group references in the replacement need per-match group
            # spans; CPU fallback handles them
            self._repl_refs = True
        else:
            self._repl_refs = False

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING

    def eval(self, batch: ColumnarBatch) -> StringColumn:
        import jax
        import jax.numpy as jnp
        c = self.children[0].eval(batch)
        rx = check_submatch_supported(self.pattern, 0)
        fwd, rev = _cached_autos(rx)
        padded = c.padded()
        cap, W = padded.shape
        lens = c.lengths()
        starts = _find_starts(rev, padded, lens,
                              end_anchored=rx.anchored_end)
        if rx.anchored_start:
            starts = starts & (jnp.arange(W + 1,
                                          dtype=jnp.int32)[None, :] == 0)

        def body(state):
            cursor, starts_sel, in_match, done = state
            p = _leftmost(starts & (jnp.arange(W + 1, dtype=jnp.int32)
                                    [None, :] >= cursor[:, None]), lens)
            row_live = (p <= lens) & ~done
            p_safe = jnp.where(row_live, p, 0)
            ends = _forward_reach(fwd, padded, lens, p_safe)
            if rx.anchored_end:
                ends = ends & (jnp.arange(W + 1, dtype=jnp.int32)
                               [None, :] == lens[:, None])
            q = _rightmost(ends, lens)
            row_live = row_live & (q >= p_safe)
            q_safe = jnp.where(row_live, q, 0)
            starts_sel = starts_sel.at[
                jnp.arange(cap), p_safe].set(
                starts_sel[jnp.arange(cap), p_safe] | row_live)
            pos = jnp.arange(W, dtype=jnp.int32)
            covered = (pos[None, :] >= p_safe[:, None]) & \
                (pos[None, :] < q_safe[:, None]) & row_live[:, None]
            in_match = in_match | covered
            new_cursor = jnp.where(
                row_live,
                q_safe + (q_safe == p_safe).astype(jnp.int32),
                cursor)
            done = done | ~row_live
            return new_cursor, starts_sel, in_match, done

        def cond(state):
            return ~jnp.all(state[3])

        init = (jnp.zeros(cap, jnp.int32),
                jnp.zeros((cap, W + 1), jnp.bool_),
                jnp.zeros((cap, W), jnp.bool_),
                jnp.zeros(cap, jnp.bool_))
        _, starts_sel, in_match, _ = jax.lax.while_loop(cond, body, init)

        repl = np.frombuffer(self.replacement.encode("utf-8"), np.uint8)
        nr = len(repl)
        # contribution per position 0..W (position W only carries an
        # end-of-string empty match's replacement)
        pos = jnp.arange(W + 1, dtype=jnp.int32)
        keep = jnp.concatenate(
            [~in_match, jnp.zeros((cap, 1), jnp.bool_)], axis=1) & \
            (pos[None, :] < lens[:, None])
        contrib = starts_sel.astype(jnp.int32) * nr + keep.astype(jnp.int32)
        out_pos = jnp.cumsum(contrib, axis=1) - contrib
        out_len = jnp.sum(contrib, axis=1)
        from ..columnar.vector import round_pow2
        # worst case: an empty match (nr bytes) at every position 0..W
        # plus every original byte kept
        out_w = round_pow2(max(W * (nr + 1) + nr, 8))
        out = jnp.zeros((cap, out_w), jnp.uint8)
        rows = jnp.arange(cap)[:, None]
        for off in range(nr):
            tgt = jnp.clip(out_pos + off, 0, out_w - 1)
            out = out.at[rows, tgt].max(
                jnp.where(starts_sel, jnp.uint8(repl[off]), 0))
        lit_tgt = jnp.clip(out_pos[:, :W] + nr * starts_sel[:, :W], 0,
                           out_w - 1)
        out = out.at[rows, lit_tgt].max(
            jnp.where(keep[:, :W], padded, 0))
        from .strings import pack_padded
        return pack_padded(out, out_len, c.validity, out_w)
