"""Regex on TPU: transpiler + vectorized NFA simulation.

Rebuild of the reference's regex stack (RegexParser.scala, 1996 LoC,
``transpile:713`` + RegexComplexityEstimator.scala, SURVEY §2.5). The
reference translates Java regex syntax into cuDF's regex dialect,
rejecting what cuDF can't run (those expressions fall back to CPU). Here
the target isn't another regex engine but a **Thompson NFA executed as
vector ops**: parse the (Java-flavored) pattern, build an NFA, close
over epsilon moves, and simulate all rows simultaneously over the
padded byte view:

    active:(cap, S) bool ->
    step j: next[:, t] = OR_s active[:, s] & class_hits[class(s,t), :]
    closure: next = next @ closure_matrix   (bool matmul -> MXU)

S (state count) is pattern-sized and static, so the whole match unrolls
into one fused XLA kernel; cost is O(W * |transitions|) vector ops.

Supported: literals, escapes (\\d \\D \\w \\W \\s \\S \\t \\n \\r \\.),
char classes incl. ranges and negation, ``.``, ``*`` ``+`` ``?``
``{m}`` ``{m,}`` ``{m,n}``, alternation, (non-)capturing groups for
grouping, anchors ``^`` ``$``, lazy quantifiers (same language for
containment testing). Rejected -> TypeError -> planner falls back to
CPU (python ``re``), mirroring the reference's transpile-or-fallback
contract: backreferences, lookaround, \\p classes, named groups, inline
flags, word boundaries.

Byte-level semantics: matching operates on UTF-8 bytes; multi-byte
literals work, but char classes/dot over non-ASCII are rejected.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnarBatch, StringColumn
from .core import Expression, Schema, make_result


class RegexUnsupported(TypeError):
    """Pattern uses a construct the TPU engine can't run (falls back)."""


# ---------------------------------------------------------------------------
# parser -> AST
# ---------------------------------------------------------------------------

class _Node:
    pass


class _Lit(_Node):  # a byte-set (one consumed byte)
    def __init__(self, byteset: np.ndarray):
        self.byteset = byteset  # (256,) bool


class _Cat(_Node):
    def __init__(self, parts):
        self.parts = parts


class _Alt(_Node):
    def __init__(self, options):
        self.options = options


class _Rep(_Node):
    def __init__(self, child, lo: int, hi: Optional[int]):
        self.child = child
        self.lo = lo
        self.hi = hi  # None = unbounded


_MAX_REP = 32  # {m,n} expansion bound (complexity estimator role)


def _class_of(chars: str) -> np.ndarray:
    b = np.zeros(256, bool)
    for c in chars:
        b[ord(c)] = True
    return b


_D = np.zeros(256, bool)
_D[ord("0"):ord("9") + 1] = True
_W = _class_of("_")
_W[ord("a"):ord("z") + 1] = True
_W[ord("A"):ord("Z") + 1] = True
_W[ord("0"):ord("9") + 1] = True
_S = _class_of(" \t\n\r\f\v")
_DOT = np.ones(256, bool)
_DOT[ord("\n")] = False
_ANY = np.ones(256, bool)

_ESCAPE_CLASSES = {"d": _D, "D": ~_D, "w": _W, "W": ~_W, "s": _S,
                   "S": ~_S}
_ESCAPE_LITERALS = {"t": "\t", "n": "\n", "r": "\r", "f": "\f",
                    "a": "\a", "e": "\x1b", "0": "\0"}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False

    def fail(self, why: str):
        raise RegexUnsupported(
            f"regex {self.p!r} at {self.i}: {why}")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> _Node:
        if self.peek() == "^":
            self.next()
            self.anchored_start = True
        node = self.alternation(top=True)
        if self.i < len(self.p):
            self.fail("unbalanced ')'")
        # Anchors are simulation-global here, but a top-level alternation
        # scopes them per branch in Java ('a|b$' anchors only 'b') —
        # reject the combination so those patterns fall back to CPU
        # instead of silently matching wrong rows.
        if (self.anchored_start or self.anchored_end) and \
                isinstance(node, _Alt):
            self.fail("anchors with top-level alternation")
        return node

    def alternation(self, top: bool = False) -> _Node:
        options = [self.sequence(top)]
        while self.peek() == "|":
            self.next()
            options.append(self.sequence(top))
        return options[0] if len(options) == 1 else _Alt(options)

    def sequence(self, top: bool = False) -> _Node:
        parts: List[_Node] = []
        while True:
            ch = self.peek()
            if ch is None or ch == "|" or ch == ")":
                break
            if ch == "$":
                # only valid as the final char of the whole pattern
                if top and self.i == len(self.p) - 1:
                    self.next()
                    self.anchored_end = True
                    break
                self.fail("'$' only supported at pattern end")
            parts.append(self.quantified())
        return _Cat(parts)

    def quantified(self) -> _Node:
        atom = self.atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.next()
                atom = _Rep(atom, 0, None)
            elif ch == "+":
                self.next()
                atom = _Rep(atom, 1, None)
            elif ch == "?":
                self.next()
                atom = _Rep(atom, 0, 1)
            elif ch == "{":
                atom = self.bounded_rep(atom)
            else:
                break
            if self.peek() == "?":  # lazy: same language for matching
                self.next()
        return atom

    def bounded_rep(self, atom: _Node) -> _Node:
        j = self.p.find("}", self.i)
        if j < 0:
            self.fail("unterminated {")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        try:
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s.strip() else None
            else:
                lo = hi = int(body)
        except ValueError:
            self.fail(f"bad repetition {{{body}}}")
        if lo > _MAX_REP or (hi is not None and hi > _MAX_REP):
            self.fail(f"repetition bound > {_MAX_REP} (state blow-up)")
        return _Rep(atom, lo, hi)

    def atom(self) -> _Node:
        ch = self.next()
        if ch == "(":
            if self.peek() == "?":
                self.next()
                nxt = self.peek()
                if nxt == ":":
                    self.next()
                else:
                    self.fail("lookaround/named groups not supported")
            node = self.alternation()
            if self.peek() != ")":
                self.fail("unbalanced '('")
            self.next()
            return node
        if ch == "[":
            return _Lit(self.char_class())
        if ch == ".":
            return _Lit(_DOT)
        if ch == "\\":
            return _Lit(self.escape())
        if ch in "*+?{":
            self.fail(f"dangling quantifier {ch!r}")
        if ch == "^":
            self.fail("'^' only supported at pattern start")
        raw = ch.encode("utf-8")
        if len(raw) == 1:
            return _Lit(_class_of(ch))
        # multi-byte literal char: a concatenation of its bytes
        return _Cat([_Lit(_byte_class(b)) for b in raw])

    def escape(self) -> np.ndarray:
        ch = self.next()
        if ch in _ESCAPE_CLASSES:
            return _ESCAPE_CLASSES[ch].copy()
        if ch in _ESCAPE_LITERALS:
            return _class_of(_ESCAPE_LITERALS[ch])
        if ch in "bBAzZGpPk123456789":
            self.fail(f"\\{ch} not supported")
        if ch == "x":
            hex2 = self.p[self.i:self.i + 2]
            self.i += 2
            return _byte_class(int(hex2, 16))
        return _class_of(ch)  # escaped metachar

    def char_class(self) -> np.ndarray:
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        out = np.zeros(256, bool)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.fail("unterminated [")
            if ch == "]" and not first:
                self.next()
                break
            first = False
            ch = self.next()
            if ch == "\\":
                cls = self.escape()
                out |= cls
                continue
            if ord(ch) > 127:
                self.fail("non-ASCII in char class")
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] not in "]":
                self.next()
                hi = self.next()
                if hi == "\\":
                    self.fail("range to escape unsupported")
                if ord(hi) > 127:
                    self.fail("non-ASCII in char class")
                out[ord(ch):ord(hi) + 1] = True
            else:
                out[ord(ch)] = True
        return ~out if negate else out


def _byte_class(b: int) -> np.ndarray:
    out = np.zeros(256, bool)
    out[b] = True
    return out


# ---------------------------------------------------------------------------
# AST -> NFA (Thompson) -> closed transition relation
# ---------------------------------------------------------------------------

_MAX_STATES = 128


class CompiledRegex:
    """Epsilon-free NFA + metadata, ready for vector simulation."""

    def __init__(self, pattern: str):
        parser = _Parser(pattern)
        ast = parser.parse()
        self.pattern = pattern
        self.anchored_start = parser.anchored_start
        self.anchored_end = parser.anchored_end

        # Thompson build over epsilon edges
        self.eps: List[Set[int]] = [set()]
        self.byte_edges: List[Tuple[int, int, np.ndarray]] = []
        start = self._new_state()
        accept = self._build(ast, start)
        self.n_states = len(self.eps)
        if self.n_states > _MAX_STATES:
            raise RegexUnsupported(
                f"regex {pattern!r}: {self.n_states} NFA states > "
                f"{_MAX_STATES}")
        self.start = start
        self.accept = accept

        # epsilon closure (S,S) bool: closure[i,j] = j reachable from i
        S = self.n_states
        closure = np.eye(S, dtype=bool)
        for s in range(S):
            stack = [s]
            while stack:
                t = stack.pop()
                for u in self.eps[t]:
                    if not closure[s, u]:
                        closure[s, u] = True
                        stack.append(u)
        self.closure = closure
        # dedupe byte classes
        classes: List[np.ndarray] = []
        trans: List[Tuple[int, int, int]] = []  # (from, class_id, to)
        for (f, t, bs) in self.byte_edges:
            for cid, c in enumerate(classes):
                if np.array_equal(c, bs):
                    break
            else:
                cid = len(classes)
                classes.append(bs)
            trans.append((f, cid, t))
        self.classes = np.stack(classes) if classes else \
            np.zeros((0, 256), bool)
        self.transitions = trans
        self.start_set = closure[start]  # (S,) bool

    def _new_state(self) -> int:
        self.eps.append(set())
        return len(self.eps) - 1

    def _build(self, node: _Node, entry: int) -> int:
        """Wire node's NFA from `entry`; return its exit state."""
        if isinstance(node, _Lit):
            out = self._new_state()
            self.byte_edges.append((entry, out, node.byteset))
            return out
        if isinstance(node, _Cat):
            cur = entry
            for p in node.parts:
                cur = self._build(p, cur)
            return cur
        if isinstance(node, _Alt):
            out = self._new_state()
            for opt in node.options:
                fork = self._new_state()
                self.eps[entry].add(fork)
                end = self._build(opt, fork)
                self.eps[end].add(out)
            return out
        if isinstance(node, _Rep):
            cur = entry
            for _ in range(node.lo):
                cur = self._build(node.child, cur)
            if node.hi is None:
                # loop: child from cur back to cur (after >= lo copies)
                loop_in = self._new_state()
                self.eps[cur].add(loop_in)
                end = self._build(node.child, loop_in)
                self.eps[end].add(loop_in)
                return loop_in
            out = self._new_state()
            self.eps[cur].add(out)
            for _ in range(node.hi - node.lo):
                cur = self._build(node.child, cur)
                self.eps[cur].add(out)
            return out
        raise AssertionError(type(node))


_COMPILE_CACHE: Dict[str, CompiledRegex] = {}


def transpile(pattern: str) -> CompiledRegex:
    """Parse+compile or raise RegexUnsupported (the planner's fallback
    signal — the reference's ``RegexParser.transpile`` contract)."""
    if pattern not in _COMPILE_CACHE:
        _COMPILE_CACHE[pattern] = CompiledRegex(pattern)
    return _COMPILE_CACHE[pattern]


# ---------------------------------------------------------------------------
# vectorized simulation
# ---------------------------------------------------------------------------

def _simulate(rx: CompiledRegex, col: StringColumn):
    """(cap,) bool: does each row's string contain/match the pattern."""
    import jax.numpy as jnp
    padded = col.padded()          # (cap, W) uint8
    cap, W = padded.shape
    lens = col.lengths()
    closure = jnp.asarray(rx.closure)          # (S, S)
    start_set = jnp.asarray(rx.start_set)      # (S,)
    classes = jnp.asarray(rx.classes)          # (C, 256)
    accept = rx.accept

    active = jnp.broadcast_to(start_set, (cap, rx.n_states))
    # empty-prefix accept (0 bytes consumed)
    matched = active[:, accept] & (
        (lens == 0) if rx.anchored_end else jnp.ones(cap, jnp.bool_))
    for j in range(W):
        byte = padded[:, j].astype(jnp.int32)          # (cap,)
        hit = classes[:, byte] if rx.classes.shape[0] else \
            jnp.zeros((0, cap), jnp.bool_)             # (C, cap)
        nxt = jnp.zeros((cap, rx.n_states), jnp.bool_)
        for (f, cid, t) in rx.transitions:
            nxt = nxt.at[:, t].set(
                nxt[:, t] | (active[:, f] & hit[cid]))
        in_str = j < lens
        # epsilon closure as a bool matmul (float lanes ride the MXU)
        nxt = ((nxt.astype(jnp.float32) @ closure.astype(jnp.float32))
               > 0) & in_str[:, None]
        if not rx.anchored_start:
            # unanchored search: re-seed the start states at every
            # position (match may begin anywhere)
            nxt = nxt | (start_set[None, :] & in_str[:, None])
        active = nxt
        consumed = j + 1
        at_end = consumed == lens
        ok = at_end if rx.anchored_end else (consumed <= lens)
        matched = matched | (active[:, accept] & ok)
    return matched


class RLike(Expression):
    """rlike / regexp_like: unanchored regex search (GpuRLike)."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__(child)
        self.pattern = pattern
        self._rx: Optional[CompiledRegex] = None

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.BOOL

    def compiled(self) -> CompiledRegex:
        if self._rx is None:
            self._rx = transpile(self.pattern)
        return self._rx

    def eval(self, batch: ColumnarBatch):
        c = self.children[0].eval(batch)
        hit = _simulate(self.compiled(), c)
        return make_result(hit, c.validity, dt.BOOL)

    def __repr__(self):
        return f"{self.children[0]!r} RLIKE {self.pattern!r}"


class RegExpExtract(Expression):
    """regexp_extract(str, pattern, group) — capture-group extraction
    needs submatch tracking the NFA simulation doesn't do yet; planner
    always falls back to CPU (python re) for this one."""

    def __init__(self, child: Expression, pattern: str, group: int = 1):
        super().__init__(child)
        self.pattern = pattern
        self.group = group

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement) — CPU fallback, as
    above."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__(child)
        self.pattern = pattern
        self.replacement = replacement

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.STRING
