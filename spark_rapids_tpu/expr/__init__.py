"""Expression library — public surface.

The flat function namespace mirrors pyspark.sql.functions so reference
users find the API familiar; each symbol maps to the expression classes
in the submodules (inventory mirrors SURVEY §2.5).
"""

from . import aggregates, arithmetic, cast, collections, conditional, core, \
    datetime, hashing, higher_order, mathfns, predicates, strings
from .collections import (ArrayContains, ArrayDistinct, ArrayExcept,
                          ArrayIntersect, ArrayMax, ArrayMin,
                          ArrayPosition, ArrayRemove, ArrayRepeat,
                          ArrayReverse, ArraysOverlap, ArrayUnion,
                          CreateArray, CreateNamedStruct, ElementAt,
                          Explode, GetArrayItem, GetStructField, Size,
                          Slice, SortArray, array, explode,
                          explode_outer, posexplode, struct)
from .higher_order import (ArrayAggregate, ArrayExists, ArrayFilter,
                           ArrayForAll, ArrayTransform, CreateMap,
                           GetMapValue, LambdaVariable, MapContainsKey,
                           MapEntries, MapFilter, MapFromArrays, MapKeys,
                           MapValues, TransformKeys, TransformValues,
                           aggregate, create_map, exists, filter_, forall,
                           get_map_value, map_contains_key, map_entries,
                           map_filter, map_from_arrays, map_keys,
                           map_values, transform, transform_keys,
                           transform_values)
from .aggregates import (AggregateFunction, Average, Count, CountStar, First,
                         Last, Max, Min, StddevPop, StddevSamp, Sum,
                         VariancePop, VarianceSamp)
from .arithmetic import (Abs, Add, Divide, Greatest, IntegralDivide, Least,
                         Multiply, Pmod, Remainder, Subtract, UnaryMinus)
from .cast import Cast
from .conditional import CaseWhen, Coalesce, If, NullIf, Nvl, Nvl2, when
from .core import (Alias, ColumnRef, Expression, Literal, col, lit,
                   output_name)
from .datetime import (AddMonths, DateAdd, DateDiff, DateSub, DayOfMonth,
                       DayOfWeek, DayOfYear, FromUnixTime, Hour, LastDay,
                       MakeDate, Minute, Month, Quarter, Second, TruncDate,
                       WeekDay, Year)
from .hashing import Murmur3Hash, XxHash64, murmur3_row_hash
from .mathfns import (Acos, Asin, Atan, Atan2, BRound, Cbrt, Ceil, Cos, Cosh,
                      Exp, Expm1, Floor, Hypot, Log, Log1p, Log2, Log10, Pow,
                      Rint, Round, Signum, Sin, Sinh, Sqrt, Tan, Tanh,
                      ToDegrees, ToRadians)
from .predicates import (And, EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, InSet, IsNaN, IsNotNull, IsNull,
                         LessThan, LessThanOrEqual, Not, Or)
from .misc import (InputFileBlockLength, InputFileBlockStart, InputFileName,
                   MonotonicallyIncreasingID, RaiseError, RaiseErrorException,
                   SparkPartitionID, Uuid, Version, input_file_block_length,
                   input_file_block_start, input_file_name,
                   monotonically_increasing_id, raise_error,
                   spark_partition_id, uuid_expr, version)
from .strings import (Concat, Contains, EndsWith, Length, Like, Lower,
                      OctetLength, StartsWith, StringTrim, StringTrimLeft,
                      StringTrimRight, Substring, Upper)


# pyspark.sql.functions-style helpers
def sum_(e):
    return Sum(_e(e))


def count(e):
    return Count(_e(e))


def count_star():
    return CountStar()


def min_(e):
    return Min(_e(e))


def max_(e):
    return Max(_e(e))


def avg(e):
    return Average(_e(e))


def first(e, ignore_nulls=False):
    return First(_e(e), ignore_nulls)


def last(e, ignore_nulls=False):
    return Last(_e(e), ignore_nulls)


def stddev(e):
    return StddevSamp(_e(e))


def stddev_pop(e):
    return StddevPop(_e(e))


def variance(e):
    return VarianceSamp(_e(e))


def var_pop(e):
    return VariancePop(_e(e))


def _e(e):
    return core.col(e) if isinstance(e, str) else e


def coalesce(*es):
    return Coalesce(*[core._lit(e) for e in es])


def concat(*es):
    return Concat(*[core._lit(e) for e in es])


def substring(e, pos, length=1 << 30):
    return Substring(_e(e), pos, length)


def length(e):
    return Length(_e(e))


def upper(e):
    return Upper(_e(e))


def lower(e):
    return Lower(_e(e))


def like(e, pattern):
    return Like(_e(e), pattern)


def year(e):
    return Year(_e(e))


def month(e):
    return Month(_e(e))


def dayofmonth(e):
    return DayOfMonth(_e(e))


def spark_hash(*es):
    return Murmur3Hash(*[_e(e) for e in es])
