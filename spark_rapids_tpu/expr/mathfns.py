"""Math expressions.

Reference surface: sql-plugin/.../rapids/mathExpressions.scala. Spark math
functions take/return double (except round/bround which preserve the input
type family); domain errors return NaN/Inf like Java's StrictMath, not null.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result, merged_validity


class _UnaryDouble(Expression):
    fn = None

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        x = c.data.astype(jnp.float64)
        if isinstance(c.dtype, dt.DecimalType):
            x = x / (10.0 ** c.dtype.scale)
        return make_result(type(self).fn(x), c.validity, dt.FLOAT64)


class Sqrt(_UnaryDouble):
    fn = staticmethod(jnp.sqrt)


class Cbrt(_UnaryDouble):
    fn = staticmethod(jnp.cbrt)


class Exp(_UnaryDouble):
    fn = staticmethod(jnp.exp)


class Expm1(_UnaryDouble):
    fn = staticmethod(jnp.expm1)


class Log(_UnaryDouble):
    fn = staticmethod(jnp.log)

    def eval(self, batch):
        # Spark: log(x) for x <= 0 -> null
        c = self.children[0].eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > 0
        data = jnp.log(jnp.where(ok, x, 1.0))
        return make_result(data, c.validity & ok, dt.FLOAT64)


class Log1p(_UnaryDouble):
    fn = staticmethod(jnp.log1p)

    def eval(self, batch):
        c = self.children[0].eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > -1
        data = jnp.log1p(jnp.where(ok, x, 0.0))
        return make_result(data, c.validity & ok, dt.FLOAT64)


class Log2(_UnaryDouble):
    def eval(self, batch):
        c = self.children[0].eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > 0
        data = jnp.log2(jnp.where(ok, x, 1.0))
        return make_result(data, c.validity & ok, dt.FLOAT64)


class Log10(_UnaryDouble):
    def eval(self, batch):
        c = self.children[0].eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > 0
        data = jnp.log10(jnp.where(ok, x, 1.0))
        return make_result(data, c.validity & ok, dt.FLOAT64)


class Sin(_UnaryDouble):
    fn = staticmethod(jnp.sin)


class Cos(_UnaryDouble):
    fn = staticmethod(jnp.cos)


class Tan(_UnaryDouble):
    fn = staticmethod(jnp.tan)


class Asin(_UnaryDouble):
    fn = staticmethod(jnp.arcsin)


class Acos(_UnaryDouble):
    fn = staticmethod(jnp.arccos)


class Atan(_UnaryDouble):
    fn = staticmethod(jnp.arctan)


class Sinh(_UnaryDouble):
    fn = staticmethod(jnp.sinh)


class Cosh(_UnaryDouble):
    fn = staticmethod(jnp.cosh)


class Tanh(_UnaryDouble):
    fn = staticmethod(jnp.tanh)


class Asinh(_UnaryDouble):
    fn = staticmethod(jnp.arcsinh)


class Acosh(_UnaryDouble):
    fn = staticmethod(jnp.arccosh)


class Atanh(_UnaryDouble):
    fn = staticmethod(jnp.arctanh)


class ToDegrees(_UnaryDouble):
    fn = staticmethod(jnp.degrees)


class ToRadians(_UnaryDouble):
    fn = staticmethod(jnp.radians)


class Signum(_UnaryDouble):
    fn = staticmethod(lambda x: jnp.sign(x))


class Rint(_UnaryDouble):
    fn = staticmethod(jnp.rint)


class Floor(Expression):
    """floor: bigint for integral/double input (Spark returns long)."""

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            return dt.DecimalType(min(t.precision - t.scale + 1, 18), 0)
        return dt.INT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        if isinstance(c.dtype, dt.DecimalType):
            s = 10 ** c.dtype.scale
            data = c.data // s
            return make_result(data, c.validity, self.data_type(batch.schema()))
        data = jnp.floor(c.data.astype(jnp.float64)).astype(jnp.int64)
        return make_result(data, c.validity, dt.INT64)


class Ceil(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            return dt.DecimalType(min(t.precision - t.scale + 1, 18), 0)
        return dt.INT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        if isinstance(c.dtype, dt.DecimalType):
            s = 10 ** c.dtype.scale
            data = -((-c.data) // s)
            return make_result(data, c.validity, self.data_type(batch.schema()))
        data = jnp.ceil(c.data.astype(jnp.float64)).astype(jnp.int64)
        return make_result(data, c.validity, dt.INT64)


class Pow(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = jnp.power(a.data.astype(jnp.float64), b.data.astype(jnp.float64))
        return make_result(data, merged_validity(a, b), dt.FLOAT64)


class Atan2(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = jnp.arctan2(a.data.astype(jnp.float64), b.data.astype(jnp.float64))
        return make_result(data, merged_validity(a, b), dt.FLOAT64)


class Hypot(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = jnp.hypot(a.data.astype(jnp.float64), b.data.astype(jnp.float64))
        return make_result(data, merged_validity(a, b), dt.FLOAT64)


class Round(Expression):
    """round(x, d): HALF_UP rounding (Spark), input type preserved."""

    def __init__(self, child: Expression, scale: int = 0):
        super().__init__(child)
        self.scale = scale

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.DecimalType):
            return dt.DecimalType(t.precision, min(self.scale, t.scale)) \
                if self.scale >= 0 else dt.DecimalType(t.precision, 0)
        return t

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        out_t = self.data_type(batch.schema())
        if isinstance(c.dtype, dt.DecimalType):
            target = min(self.scale, c.dtype.scale) if self.scale >= 0 else 0
            drop = c.dtype.scale - target
            if drop <= 0:
                return c
            p = 10 ** drop
            half = p // 2
            # HALF_UP away from zero on the unscaled value
            q = (jnp.abs(c.data) + half) // p
            data = jnp.sign(c.data) * q
            return make_result(data, c.validity, out_t)
        if c.dtype.is_floating:
            p = 10.0 ** self.scale
            x = c.data.astype(jnp.float64) * p
            data = (jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)) / p
            return make_result(data.astype(out_t.physical), c.validity, out_t)
        if self.scale >= 0:
            return c
        p = 10 ** (-self.scale)
        half = p // 2
        q = (jnp.abs(c.data.astype(jnp.int64)) + half) // p * p
        data = (jnp.sign(c.data) * q).astype(out_t.physical)
        return make_result(data, c.validity, out_t)


class BRound(Round):
    """bround: HALF_EVEN (banker's) rounding."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        out_t = self.data_type(batch.schema())
        if c.dtype.is_floating:
            p = 10.0 ** self.scale
            data = jnp.round(c.data.astype(jnp.float64) * p) / p  # rint = HALF_EVEN
            return make_result(data.astype(out_t.physical), c.validity, out_t)
        return super().eval(batch)
