"""Higher-order functions (lambda expressions over arrays/maps) and the
map expression surface.

Rebuild of the reference's higherOrderFunctions.scala (GpuLambdaFunction,
GpuNamedLambdaVariable, GpuArrayTransform :221, GpuArrayExists :352,
GpuArrayFilter :412, GpuTransformKeys :450, GpuTransformValues :516,
GpuMapFilter :559) and GpuMapUtils.scala (map_keys/map_values/entries,
GpuGetMapValue, GpuElementAt-on-map).

TPU lowering: a lambda body is an ordinary Expression evaluated over the
ELEMENT LANES of the list — the dense ``(capacity, pad_bucket)`` view
from ``ListColumn.element_lanes`` flattened row-major to one synthetic
batch of ``capacity * pad_bucket`` rows. The lambda variable becomes a
plain column of that batch; outer-scope columns the body references are
gathered (repeated per lane) into it. One ``eval`` of the body then
computes the lambda for every element of every row at once — no per-row
loop, and XLA fuses the whole thing. ``aggregate`` is the exception: it
folds sequentially over lanes with ``lax.scan`` (the accumulator chain
is inherently sequential), with the merge body traced ONCE.

Maps are list<struct<key,value>> (columnar/nested.py:240), so every map
function lowers to list machinery over the key/value children.

Null semantics follow Spark:
- transform of a null array -> null; lambda sees null elements,
- exists: true if any true, else null if any null result, else false
  (3-valued, matching Spark's ArrayExists with followThreeValuedLogic),
- filter drops elements whose predicate is null or false,
- aggregate threads nulls through the merge lambda,
- element_at(map, k) of a missing key -> null.
"""

from __future__ import annotations

import itertools
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.nested import ListColumn, StructColumn
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               round_pow2)
from .core import Expression, Schema, make_result

_VAR_IDS = itertools.count()


class LambdaVariable(Expression):
    """A named lambda parameter (GpuNamedLambdaVariable). Its dtype is
    bound by the enclosing higher-order function when IT is typed
    against the outer schema; eval reads the synthetic lane batch."""

    def __init__(self, name: Optional[str] = None):
        super().__init__()
        self.name = name or f"lambda_x#{next(_VAR_IDS)}"
        self._dtype: Optional[dt.DType] = None

    def data_type(self, schema: Schema) -> dt.DType:
        if self._dtype is None:
            raise TypeError(
                f"unbound lambda variable {self.name} (typed outside "
                f"its higher-order function?)")
        return self._dtype

    def references(self) -> set:
        return set()  # bound, not free — never demanded from the input

    def eval(self, batch: ColumnarBatch) -> Column:
        return batch.column(self.name)

    def __repr__(self):
        return self.name


def _outer_refs(body: Expression, vars_: Sequence[LambdaVariable]) -> set:
    bound = {v.name for v in vars_}
    refs = set()

    def walk(e: Expression):
        if isinstance(e, LambdaVariable):
            return
        from .core import ColumnRef
        if isinstance(e, ColumnRef):
            refs.add(e.name)
        for c in e.children:
            walk(c)
    walk(body)
    return refs - bound


class HigherOrderFunction(Expression):
    """Common machinery: lane-batch construction + lambda binding."""

    #: subclasses list their lambda vars here (in binding order)
    lambda_vars: Sequence[LambdaVariable] = ()

    def references(self) -> set:
        refs = set()
        for c in self.children:
            refs |= c.references()
        return refs - {v.name for v in self.lambda_vars}

    # --- typing helpers ---
    def _array_type(self, schema: Schema) -> dt.ArrayType:
        t = self.children[0].data_type(schema)
        if isinstance(t, dt.MapType):
            return dt.ArrayType(dt.StructType(
                (("key", t.key_type), ("value", t.value_type))))
        if not isinstance(t, dt.ArrayType):
            raise TypeError(f"{type(self).__name__} expects an array, "
                            f"got {t}")
        return t

    # --- lane-batch construction ---
    def _lane_batch(self, batch: ColumnarBatch, lc: ListColumn,
                    bindings: dict) -> ColumnarBatch:
        """The synthetic element-level batch: ``capacity*pad_bucket``
        rows, lambda-var columns from ``bindings``, plus any outer
        columns the body references (gathered so row i's value repeats
        across row i's lanes)."""
        cap, w = lc.capacity, lc.pad_bucket
        n = cap * w
        names, cols = [], []
        for name, column in bindings.items():
            names.append(name)
            cols.append(column)
        outer = set()
        for body in self._bodies():
            outer |= _outer_refs(body, self.lambda_vars)
        if outer:
            rows = jnp.repeat(jnp.arange(cap, dtype=jnp.int32), w)
            live = jnp.repeat(batch.live_mask(), w)
            sub = batch.select([c for c in batch.names if c in outer])
            expanded = sub.gather(rows, batch.num_rows * w)
            # gather marks rows >= new_num_rows dead; lanes interleave
            # so re-validate from the source row liveness instead
            for name, column in zip(expanded.names, expanded.columns):
                src = batch.column(name)
                v = jnp.take(src.validity,
                             jnp.clip(rows, 0, cap - 1)) & live
                names.append(name)
                cols.append(column.with_validity(v)
                            if not isinstance(column, ColumnVector)
                            else make_result(column.data, v, column.dtype))
        return ColumnarBatch(cols, names, n)

    def _bodies(self) -> Sequence[Expression]:
        raise NotImplementedError

    def _element_binding(self, lc: ListColumn, var: LambdaVariable,
                         idx_var: Optional[LambdaVariable] = None) -> dict:
        vals, lane_ok, elem_ok = lc.element_lanes()
        cap, w = lc.capacity, lc.pad_bucket
        bind = {var.name: ColumnVector(
            vals.reshape(cap * w), elem_ok.reshape(cap * w),
            lc.dtype.element_type)}
        if idx_var is not None:
            k = jnp.tile(jnp.arange(w, dtype=jnp.int32), cap)
            bind[idx_var.name] = ColumnVector(
                k, lane_ok.reshape(cap * w), dt.INT32)
        return bind, lane_ok


def _lanes_to_list(lc: ListColumn, new_vals: jnp.ndarray,
                   new_ok: jnp.ndarray, element_type: dt.DType,
                   offsets: Optional[jnp.ndarray] = None,
                   child_cap: Optional[int] = None) -> ListColumn:
    """Repack a (capacity, pad_bucket) lane block into a flat-child
    ListColumn with the given offsets (defaults: the source's — same
    lengths). Lanes must already be left-compacted per row."""
    cap, w = new_vals.shape
    offs = lc.offsets if offsets is None else offsets
    ccap = child_cap or lc.child_capacity
    pos = jnp.arange(ccap, dtype=jnp.int32)
    row = jnp.searchsorted(offs[1:], pos, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, cap - 1)
    within = jnp.clip(pos - jnp.take(offs, row_c), 0, w - 1)
    data = new_vals[row_c, within]
    okv = new_ok[row_c, within] & (pos < offs[cap])
    data = jnp.where(okv, data, jnp.zeros((), data.dtype))
    child = ColumnVector(data, okv, element_type)
    return ListColumn(offs, child, lc.validity, element_type,
                      lc.pad_bucket)


class ArrayTransform(HigherOrderFunction):
    """transform(arr, x -> body) / transform(arr, (x, i) -> body)
    (higherOrderFunctions.scala GpuArrayTransform:221)."""

    def __init__(self, child: Expression, var: LambdaVariable,
                 body: Expression,
                 idx_var: Optional[LambdaVariable] = None):
        super().__init__(child, body)
        self.var = var
        self.idx_var = idx_var
        self.lambda_vars = (var,) + ((idx_var,) if idx_var else ())

    def data_type(self, schema: Schema) -> dt.DType:
        at = self._array_type(schema)
        self.var._dtype = at.element_type
        if self.idx_var:
            self.idx_var._dtype = dt.INT32
        return dt.ArrayType(self.children[1].data_type(schema))

    def _bodies(self):
        return (self.children[1],)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        self.var._dtype = lc.dtype.element_type
        bind, lane_ok = self._element_binding(lc, self.var, self.idx_var)
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        cap, w = lc.capacity, lc.pad_bucket
        vals = out.data.reshape(cap, w)
        ok = (out.validity.reshape(cap, w)) & lane_ok
        return _lanes_to_list(lc, vals, ok, out.dtype)

    def __repr__(self):
        v = f"({self.var!r}, {self.idx_var!r})" if self.idx_var \
            else repr(self.var)
        return f"transform({self.children[0]!r}, {v} -> " \
               f"{self.children[1]!r})"


class ArrayExists(HigherOrderFunction):
    """exists(arr, x -> pred) with Spark's three-valued logic
    (GpuArrayExists:352): TRUE if any element satisfies, else NULL if
    any predicate result was null, else FALSE."""

    def __init__(self, child: Expression, var: LambdaVariable,
                 body: Expression):
        super().__init__(child, body)
        self.var = var
        self.lambda_vars = (var,)

    def data_type(self, schema: Schema) -> dt.DType:
        at = self._array_type(schema)
        self.var._dtype = at.element_type
        return dt.BOOL

    def _bodies(self):
        return (self.children[1],)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        self.var._dtype = lc.dtype.element_type
        bind, lane_ok = self._element_binding(lc, self.var)
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        cap, w = lc.capacity, lc.pad_bucket
        pred = out.data.reshape(cap, w)
        pok = out.validity.reshape(cap, w)
        any_true = jnp.any(lane_ok & pok & pred, axis=1)
        any_null = jnp.any(lane_ok & ~pok, axis=1)
        return make_result(any_true,
                           lc.validity & (any_true | ~any_null),
                           dt.BOOL)


class ArrayForAll(ArrayExists):
    """forall(arr, x -> pred): FALSE if any false, else NULL if any
    null, else TRUE."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc: ListColumn = self.children[0].eval(batch)
        self.var._dtype = lc.dtype.element_type
        bind, lane_ok = self._element_binding(lc, self.var)
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        cap, w = lc.capacity, lc.pad_bucket
        pred = out.data.reshape(cap, w)
        pok = out.validity.reshape(cap, w)
        any_false = jnp.any(lane_ok & pok & ~pred, axis=1)
        any_null = jnp.any(lane_ok & ~pok, axis=1)
        return make_result(~any_false,
                           lc.validity & (any_false | ~any_null),
                           dt.BOOL)


class ArrayFilter(HigherOrderFunction):
    """filter(arr, x -> pred) (GpuArrayFilter:412): keep elements whose
    predicate is true-and-not-null; list lengths shrink."""

    def __init__(self, child: Expression, var: LambdaVariable,
                 body: Expression):
        super().__init__(child, body)
        self.var = var
        self.lambda_vars = (var,)

    def data_type(self, schema: Schema) -> dt.DType:
        at = self._array_type(schema)
        self.var._dtype = at.element_type
        self.children[1].data_type(schema)  # type the body
        return at

    def _bodies(self):
        return (self.children[1],)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc: ListColumn = self.children[0].eval(batch)
        self.var._dtype = lc.dtype.element_type
        bind, lane_ok = self._element_binding(lc, self.var)
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        cap, w = lc.capacity, lc.pad_bucket
        keep = lane_ok & (out.data & out.validity).reshape(cap, w)
        vals, _, elem_ok = lc.element_lanes()
        # left-compact kept lanes: stable argsort on ~keep
        order = jnp.argsort(~keep, axis=1, stable=True)
        vals_c = jnp.take_along_axis(vals, order, axis=1)
        ok_c = jnp.take_along_axis(elem_ok & keep, order, axis=1)
        lens = jnp.sum(keep, axis=1, dtype=jnp.int32)
        lens = jnp.where(lc.validity, lens, 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        return _lanes_to_list(lc, vals_c, ok_c, lc.dtype.element_type,
                              offsets=offsets)


class ArrayAggregate(HigherOrderFunction):
    """aggregate(arr, zero, (acc, x) -> merge[, acc -> finish])
    (higherOrderFunctions.scala GpuArrayAggregate role): sequential
    fold over the lanes with lax.scan — the merge body traces ONCE and
    runs ``pad_bucket`` times, each step advancing every row's
    accumulator in parallel."""

    def __init__(self, child: Expression, zero: Expression,
                 acc_var: LambdaVariable, elem_var: LambdaVariable,
                 merge: Expression,
                 finish: Optional[Expression] = None):
        children = [child, zero, merge] + ([finish] if finish else [])
        super().__init__(*children)
        self.acc_var = acc_var
        self.elem_var = elem_var
        self.has_finish = finish is not None
        self.lambda_vars = (acc_var, elem_var)

    def data_type(self, schema: Schema) -> dt.DType:
        at = self._array_type(schema)
        self.elem_var._dtype = at.element_type
        self.acc_var._dtype = self.children[1].data_type(schema)
        merged = self.children[2].data_type(schema)
        if merged != self.acc_var._dtype:
            # Spark coerces; here the merge body must preserve acc type
            self.acc_var._dtype = merged
        if self.has_finish:
            return self.children[3].data_type(schema)
        return merged

    def _bodies(self):
        return tuple(self.children[2:])

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        import numpy as np
        lc: ListColumn = self.children[0].eval(batch)
        self.elem_var._dtype = lc.dtype.element_type
        zero = self.children[1].eval(batch)
        # accumulator dtype = the MERGE body's result type (bound by
        # data_type during planning), not the zero's: acc+x*0.5 over an
        # int zero must fold in double, and the scan carry's physical
        # dtype is fixed across steps
        acc_t = self.acc_var._dtype or zero.dtype
        if acc_t != zero.dtype:
            zero = make_result(
                zero.data.astype(np.dtype(acc_t.physical)),
                zero.validity, acc_t)
        self.acc_var._dtype = acc_t
        vals, lane_ok, elem_ok = lc.element_lanes()
        cap, w = lc.capacity, lc.pad_bucket
        merge = self.children[2]
        outer = _outer_refs(merge, self.lambda_vars)
        if outer:
            raise RuntimeError(
                "aggregate() merge lambda referencing outer columns is "
                "not lowered on TPU (planner should have fallen back)")
        et = lc.dtype.element_type
        names = [self.acc_var.name, self.elem_var.name]

        def step(carry, xs):
            acc_data, acc_ok = carry
            x_data, x_ok, l_ok = xs
            b = ColumnarBatch(
                [ColumnVector(acc_data, acc_ok, acc_t),
                 ColumnVector(x_data, x_ok, et)], names, cap)
            out = merge.eval(b)
            nd = jnp.where(l_ok, out.data.astype(acc_data.dtype),
                           acc_data)
            nk = jnp.where(l_ok, out.validity, acc_ok)
            return (nd, nk), None

        xs = (vals.T, elem_ok.T, lane_ok.T)  # (w, cap)
        (acc_data, acc_ok), _ = jax.lax.scan(
            step, (zero.data, zero.validity), xs)
        result = make_result(acc_data, acc_ok & lc.validity, acc_t)
        if self.has_finish:
            b = ColumnarBatch([result], [self.acc_var.name], cap)
            out = self.children[3].eval(b)
            return make_result(out.data, out.validity & lc.validity,
                               out.dtype)
        return result


# ---------------------------------------------------------------------------
# map expressions (GpuMapUtils.scala; maps are list<struct<key,value>>)
# ---------------------------------------------------------------------------

def _map_type(expr: Expression, schema: Schema) -> dt.MapType:
    t = expr.data_type(schema)
    if not isinstance(t, dt.MapType):
        raise TypeError(f"expected map input, got {t}")
    return t


def _entries(col) -> ListColumn:
    assert isinstance(col, ListColumn) and \
        isinstance(col.child, StructColumn), f"not a map column: {col}"
    return col


def _key_list(lc: ListColumn, key_type: dt.DType) -> ListColumn:
    return ListColumn(lc.offsets, lc.child.field("key"), lc.validity,
                      key_type, lc.pad_bucket)


def _value_list(lc: ListColumn, value_type: dt.DType) -> ListColumn:
    return ListColumn(lc.offsets, lc.child.field("value"), lc.validity,
                      value_type, lc.pad_bucket)


class MapKeys(Expression):
    """map_keys(m) (GpuMapUtils getKeysAsListView)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(_map_type(self.children[0], schema).key_type)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc = _entries(self.children[0].eval(batch))
        return _key_list(lc, lc.child.dtype.fields[0][1])


class MapValues(Expression):
    """map_values(m) (GpuMapUtils getValuesAsListView)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.ArrayType(
            _map_type(self.children[0], schema).value_type)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc = _entries(self.children[0].eval(batch))
        return _value_list(lc, lc.child.dtype.fields[1][1])


class MapEntries(Expression):
    """map_entries(m) -> array<struct<key,value>> (the physical layout,
    re-typed)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema: Schema) -> dt.DType:
        mt = _map_type(self.children[0], schema)
        return dt.ArrayType(dt.StructType(
            (("key", mt.key_type), ("value", mt.value_type))))

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc = _entries(self.children[0].eval(batch))
        # RE-TYPE to a plain array<struct>: keeping the MapType dtype
        # would make host collection rebuild dicts, diverging from the
        # declared entry-list type (and the CPU oracle)
        return ListColumn(lc.offsets, lc.child, lc.validity,
                          lc.child.dtype, lc.pad_bucket)


class GetMapValue(Expression):
    """m[key] / element_at(m, key): the value of the FIRST matching key,
    null if absent (GpuGetMapValue / GpuElementAt on maps). Primitive
    keys lower as a lane equality + argmax; string keys compare padded
    lanes bytewise."""

    def __init__(self, child: Expression, key: Expression):
        super().__init__(child, key)

    def data_type(self, schema: Schema) -> dt.DType:
        return _map_type(self.children[0], schema).value_type

    def eval(self, batch: ColumnarBatch):
        lc = _entries(self.children[0].eval(batch))
        needle = self.children[1].eval(batch)
        key_child = lc.child.field("key")
        key_t = lc.child.dtype.fields[0][1]
        keys = ListColumn(lc.offsets, key_child, lc.validity, key_t,
                          lc.pad_bucket)
        vals, lane_ok, elem_ok = keys.element_lanes()
        hit = elem_ok & (vals == needle.data[:, None])
        found = jnp.any(hit, axis=1)
        first = jnp.argmax(hit, axis=1).astype(jnp.int32)
        ok = lc.validity & needle.validity & found
        src = jnp.clip(lc.offsets[:-1] + first, 0,
                       lc.child_capacity - 1)
        value_child = lc.child.field("value")
        return value_child.gather(src, ok)


class MapContainsKey(Expression):
    """map_contains_key(m, k)."""

    def __init__(self, child: Expression, key: Expression):
        super().__init__(child, key)

    def data_type(self, schema: Schema) -> dt.DType:
        _map_type(self.children[0], schema)
        return dt.BOOL

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        lc = _entries(self.children[0].eval(batch))
        needle = self.children[1].eval(batch)
        key_child = lc.child.field("key")
        key_t = lc.child.dtype.fields[0][1]
        keys = ListColumn(lc.offsets, key_child, lc.validity, key_t,
                          lc.pad_bucket)
        vals, _, elem_ok = keys.element_lanes()
        found = jnp.any(elem_ok & (vals == needle.data[:, None]), axis=1)
        return make_result(found, lc.validity & needle.validity, dt.BOOL)


class TransformValues(HigherOrderFunction):
    """transform_values(m, (k, v) -> body) (GpuTransformValues:516):
    keys unchanged, values mapped."""

    def __init__(self, child: Expression, key_var: LambdaVariable,
                 val_var: LambdaVariable, body: Expression):
        super().__init__(child, body)
        self.key_var = key_var
        self.val_var = val_var
        self.lambda_vars = (key_var, val_var)

    def data_type(self, schema: Schema) -> dt.DType:
        mt = _map_type(self.children[0], schema)
        self.key_var._dtype = mt.key_type
        self.val_var._dtype = mt.value_type
        return dt.MapType(mt.key_type,
                          self.children[1].data_type(schema))

    def _bodies(self):
        return (self.children[1],)

    def _eval_mapped(self, batch: ColumnarBatch, map_keys: bool):
        lc = _entries(self.children[0].eval(batch))
        key_t = lc.child.dtype.fields[0][1]
        val_t = lc.child.dtype.fields[1][1]
        self.key_var._dtype, self.val_var._dtype = key_t, val_t
        keys = _key_list(lc, key_t)
        values = _value_list(lc, val_t)
        kv, k_lane, k_ok = keys.element_lanes()
        vv, lane_ok, v_ok = values.element_lanes()
        cap, w = lc.capacity, lc.pad_bucket
        n = cap * w
        bind = {self.key_var.name: ColumnVector(
                    kv.reshape(n), k_ok.reshape(n), key_t),
                self.val_var.name: ColumnVector(
                    vv.reshape(n), v_ok.reshape(n), val_t)}
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        new_vals = out.data.reshape(cap, w)
        new_ok = out.validity.reshape(cap, w) & lane_ok
        if map_keys:
            new_keys = _lanes_to_list(lc, new_vals, new_ok, out.dtype)
            st = dt.StructType((("key", out.dtype), ("value", val_t)))
            child = StructColumn([new_keys.child,
                                  lc.child.field("value")],
                                 lc.child.validity, st)
        else:
            new_values = _lanes_to_list(lc, new_vals, new_ok, out.dtype)
            st = dt.StructType((("key", key_t), ("value", out.dtype)))
            child = StructColumn([lc.child.field("key"),
                                  new_values.child],
                                 lc.child.validity, st)
        return ListColumn(lc.offsets, child, lc.validity, st,
                          lc.pad_bucket)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        return self._eval_mapped(batch, map_keys=False)


class TransformKeys(TransformValues):
    """transform_keys(m, (k, v) -> body) (GpuTransformKeys:450). Spark
    raises on null new keys; here a null result key nulls the entry
    (documented deviation — the planner can force CPU via conf)."""

    def data_type(self, schema: Schema) -> dt.DType:
        mt = _map_type(self.children[0], schema)
        self.key_var._dtype = mt.key_type
        self.val_var._dtype = mt.value_type
        return dt.MapType(self.children[1].data_type(schema),
                          mt.value_type)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        return self._eval_mapped(batch, map_keys=True)


class MapFilter(HigherOrderFunction):
    """map_filter(m, (k, v) -> pred) (GpuMapFilter:559)."""

    def __init__(self, child: Expression, key_var: LambdaVariable,
                 val_var: LambdaVariable, body: Expression):
        super().__init__(child, body)
        self.key_var = key_var
        self.val_var = val_var
        self.lambda_vars = (key_var, val_var)

    def data_type(self, schema: Schema) -> dt.DType:
        mt = _map_type(self.children[0], schema)
        self.key_var._dtype = mt.key_type
        self.val_var._dtype = mt.value_type
        self.children[1].data_type(schema)
        return mt

    def _bodies(self):
        return (self.children[1],)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        lc = _entries(self.children[0].eval(batch))
        key_t = lc.child.dtype.fields[0][1]
        val_t = lc.child.dtype.fields[1][1]
        self.key_var._dtype, self.val_var._dtype = key_t, val_t
        keys = _key_list(lc, key_t)
        values = _value_list(lc, val_t)
        kv, _, k_ok = keys.element_lanes()
        vv, lane_ok, v_ok = values.element_lanes()
        cap, w = lc.capacity, lc.pad_bucket
        n = cap * w
        bind = {self.key_var.name: ColumnVector(
                    kv.reshape(n), k_ok.reshape(n), key_t),
                self.val_var.name: ColumnVector(
                    vv.reshape(n), v_ok.reshape(n), val_t)}
        lanes = self._lane_batch(batch, lc, bind)
        out = self.children[1].eval(lanes)
        keep = lane_ok & (out.data & out.validity).reshape(cap, w)
        order = jnp.argsort(~keep, axis=1, stable=True)
        kv_c = jnp.take_along_axis(kv, order, axis=1)
        ko_c = jnp.take_along_axis(k_ok & keep, order, axis=1)
        vv_c = jnp.take_along_axis(vv, order, axis=1)
        vo_c = jnp.take_along_axis(v_ok & keep, order, axis=1)
        lens = jnp.where(lc.validity,
                         jnp.sum(keep, axis=1, dtype=jnp.int32), 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        new_keys = _lanes_to_list(lc, kv_c, ko_c, key_t, offsets=offsets)
        new_vals = _lanes_to_list(lc, vv_c, vo_c, val_t, offsets=offsets)
        st = lc.child.dtype
        entry_ok = new_keys.child.validity | new_vals.child.validity
        child = StructColumn([new_keys.child, new_vals.child],
                             entry_ok, st)
        return ListColumn(offsets, child, lc.validity, st, lc.pad_bucket)


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) (GpuCreateMap)."""

    def __init__(self, *children: Expression):
        assert len(children) % 2 == 0 and children, \
            "map() needs key/value pairs"
        super().__init__(*children)

    def data_type(self, schema: Schema) -> dt.DType:
        from .conditional import _common_type
        kt = _common_type([c.data_type(schema)
                           for c in self.children[0::2]])
        vt = _common_type([c.data_type(schema)
                           for c in self.children[1::2]])
        return dt.MapType(kt, vt)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        from .collections import CreateArray
        keys = CreateArray(*self.children[0::2]).eval(batch)
        vals = CreateArray(*self.children[1::2]).eval(batch)
        st = dt.StructType((("key", keys.dtype.element_type),
                            ("value", vals.dtype.element_type)))
        entry_ok = keys.child.validity | vals.child.validity
        child = StructColumn([keys.child, vals.child], entry_ok, st)
        return ListColumn(keys.offsets, child, keys.validity, st,
                          keys.pad_bucket)


class MapFromArrays(Expression):
    """map_from_arrays(keys, values) (GpuMapFromArrays role)."""

    def __init__(self, keys: Expression, values: Expression):
        super().__init__(keys, values)

    def data_type(self, schema: Schema) -> dt.DType:
        kt = self.children[0].data_type(schema)
        vt = self.children[1].data_type(schema)
        if not (isinstance(kt, dt.ArrayType) and
                isinstance(vt, dt.ArrayType)):
            raise TypeError("map_from_arrays needs two arrays")
        return dt.MapType(kt.element_type, vt.element_type)

    def eval(self, batch: ColumnarBatch) -> ListColumn:
        keys: ListColumn = self.children[0].eval(batch)
        vals: ListColumn = self.children[1].eval(batch)
        st = dt.StructType((("key", keys.dtype.element_type),
                            ("value", vals.dtype.element_type)))
        # zip by position: key i pairs value i; extents must match —
        # mismatched rows null out (Spark raises; documented deviation)
        same = keys.lengths() == vals.lengths()
        validity = keys.validity & vals.validity & same
        # align the value child onto the key child's offsets
        kv, k_lane, k_ok = keys.element_lanes()
        vv, v_lane, v_ok = vals.element_lanes()
        w = max(keys.pad_bucket, vals.pad_bucket)
        cap = keys.capacity

        def widen(a, width):
            if a.shape[1] == width:
                return a
            pad = width - a.shape[1]
            return jnp.pad(a, ((0, 0), (0, pad)))
        kv, k_ok = widen(kv, w), widen(k_ok, w)
        vv, v_ok = widen(vv, w), widen(v_ok, w)
        lens = jnp.where(validity, keys.lengths(), 0)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
        base = ListColumn(offsets, keys.child, validity,
                          keys.dtype.element_type, w)
        nk = _lanes_to_list(base, kv, k_ok, keys.dtype.element_type,
                            offsets=offsets,
                            child_cap=keys.child_capacity)
        nv = _lanes_to_list(base, vv, v_ok, vals.dtype.element_type,
                            offsets=offsets,
                            child_cap=keys.child_capacity)
        entry_ok = nk.child.validity | nv.child.validity
        child = StructColumn([nk.child, nv.child], entry_ok, st)
        return ListColumn(offsets, child, validity, st, w)


# ---------------------------------------------------------------------------
# python-lambda API (the DataFrame-side sugar)
# ---------------------------------------------------------------------------

def _one_arg(fn: Callable) -> tuple:
    v = LambdaVariable()
    return v, fn(v)


def transform(arr, fn: Callable) -> ArrayTransform:
    """transform(col, x -> expr) or (x, i) -> expr by arity."""
    import inspect
    from .core import _lit
    arity = len(inspect.signature(fn).parameters)
    if arity == 2:
        x, i = LambdaVariable(), LambdaVariable()
        return ArrayTransform(_lit(arr), x, _lit(fn(x, i)), idx_var=i)
    x, body = _one_arg(fn)
    return ArrayTransform(_lit(arr), x, _lit(body))


def exists(arr, fn: Callable) -> ArrayExists:
    from .core import _lit
    x, body = _one_arg(fn)
    return ArrayExists(_lit(arr), x, _lit(body))


def forall(arr, fn: Callable) -> ArrayForAll:
    from .core import _lit
    x, body = _one_arg(fn)
    return ArrayForAll(_lit(arr), x, _lit(body))


def filter_(arr, fn: Callable) -> ArrayFilter:
    from .core import _lit
    x, body = _one_arg(fn)
    return ArrayFilter(_lit(arr), x, _lit(body))


def aggregate(arr, zero, merge: Callable,
              finish: Optional[Callable] = None) -> ArrayAggregate:
    from .core import _lit
    acc, x = LambdaVariable(), LambdaVariable()
    fin = None
    if finish is not None:
        facc = acc  # finish sees the same accumulator variable
        fin = _lit(finish(facc))
    return ArrayAggregate(_lit(arr), _lit(zero), acc, x,
                          _lit(merge(acc, x)), fin)


def map_keys(m) -> MapKeys:
    from .core import _lit
    return MapKeys(_lit(m))


def map_values(m) -> MapValues:
    from .core import _lit
    return MapValues(_lit(m))


def map_entries(m) -> MapEntries:
    from .core import _lit
    return MapEntries(_lit(m))


def map_contains_key(m, k) -> MapContainsKey:
    from .core import _lit
    return MapContainsKey(_lit(m), _lit(k))


def get_map_value(m, k) -> GetMapValue:
    from .core import _lit
    return GetMapValue(_lit(m), _lit(k))


def transform_values(m, fn: Callable) -> TransformValues:
    from .core import _lit
    k, v = LambdaVariable(), LambdaVariable()
    return TransformValues(_lit(m), k, v, _lit(fn(k, v)))


def transform_keys(m, fn: Callable) -> TransformKeys:
    from .core import _lit
    k, v = LambdaVariable(), LambdaVariable()
    return TransformKeys(_lit(m), k, v, _lit(fn(k, v)))


def map_filter(m, fn: Callable) -> MapFilter:
    from .core import _lit
    k, v = LambdaVariable(), LambdaVariable()
    return MapFilter(_lit(m), k, v, _lit(fn(k, v)))


def create_map(*kv) -> CreateMap:
    from .core import _lit
    return CreateMap(*[_lit(e) for e in kv])


def map_from_arrays(keys, values) -> MapFromArrays:
    from .core import _lit
    return MapFromArrays(_lit(keys), _lit(values))
