"""Expression IR core.

TPU-native analogue of the reference's expression layer: where the
reference wraps Catalyst Expressions in BaseExprMeta and lowers each to a
cuDF ColumnVector call (RapidsMeta.scala:1030, per-expression GpuExpression
impls across sql-plugin), here an Expression tree lowers directly to
jax.numpy ops over ColumnVector/StringColumn buffers. An entire operator's
expression set evaluates inside one jax.jit trace, so XLA fuses the whole
expression DAG into a handful of TPU kernels — the "one JNI call per
expression" hot loop of the reference (SURVEY §3.3) simply does not exist
here.

Null semantics are SQL three-valued logic carried in the validity mask:
- most scalar functions: result null iff any input null,
- AND/OR use Kleene logic (predicates.py),
- data lanes under a null are zeroed so downstream kernels never see
  garbage (the invariant established in columnar/vector.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import Column, ColumnVector, ColumnarBatch, StringColumn

Schema = Sequence  # [(name, DType), ...]


class Expression:
    """Base expression node. Immutable; children in ``children``."""

    def __init__(self, *children: "Expression"):
        self.children: List[Expression] = list(children)

    # --- planning-time ---
    def data_type(self, schema: Schema) -> dt.DType:
        raise NotImplementedError

    def nullable(self, schema: Schema) -> bool:
        return True

    def references(self) -> set:
        refs = set()
        for c in self.children:
            refs |= c.references()
        return refs

    # --- execution-time (inside jit) ---
    def eval(self, batch: ColumnarBatch) -> Column:
        raise NotImplementedError

    # --- sugar for building trees (mirrors Spark's Column DSL) ---
    def __add__(self, other):
        from .arithmetic import Add
        return Add(self, _lit(other))

    def __radd__(self, other):
        from .arithmetic import Add
        return Add(_lit(other), self)

    def __sub__(self, other):
        from .arithmetic import Subtract
        return Subtract(self, _lit(other))

    def __rsub__(self, other):
        from .arithmetic import Subtract
        return Subtract(_lit(other), self)

    def __mul__(self, other):
        from .arithmetic import Multiply
        return Multiply(self, _lit(other))

    def __rmul__(self, other):
        from .arithmetic import Multiply
        return Multiply(_lit(other), self)

    def __truediv__(self, other):
        from .arithmetic import Divide
        return Divide(self, _lit(other))

    def __mod__(self, other):
        from .arithmetic import Remainder
        return Remainder(self, _lit(other))

    def __neg__(self):
        from .arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __eq__(self, other):  # type: ignore[override]
        from .predicates import EqualTo
        return EqualTo(self, _lit(other))

    def __ne__(self, other):  # type: ignore[override]
        from .predicates import Not, EqualTo
        return Not(EqualTo(self, _lit(other)))

    def __lt__(self, other):
        from .predicates import LessThan
        return LessThan(self, _lit(other))

    def __le__(self, other):
        from .predicates import LessThanOrEqual
        return LessThanOrEqual(self, _lit(other))

    def __gt__(self, other):
        from .predicates import GreaterThan
        return GreaterThan(self, _lit(other))

    def __ge__(self, other):
        from .predicates import GreaterThanOrEqual
        return GreaterThanOrEqual(self, _lit(other))

    def __and__(self, other):
        from .predicates import And
        return And(self, _lit(other))

    def __or__(self, other):
        from .predicates import Or
        return Or(self, _lit(other))

    def __invert__(self):
        from .predicates import Not
        return Not(self)

    def __hash__(self):
        return id(self)

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, to: dt.DType) -> "Expression":
        from .cast import Cast
        return Cast(self, to)

    def is_null(self):
        from .predicates import IsNull
        return IsNull(self)

    def is_not_null(self):
        from .predicates import IsNotNull
        return IsNotNull(self)

    def isin(self, *values):
        from .predicates import InSet
        return InSet(self, list(values))

    def between(self, lo, hi):
        return (self >= lo) & (self <= hi)

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}({args})"


def _lit(v):
    if isinstance(v, Expression):
        return v
    return Literal(v)


class ColumnRef(Expression):
    """Reference to a named input column (Catalyst AttributeReference)."""

    def __init__(self, name: str):
        super().__init__()
        self.name = name

    def data_type(self, schema: Schema) -> dt.DType:
        for n, t in schema:
            if n == self.name:
                return t
        raise KeyError(f"column {self.name!r} not in schema {[n for n, _ in schema]}")

    def references(self) -> set:
        return {self.name}

    def eval(self, batch: ColumnarBatch) -> Column:
        return batch.column(self.name)

    def __repr__(self):
        return f"col({self.name!r})"


def col(name: str) -> ColumnRef:
    return ColumnRef(name)


def _infer_literal_dtype(value) -> dt.DType:
    if value is None:
        return dt.NULL
    if isinstance(value, bool):
        return dt.BOOL
    if isinstance(value, int):
        if -(2**31) <= value < 2**31:
            return dt.INT32
        if -(2**63) <= value < 2**63:
            return dt.INT64
        # Spark types integral literals beyond long as DecimalType
        # (Literal.apply on BigInt/BigDecimal); beyond 38 digits Spark
        # fails analysis (DECIMAL_PRECISION_EXCEEDED) — mirror that
        # rather than silently clamping to an unrepresentable type
        digits = len(str(abs(value)))
        if digits > 38:
            raise TypeError(
                f"integral literal needs precision {digits} > 38")
        return dt.DecimalType(digits, 0)
    if isinstance(value, float):
        return dt.FLOAT64
    if isinstance(value, str):
        return dt.STRING
    import datetime
    if isinstance(value, datetime.datetime):
        return dt.TIMESTAMP
    if isinstance(value, datetime.date):
        return dt.DATE
    import decimal
    if isinstance(value, decimal.Decimal):
        exp = -value.as_tuple().exponent
        digits = len(value.as_tuple().digits)
        return dt.DecimalType(max(digits, exp + 1), max(exp, 0))
    raise TypeError(f"cannot make literal from {type(value)}")


class Literal(Expression):
    """A scalar constant, broadcast to the batch capacity at eval.

    XLA constant-folds and fuses the broadcast, so unlike cuDF Scalars
    there is no per-literal device allocation.
    """

    def __init__(self, value, dtype: Optional[dt.DType] = None):
        super().__init__()
        self.value = value
        self.dtype = dtype or _infer_literal_dtype(value)

    def data_type(self, schema: Schema) -> dt.DType:
        return self.dtype

    def nullable(self, schema: Schema) -> bool:
        return self.value is None

    def eval(self, batch: ColumnarBatch) -> Column:
        cap = batch.capacity
        live = batch.live_mask()
        if self.value is None:
            if self.dtype == dt.STRING:
                return StringColumn(jnp.zeros(cap + 1, jnp.int32),
                                    jnp.zeros(8, jnp.uint8),
                                    jnp.zeros(cap, jnp.bool_),
                                    pad_bucket=8)
            phys = self.dtype.physical or jnp.int32
            return ColumnVector(jnp.zeros(cap, phys), jnp.zeros(cap, jnp.bool_),
                                self.dtype if self.dtype != dt.NULL else dt.INT32)
        if self.dtype == dt.STRING:
            from ..columnar.vector import round_pow2
            raw = str(self.value).encode("utf-8")
            n = len(raw)
            pad = round_pow2(n)
            offsets = jnp.arange(cap + 1, dtype=jnp.int32) * n
            chars = jnp.tile(jnp.frombuffer(raw, dtype=jnp.uint8) if n else
                             jnp.zeros(1, jnp.uint8), max(cap, 1))
            return StringColumn(offsets, chars, live, pad_bucket=pad)
        phys = self.dtype.physical
        value = self.value
        if isinstance(self.dtype, dt.DecimalType):
            import decimal
            value = int(decimal.Decimal(value).scaleb(self.dtype.scale).to_integral_value())
            if self.dtype.is_wide:
                from ..columnar.decimal128 import Decimal128Column
                hi = jnp.full(cap, value >> 64, jnp.int64)
                lo = jnp.full(cap, value & ((1 << 64) - 1), jnp.uint64)
                z64, zu = jnp.zeros((), jnp.int64), jnp.zeros((), jnp.uint64)
                return Decimal128Column(jnp.where(live, hi, z64),
                                        jnp.where(live, lo, zu),
                                        live, self.dtype)
        import datetime
        if isinstance(value, datetime.datetime):
            value = int(value.replace(tzinfo=datetime.timezone.utc).timestamp() * 1_000_000)
        elif isinstance(value, datetime.date):
            value = (value - datetime.date(1970, 1, 1)).days
        data = jnp.full(cap, value, phys)
        return ColumnVector(jnp.where(live, data, jnp.zeros((), phys)), live, self.dtype)

    def __repr__(self):
        return f"lit({self.value!r})"


def lit(value, dtype: Optional[dt.DType] = None) -> Literal:
    return Literal(value, dtype)


class Alias(Expression):
    """Named output expression (Catalyst Alias)."""

    def __init__(self, child: Expression, name: str):
        super().__init__(child)
        self.name = name

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def nullable(self, schema: Schema) -> bool:
        return self.children[0].nullable(schema)

    def eval(self, batch: ColumnarBatch) -> Column:
        return self.children[0].eval(batch)

    def __repr__(self):
        return f"{self.children[0]!r}.alias({self.name!r})"


def output_name(expr: Expression, index: int) -> str:
    """Output column name for a projection list entry."""
    if isinstance(expr, Alias):
        return expr.name
    if isinstance(expr, ColumnRef):
        return expr.name
    return f"_c{index}"


# ---------------------------------------------------------------------------
# Helpers shared by concrete expression modules
# ---------------------------------------------------------------------------

def numeric_result(*cols: ColumnVector) -> dt.DType:
    out = cols[0].dtype
    for c in cols[1:]:
        out = dt.promote(out, c.dtype)
    return out


def merged_validity(*cols: Column):
    v = cols[0].validity
    for c in cols[1:]:
        v = v & c.validity
    return v


def make_result(data, validity, dtype: dt.DType) -> ColumnVector:
    """Standard result construction: zero data lanes under nulls."""
    data = jnp.where(validity, data, jnp.zeros((), data.dtype))
    return ColumnVector(data, validity, dtype)
