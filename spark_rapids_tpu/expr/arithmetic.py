"""Arithmetic expressions.

Covers the reference's arithmetic surface
(sql-plugin/src/main/scala/org/apache/spark/sql/rapids/arithmetic.scala):
add/subtract/multiply/divide/integral-divide/remainder/pmod/unary ops with
Spark semantics — divide-by-zero yields null (non-ANSI mode), Divide on
non-decimals returns double, decimal +,-,* follow Spark's result
precision/scale rules for long-backed (p<=18) decimals.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result, merged_validity


def _decimal_result(op: str, a: dt.DecimalType, b: dt.DecimalType) -> dt.DecimalType:
    """Spark DecimalPrecision result types (capped at long-backed p=18)."""
    p1, s1, p2, s2 = a.precision, a.scale, b.precision, b.scale
    if op in ("add", "sub"):
        scale = max(s1, s2)
        prec = max(p1 - s1, p2 - s2) + scale + 1
    elif op == "mul":
        scale = s1 + s2
        prec = p1 + p2 + 1
    else:
        raise TypeError(f"decimal {op} unsupported")
    prec = min(prec, dt.DecimalType.MAX_LONG_PRECISION)
    scale = min(scale, prec)
    return dt.DecimalType(prec, scale)


class BinaryArithmetic(Expression):
    op_name = "?"

    def data_type(self, schema: Schema) -> dt.DType:
        lt = self.children[0].data_type(schema)
        rt = self.children[1].data_type(schema)
        if isinstance(lt, dt.DecimalType) and isinstance(rt, dt.DecimalType):
            return self._decimal_type(lt, rt)
        if isinstance(lt, dt.DecimalType) or isinstance(rt, dt.DecimalType):
            raise TypeError("implicit decimal/non-decimal arithmetic needs a cast")
        return self._result_type(lt, rt)

    def _result_type(self, lt: dt.DType, rt: dt.DType) -> dt.DType:
        return dt.promote(lt, rt)

    def _decimal_type(self, lt, rt) -> dt.DType:
        raise TypeError(f"{self.op_name} does not support decimals")

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        left = self.children[0].eval(batch)
        right = self.children[1].eval(batch)
        out_t = self.data_type(batch.schema())
        validity = merged_validity(left, right)
        if isinstance(out_t, dt.DecimalType):
            data, validity = self._compute_decimal(
                left, right, out_t, validity)
            return make_result(data, validity, out_t)
        phys = out_t.physical
        a = left.data.astype(phys)
        b = right.data.astype(phys)
        data, validity = self._compute(a, b, validity, out_t)
        return make_result(data, validity, out_t)

    def _compute(self, a, b, validity, out_t):
        raise NotImplementedError

    def _compute_decimal(self, left, right, out_t, validity):
        raise TypeError(f"{self.op_name} does not support decimals")


def _rescale(data, from_scale: int, to_scale: int):
    if to_scale > from_scale:
        return data * jnp.asarray(10 ** (to_scale - from_scale), data.dtype)
    if to_scale < from_scale:
        return data // jnp.asarray(10 ** (from_scale - to_scale), data.dtype)
    return data


class Add(BinaryArithmetic):
    op_name = "+"

    def _compute(self, a, b, validity, out_t):
        return a + b, validity

    def _decimal_type(self, lt, rt):
        return _decimal_result("add", lt, rt)

    def _compute_decimal(self, left, right, out_t, validity):
        a = _rescale(left.data, left.dtype.scale, out_t.scale)
        b = _rescale(right.data, right.dtype.scale, out_t.scale)
        return a + b, validity


class Subtract(BinaryArithmetic):
    op_name = "-"

    def _compute(self, a, b, validity, out_t):
        return a - b, validity

    def _decimal_type(self, lt, rt):
        return _decimal_result("sub", lt, rt)

    def _compute_decimal(self, left, right, out_t, validity):
        a = _rescale(left.data, left.dtype.scale, out_t.scale)
        b = _rescale(right.data, right.dtype.scale, out_t.scale)
        return a - b, validity


class Multiply(BinaryArithmetic):
    op_name = "*"

    def _compute(self, a, b, validity, out_t):
        return a * b, validity

    def _decimal_type(self, lt, rt):
        return _decimal_result("mul", lt, rt)

    def _compute_decimal(self, left, right, out_t, validity):
        raw = left.data * right.data  # scale s1+s2
        raw_scale = left.dtype.scale + right.dtype.scale
        return _rescale(raw, raw_scale, out_t.scale), validity


class Divide(BinaryArithmetic):
    """Spark Divide: non-decimal result is always double; x/0 -> null."""

    op_name = "/"

    def _result_type(self, lt, rt):
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        left = self.children[0].eval(batch)
        right = self.children[1].eval(batch)
        validity = merged_validity(left, right)
        a = left.data.astype(jnp.float64)
        b = right.data.astype(jnp.float64)
        if isinstance(left.dtype, dt.DecimalType):
            a = a / (10.0 ** left.dtype.scale)
        if isinstance(right.dtype, dt.DecimalType):
            b = b / (10.0 ** right.dtype.scale)
        validity = validity & (b != 0.0)
        data = jnp.where(b != 0.0, a / jnp.where(b == 0.0, 1.0, b), 0.0)
        return make_result(data, validity, dt.FLOAT64)

    def _decimal_type(self, lt, rt):
        # Simplified: decimal division flows through double (cast back if
        # a decimal result is required). Full decimal division lands with
        # the decimal128 work.
        return dt.FLOAT64


class IntegralDivide(BinaryArithmetic):
    """`div` — always returns bigint; x div 0 -> null."""

    op_name = "div"

    def _result_type(self, lt, rt):
        return dt.INT64

    def _compute(self, a, b, validity, out_t):
        zero = b == 0
        validity = validity & ~zero
        safe_b = jnp.where(zero, jnp.ones((), b.dtype), b)
        # Spark/Java semantics: truncate toward zero (jnp floor-divides).
        q = jnp.trunc(a.astype(jnp.float64) / safe_b.astype(jnp.float64)) \
            if jnp.issubdtype(a.dtype, jnp.floating) else _trunc_div(a, safe_b)
        return q.astype(jnp.int64), validity


def _trunc_div(a, b):
    q = a // b
    r = a - q * b
    # floor->trunc correction when signs differ and remainder nonzero
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


def _trunc_mod(a, b):
    r = a % b
    # Python % is floor-mod; Java % is trunc-mod: result takes sign of a.
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return r - jnp.where(adjust, b, jnp.zeros((), b.dtype))


class Remainder(BinaryArithmetic):
    """% with Java sign semantics; x % 0 -> null."""

    op_name = "%"

    def _compute(self, a, b, validity, out_t):
        if jnp.issubdtype(a.dtype, jnp.floating):
            zero = b == 0.0
            validity = validity & ~zero
            safe = jnp.where(zero, jnp.ones((), b.dtype), b)
            return jnp.fmod(a, safe), validity
        zero = b == 0
        validity = validity & ~zero
        safe = jnp.where(zero, jnp.ones((), b.dtype), b)
        return _trunc_mod(a, safe), validity


class Pmod(BinaryArithmetic):
    """pmod(a, b): positive modulus."""

    op_name = "pmod"

    def _compute(self, a, b, validity, out_t):
        zero = b == 0
        validity = validity & ~zero
        safe = jnp.where(zero, jnp.ones((), b.dtype), b)
        if jnp.issubdtype(a.dtype, jnp.floating):
            r = jnp.fmod(a, safe)
            r = jnp.where(r < 0, r + jnp.abs(safe), r)
            return r, validity
        r = _trunc_mod(a, safe)
        r = jnp.where(r < 0, r + jnp.abs(safe), r)
        return r, validity


class UnaryMinus(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        return make_result(-c.data, c.validity, c.dtype)


class UnaryPositive(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        return self.children[0].eval(batch)


class Abs(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        return make_result(jnp.abs(c.data), c.validity, c.dtype)


class Least(Expression):
    """least(...) — null-skipping minimum across columns."""

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        for c in self.children[1:]:
            t = dt.promote(t, c.data_type(schema))
        return t

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        out_t = self.data_type(batch.schema())
        phys = out_t.physical
        cols = [c.eval(batch) for c in self.children]
        big = jnp.asarray(dt.max_value(out_t), phys)
        data = jnp.full(batch.capacity, big, phys)
        any_valid = jnp.zeros(batch.capacity, jnp.bool_)
        for c in cols:
            v = jnp.where(c.validity, c.data.astype(phys), big)
            data = jnp.minimum(data, v)
            any_valid = any_valid | c.validity
        return make_result(data, any_valid, out_t)


class Greatest(Expression):
    """greatest(...) — null-skipping maximum across columns."""

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        for c in self.children[1:]:
            t = dt.promote(t, c.data_type(schema))
        return t

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        out_t = self.data_type(batch.schema())
        phys = out_t.physical
        cols = [c.eval(batch) for c in self.children]
        small = jnp.asarray(dt.min_value(out_t), phys)
        data = jnp.full(batch.capacity, small, phys)
        any_valid = jnp.zeros(batch.capacity, jnp.bool_)
        for c in cols:
            v = jnp.where(c.validity, c.data.astype(phys), small)
            data = jnp.maximum(data, v)
            any_valid = any_valid | c.validity
        return make_result(data, any_valid, out_t)
