"""Arithmetic expressions.

Covers the reference's arithmetic surface
(sql-plugin/src/main/scala/org/apache/spark/sql/rapids/arithmetic.scala):
add/subtract/multiply/divide/integral-divide/remainder/pmod/unary ops with
Spark semantics — divide-by-zero yields null (non-ANSI mode), Divide on
non-decimals returns double, decimal +,-,* follow Spark's result
precision/scale rules for long-backed (p<=18) decimals.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import decimal128 as d128
from ..columnar import dtypes as dt
from ..columnar.decimal128 import Decimal128Column
from ..columnar.vector import ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result, merged_validity


def _decimal_result(op: str, a: dt.DecimalType, b: dt.DecimalType) -> dt.DecimalType:
    """Spark DecimalPrecision result types (dtypes.decimal_result_type;
    full decimal128 range, allowPrecisionLoss semantics)."""
    return dt.decimal_result_type(op, a, b)


def _is_narrow_fast(left, right, out_t: dt.DecimalType) -> bool:
    """Both operands long-backed and the result fits long-backed: the
    plain int64 lane path is exact (result precision accounts for the
    carry / product width)."""
    return (not isinstance(left, Decimal128Column)
            and not isinstance(right, Decimal128Column)
            and not out_t.is_wide)


def _lift_rescaled(col, to_scale: int):
    """(hi, lo, upscale_overflow) of a decimal column rescaled to
    ``to_scale``. Scale reduction (result scale adjusted below an
    operand scale by adjustPrecisionScale) rounds HALF_UP, matching the
    implicit cast Spark inserts to the result type."""
    hi, lo = d128.limbs_of(col)
    k = to_scale - col.dtype.scale
    if k == 0:
        return hi, lo, jnp.zeros(hi.shape, jnp.bool_)
    if k < 0:
        hi, lo = d128.d128_div_pow10_half_up(hi, lo, -k)
        return hi, lo, jnp.zeros(hi.shape, jnp.bool_)
    hi, lo, ovf = d128.d128_mul_pow10(hi, lo, k)
    return hi, lo, ovf


def _finish_decimal(hi, lo, validity, ok, out_t: dt.DecimalType):
    """Overflow->null (non-ANSI Spark) + precision bound check."""
    ok = ok & d128.d128_fits_precision(hi, lo, out_t.precision)
    return d128.build_decimal_column(hi, lo, validity & ok, out_t)


#: Spark's exact decimal representation of each integral type
#: (DecimalType.forType): the implicit-coercion target for
#: integral-op-decimal arithmetic.
_INTEGRAL_DECIMAL = {dt.INT8: (3, 0), dt.INT16: (5, 0),
                     dt.INT32: (10, 0), dt.INT64: (20, 0)}


def decimal_coerced_children(expr: Expression, schema: Schema):
    """Spark's DecimalPrecision implicit coercion for mixed
    decimal/non-decimal binary arithmetic: the integral side casts to
    its exact decimal type (int -> decimal(10,0), bigint ->
    decimal(20,0), ...); against a float/double the DECIMAL side casts
    to double. Shared by the device eval AND the CPU oracle so both
    engines resolve the same promoted tree."""
    left, right = expr.children[0], expr.children[1]
    lt, rt = left.data_type(schema), right.data_type(schema)
    ldec = isinstance(lt, dt.DecimalType)
    rdec = isinstance(rt, dt.DecimalType)
    if ldec == rdec:
        return left, right
    from .cast import Cast
    other_t = rt if ldec else lt
    if other_t in _INTEGRAL_DECIMAL:
        other = right if ldec else left
        from .core import Literal
        if isinstance(other, Literal) and other.value is not None:
            # Spark DecimalPrecision.nondecimalAndDecimal uses the
            # TIGHT DecimalType.fromLiteral for literal operands
            # (precision = significant digits of the value, scale 0) —
            # the attribute-width forType mapping below would widen the
            # result type and move the overflow-null boundary near
            # precision 38.
            digits = max(1, len(str(abs(int(other.value)))))
            target = dt.DecimalType(digits, 0)
        else:
            target = dt.DecimalType(*_INTEGRAL_DECIMAL[other_t])
        wrapped = Cast(other, target)
        return (left, wrapped) if ldec else (wrapped, right)
    if getattr(other_t, "is_floating", False):
        if ldec:
            return Cast(left, dt.FLOAT64), right
        return left, Cast(right, dt.FLOAT64)
    raise TypeError(
        f"decimal {expr.op_name} {other_t}: no implicit coercion "
        "(Spark coerces integral and floating operands only)")


class BinaryArithmetic(Expression):
    op_name = "?"
    #: ANSI mode (spark.sql.ansi.enabled): set by expr/ansi.enable_ansi
    #: at plan time; marked trees evaluate eagerly so the guards below
    #: can raise (reference: GpuOverrides.scala:1113-1122 wraps each op
    #: in an overflow-check kernel under ansiEnabled)
    ansi = False

    def coerced_children(self, schema: Schema):
        """The children this op ACTUALLY computes on, after implicit
        type coercion (DecimalPrecision; ops with narrower inputTypes
        override and add their own casts — IntegralDivide). Both
        engines (device eval and the CPU oracle) must evaluate THESE,
        never raw ``self.children``."""
        return decimal_coerced_children(self, schema)

    def _out_type(self, lt: dt.DType, rt: dt.DType) -> dt.DType:
        if isinstance(lt, dt.DecimalType) and \
                isinstance(rt, dt.DecimalType):
            return self._decimal_type(lt, rt)
        return self._result_type(lt, rt)

    def data_type(self, schema: Schema) -> dt.DType:
        left, right = self.coerced_children(schema)
        return self._out_type(left.data_type(schema),
                              right.data_type(schema))

    def _result_type(self, lt: dt.DType, rt: dt.DType) -> dt.DType:
        return dt.promote(lt, rt)

    def _decimal_type(self, lt, rt) -> dt.DType:
        raise TypeError(f"{self.op_name} does not support decimals")

    def eval(self, batch: ColumnarBatch):
        lc, rc = self.coerced_children(batch.schema())
        left = lc.eval(batch)
        right = rc.eval(batch)
        out_t = self._out_type(left.dtype, right.dtype)
        validity = merged_validity(left, right)
        if isinstance(out_t, dt.DecimalType) or \
                isinstance(left.dtype, dt.DecimalType):
            res = self._eval_decimal(left, right, out_t, validity)
            if self.ansi:
                from . import errors as ERR
                from .ansi import guard
                guard(validity & ~res.validity, ERR.SparkArithmeticException(
                    f"{self.op_name}: decimal overflow or division by "
                    f"zero (ANSI mode)"))
            return res
        phys = out_t.physical
        a = left.data.astype(phys)
        b = right.data.astype(phys)
        data, validity2 = self._compute(a, b, validity, out_t)
        if self.ansi:
            self._ansi_post(a, b, data, validity, validity2, out_t)
        return make_result(data, validity2, out_t)

    def _ansi_post(self, a, b, data, validity, validity2, out_t) -> None:
        """Default ANSI check: any null INTRODUCED by the op (x/0,
        x % 0) is an error instead of a null."""
        from . import errors as ERR
        from .ansi import guard
        guard(validity & ~validity2,
              ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))

    def _ansi_int_overflow(self, ovf, validity, out_t) -> None:
        from . import errors as ERR
        from .ansi import guard
        guard(ovf & validity, ERR.SparkArithmeticException(
            ERR.overflow_message(str(out_t))))

    def _compute(self, a, b, validity, out_t):
        raise NotImplementedError

    def _eval_decimal(self, left, right, out_t, validity):
        raise TypeError(f"{self.op_name} does not support decimals")


def _rescale(data, from_scale: int, to_scale: int):
    if to_scale > from_scale:
        return data * jnp.asarray(10 ** (to_scale - from_scale), data.dtype)
    if to_scale < from_scale:
        return data // jnp.asarray(10 ** (from_scale - to_scale), data.dtype)
    return data


class _AddSubBase(BinaryArithmetic):
    _sub = False

    def _eval_decimal(self, left, right, out_t, validity):
        if _is_narrow_fast(left, right, out_t):
            a = _rescale(left.data, left.dtype.scale, out_t.scale)
            b = _rescale(right.data, right.dtype.scale, out_t.scale)
            data = a - b if self._sub else a + b
            return make_result(data, validity, out_t)
        ah, al, o1 = _lift_rescaled(left, out_t.scale)
        bh, bl, o2 = _lift_rescaled(right, out_t.scale)
        if self._sub:
            rh, rl = d128.d128_sub(ah, al, bh, bl)
        else:
            rh, rl = d128.d128_add(ah, al, bh, bl)
        # a 128-bit wrap on the add itself always lands outside the
        # precision bound (|a|,|b| < 10^38 and 2*10^38 - 2^128 < -10^38),
        # so the fits check catches it.
        return _finish_decimal(rh, rl, validity, ~(o1 | o2), out_t)


class Add(_AddSubBase):
    op_name = "+"

    def _compute(self, a, b, validity, out_t):
        return a + b, validity

    def _ansi_post(self, a, b, data, validity, validity2, out_t):
        if out_t.is_integral:
            # Math.addExact: same operand signs, flipped result sign
            ovf = ((a >= 0) == (b >= 0)) & ((data >= 0) != (a >= 0))
            self._ansi_int_overflow(ovf, validity, out_t)

    def _decimal_type(self, lt, rt):
        return _decimal_result("add", lt, rt)


class Subtract(_AddSubBase):
    op_name = "-"
    _sub = True

    def _compute(self, a, b, validity, out_t):
        return a - b, validity

    def _ansi_post(self, a, b, data, validity, validity2, out_t):
        if out_t.is_integral:
            # Math.subtractExact: differing signs, result sign != a's
            ovf = ((a >= 0) != (b >= 0)) & ((data >= 0) != (a >= 0))
            self._ansi_int_overflow(ovf, validity, out_t)

    def _decimal_type(self, lt, rt):
        return _decimal_result("sub", lt, rt)


class Multiply(BinaryArithmetic):
    op_name = "*"

    def _compute(self, a, b, validity, out_t):
        return a * b, validity

    def _ansi_post(self, a, b, data, validity, validity2, out_t):
        if out_t.is_integral:
            # Math.multiplyExact: wrapped product fails the division
            # round-trip; MIN * -1 wraps back to MIN and needs the
            # explicit corner check
            lo = jnp.iinfo(out_t.physical).min
            nz = b != 0
            safe_b = jnp.where(nz, b, jnp.ones((), b.dtype))
            ovf = nz & (_trunc_div(data, safe_b) != a)
            ovf = ovf | ((a == lo) & (b == -1)) | ((b == lo) & (a == -1))
            self._ansi_int_overflow(ovf, validity, out_t)

    def _decimal_type(self, lt, rt):
        return _decimal_result("mul", lt, rt)

    def _eval_decimal(self, left, right, out_t, validity):
        raw_scale = left.dtype.scale + right.dtype.scale
        if _is_narrow_fast(left, right, out_t) and raw_scale == out_t.scale:
            # p1+p2+1 <= 18 so the int64 product cannot overflow
            return make_result(left.data * right.data, validity, out_t)
        ah, al = d128.limbs_of(left)
        bh, bl = d128.limbs_of(right)
        rh, rl, ovf = d128.d128_mul_exact(ah, al, bh, bl,
                                          raw_scale - out_t.scale)
        return _finish_decimal(rh, rl, validity, ~ovf, out_t)


class Divide(BinaryArithmetic):
    """Spark Divide: non-decimal result is always double, decimal /
    decimal is exact decimal division (HALF_UP at the result scale);
    x/0 -> null in either mode."""

    op_name = "/"

    def _result_type(self, lt, rt):
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch):
        lc, rc = self.coerced_children(batch.schema())
        left = lc.eval(batch)
        right = rc.eval(batch)
        out_t = self._out_type(left.dtype, right.dtype)
        validity = merged_validity(left, right)
        if isinstance(out_t, dt.DecimalType):
            res = self._eval_decimal(left, right, out_t, validity)
            if self.ansi:
                from . import errors as ERR
                from .ansi import guard
                guard(validity & ~res.validity, ERR.SparkArithmeticException(
                    "/: decimal overflow or division by zero (ANSI mode)"))
            return res
        a = left.data.astype(jnp.float64)
        b = right.data.astype(jnp.float64)
        if self.ansi:
            from . import errors as ERR
            from .ansi import guard
            guard(validity & (b == 0.0),
                  ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))
        validity = validity & (b != 0.0)
        data = jnp.where(b != 0.0, a / jnp.where(b == 0.0, 1.0, b), 0.0)
        return make_result(data, validity, dt.FLOAT64)

    def _decimal_type(self, lt, rt):
        return _decimal_result("div", lt, rt)

    def _eval_decimal(self, left, right, out_t, validity):
        lt, rt = left.dtype, right.dtype
        ah, al = d128.limbs_of(left)
        bh, bl = d128.limbs_of(right)
        nonzero = (bh != 0) | (bl != 0)
        validity = validity & nonzero
        safe_bl = jnp.where(nonzero, bl, jnp.uint64(1))
        up = out_t.scale - lt.scale + rt.scale
        rh, rl, ovf = d128.d128_div_exact(ah, al, bh, safe_bl, up)
        return _finish_decimal(rh, rl, validity, ~ovf, out_t)


def _decimal_divmod_aligned(left, right, validity):
    """Common-scale 128-bit truncating divmod for long-backed decimal
    operands (alignment cannot overflow: |v| < 10^18 * 10^18 < 2^127).
    Returns (qh, ql, rh, rl, bh, bl, validity&nonzero, scale)."""
    s = max(left.dtype.scale, right.dtype.scale)
    ah, al, _ = _lift_rescaled(left, s)
    bh, bl, _ = _lift_rescaled(right, s)
    nonzero = (bh != 0) | (bl != 0)
    safe_bl = jnp.where(nonzero, bl, jnp.uint64(1))
    qh, ql, rh, rl = d128.d128_div_trunc(ah, al, bh, safe_bl)
    return qh, ql, rh, rl, bh, safe_bl, validity & nonzero, s


class IntegralDivide(BinaryArithmetic):
    """`div` — always returns bigint; x div 0 -> null."""

    op_name = "div"

    def _result_type(self, lt, rt):
        return dt.INT64

    def _decimal_type(self, lt, rt):
        # wide operands are excluded at tagging (plan/overrides.py sig)
        return dt.INT64

    def coerced_children(self, schema: Schema):
        """Spark IntegralDivide inputType is (LongType, DecimalType):
        the analyzer casts FLOATING operands to long BEFORE dividing
        (CAST(0.5 AS DOUBLE) becomes 0 -> x div 0 is NULL), and
        integral operands widen to long (so INT_MIN div -1 = 2^31,
        no 32-bit wrap)."""
        from .cast import Cast
        left, right = decimal_coerced_children(self, schema)
        lt = left.data_type(schema)
        rt = right.data_type(schema)
        if getattr(lt, "is_floating", False):
            left = Cast(left, dt.INT64)
        if getattr(rt, "is_floating", False):
            right = Cast(right, dt.INT64)
        return left, right

    def _eval_decimal(self, left, right, out_t, validity):
        qh, ql, _, _, _, _, validity, _ = _decimal_divmod_aligned(
            left, right, validity)
        # quotient must fit a long; out-of-range -> null (non-ANSI)
        fits = (qh == jnp.where(ql.astype(jnp.int64) < 0, jnp.int64(-1),
                                jnp.int64(0)))
        return make_result(ql.astype(jnp.int64), validity & fits, dt.INT64)

    def _compute(self, a, b, validity, out_t):
        zero = b == 0
        validity = validity & ~zero
        safe_b = jnp.where(zero, jnp.ones((), b.dtype), b)
        # Spark/Java semantics: truncate toward zero (jnp floor-divides).
        q = jnp.trunc(a.astype(jnp.float64) / safe_b.astype(jnp.float64)) \
            if jnp.issubdtype(a.dtype, jnp.floating) else _trunc_div(a, safe_b)
        return q.astype(jnp.int64), validity

    def _ansi_post(self, a, b, data, validity, validity2, out_t):
        super()._ansi_post(a, b, data, validity, validity2, out_t)
        if not jnp.issubdtype(a.dtype, jnp.floating):
            lo = jnp.iinfo(jnp.int64).min
            ovf = (a.astype(jnp.int64) == lo) & (b.astype(jnp.int64) == -1)
            self._ansi_int_overflow(ovf, validity, dt.INT64)


def _trunc_div(a, b):
    q = a // b
    r = a - q * b
    # floor->trunc correction when signs differ and remainder nonzero
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


def _trunc_mod(a, b):
    r = a % b
    # Python % is floor-mod; Java % is trunc-mod: result takes sign of a.
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return r - jnp.where(adjust, b, jnp.zeros((), b.dtype))


class Remainder(BinaryArithmetic):
    """% with Java sign semantics; x % 0 -> null."""

    op_name = "%"

    def _decimal_type(self, lt, rt):
        # wide operands are excluded at tagging (plan/overrides.py sig)
        return _decimal_result("mod", lt, rt)

    def _eval_decimal(self, left, right, out_t, validity):
        _, _, rh, rl, _, _, validity, s = _decimal_divmod_aligned(
            left, right, validity)
        if out_t.scale != s:  # mod result scale is max(s1,s2) pre-adjust
            rh, rl = d128.d128_div_pow10_half_up(rh, rl, s - out_t.scale)
        return _finish_decimal(rh, rl, validity,
                               jnp.ones(rh.shape, jnp.bool_), out_t)

    def _compute(self, a, b, validity, out_t):
        if jnp.issubdtype(a.dtype, jnp.floating):
            zero = b == 0.0
            validity = validity & ~zero
            safe = jnp.where(zero, jnp.ones((), b.dtype), b)
            return jnp.fmod(a, safe), validity
        zero = b == 0
        validity = validity & ~zero
        safe = jnp.where(zero, jnp.ones((), b.dtype), b)
        return _trunc_mod(a, safe), validity


class Pmod(BinaryArithmetic):
    """pmod(a, b): positive modulus."""

    op_name = "pmod"

    def _decimal_type(self, lt, rt):
        # wide operands are excluded at tagging (plan/overrides.py sig)
        return _decimal_result("mod", lt, rt)

    def _eval_decimal(self, left, right, out_t, validity):
        _, _, rh, rl, bh, bl, validity, s = _decimal_divmod_aligned(
            left, right, validity)
        abh, abl = d128.d128_abs(bh, bl)
        ph, pl = d128.d128_add(rh, rl, abh, abl)
        neg = rh < 0
        rh = jnp.where(neg, ph, rh)
        rl = jnp.where(neg, pl, rl)
        if out_t.scale != s:
            rh, rl = d128.d128_div_pow10_half_up(rh, rl, s - out_t.scale)
        return _finish_decimal(rh, rl, validity,
                               jnp.ones(rh.shape, jnp.bool_), out_t)

    def _compute(self, a, b, validity, out_t):
        zero = b == 0
        validity = validity & ~zero
        safe = jnp.where(zero, jnp.ones((), b.dtype), b)
        if jnp.issubdtype(a.dtype, jnp.floating):
            r = jnp.fmod(a, safe)
            r = jnp.where(r < 0, r + jnp.abs(safe), r)
            return r, validity
        r = _trunc_mod(a, safe)
        r = jnp.where(r < 0, r + jnp.abs(safe), r)
        return r, validity


class UnaryMinus(Expression):
    ansi = False

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        if isinstance(c, Decimal128Column):
            nh, nl = d128.d128_neg(c.hi, c.lo)
            return d128.build_decimal_column(nh, nl, c.validity, c.dtype)
        if self.ansi and c.dtype.is_integral:
            from . import errors as ERR
            from .ansi import guard
            lo = jnp.iinfo(c.dtype.physical).min
            guard(c.validity & (c.data == lo),
                  ERR.SparkArithmeticException(
                      ERR.overflow_message(str(c.dtype))))
        return make_result(-c.data, c.validity, c.dtype)


class UnaryPositive(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        return self.children[0].eval(batch)


class Abs(Expression):
    ansi = False

    def data_type(self, schema: Schema) -> dt.DType:
        return self.children[0].data_type(schema)

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        if isinstance(c, Decimal128Column):
            ah, al = d128.d128_abs(c.hi, c.lo)
            return d128.build_decimal_column(ah, al, c.validity, c.dtype)
        if self.ansi and c.dtype.is_integral:
            from . import errors as ERR
            from .ansi import guard
            lo = jnp.iinfo(c.dtype.physical).min
            guard(c.validity & (c.data == lo),
                  ERR.SparkArithmeticException(
                      ERR.overflow_message(str(c.dtype))))
        return make_result(jnp.abs(c.data), c.validity, c.dtype)


class _Materialized(Expression):
    """Wraps an already-evaluated column so fold steps re-reference it
    in O(1) instead of re-evaluating a duplicated subtree (a naive
    If-fold references its accumulator 4x per step => O(4^n) tree)."""

    def __init__(self, column, dtype_: dt.DType):
        super().__init__()
        self._col = column
        self._t = dtype_

    def data_type(self, schema: Schema) -> dt.DType:
        return self._t

    def eval(self, batch: ColumnarBatch):
        return self._col


def minmax_fold(children, largest: bool) -> Expression:
    """least/greatest as a null-skipping If-fold — the lane for types
    without a numeric identity value (strings). Shared with the CPU
    oracle so both engines resolve the identical per-step semantics.

    The returned expression evaluates each child ONCE and each fold
    step once (children materialize through _Materialized wrappers at
    eval time), keeping cost linear in the child count."""
    from .conditional import If
    from .predicates import IsNull

    class _Fold(Expression):
        def __init__(self):
            super().__init__(*children)

        def data_type(self, schema: Schema) -> dt.DType:
            t = children[0].data_type(schema)
            for c in children[1:]:
                t = dt.promote(t, c.data_type(schema))
            return t

        def eval(self, batch: ColumnarBatch):
            out_t = self.data_type(batch.schema())
            acc = children[0].eval(batch)
            for c in children[1:]:
                wa = _Materialized(acc, out_t)
                wc = _Materialized(c.eval(batch), out_t)
                pick = If(wc > wa if largest else wc < wa, wc, wa)
                acc = If(IsNull(wa), wc,
                         If(IsNull(wc), wa, pick)).eval(batch)
            return acc

    return _Fold()


class _LeastGreatestBase(Expression):
    largest = False

    def data_type(self, schema: Schema) -> dt.DType:
        t = self.children[0].data_type(schema)
        for c in self.children[1:]:
            t = dt.promote(t, c.data_type(schema))
        return t

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        out_t = self.data_type(batch.schema())
        if isinstance(out_t, dt.StringType):
            return minmax_fold(list(self.children),
                               self.largest).eval(batch)
        phys = out_t.physical
        cols = [c.eval(batch) for c in self.children]
        cap = batch.capacity
        fill = dt.min_value(out_t) if self.largest else dt.max_value(out_t)
        fill = jnp.asarray(fill, phys)
        data = jnp.full(cap, fill, phys)
        any_valid = jnp.zeros(cap, jnp.bool_)
        red = jnp.maximum if self.largest else jnp.minimum
        if out_t.is_floating:
            # Spark float order: NaN GREATEST. greatest => any valid
            # NaN wins; least => NaN only when no non-NaN valid value
            nan_v = jnp.asarray(jnp.nan, phys)
            nan_seen = jnp.zeros(cap, jnp.bool_)
            num_seen = jnp.zeros(cap, jnp.bool_)
            for c in cols:
                nan = jnp.isnan(c.data)
                v = jnp.where(c.validity & ~nan, c.data.astype(phys),
                              fill)
                data = red(data, v)
                nan_seen = nan_seen | (c.validity & nan)
                num_seen = num_seen | (c.validity & ~nan)
                any_valid = any_valid | c.validity
            if self.largest:
                data = jnp.where(nan_seen, nan_v, data)
            else:
                data = jnp.where(num_seen, data, nan_v)
            return make_result(data, any_valid, out_t)
        for c in cols:
            v = jnp.where(c.validity, c.data.astype(phys), fill)
            data = red(data, v)
            any_valid = any_valid | c.validity
        return make_result(data, any_valid, out_t)


class Least(_LeastGreatestBase):
    """least(...) — null-skipping minimum across columns."""

    largest = False


class Greatest(_LeastGreatestBase):
    """greatest(...) — null-skipping maximum across columns."""

    largest = True
