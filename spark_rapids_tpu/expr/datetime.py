"""Date/time expressions.

Reference surface: sql-plugin/.../rapids/datetimeExpressions.scala (+ JNI
GpuTimeZoneDB). All timestamps are UTC micros; session-timezone handling
beyond UTC and the Julian/Gregorian rebase matrix (datetimeRebaseUtils)
land with the IO rebase work. Calendar math uses Hinnant's civil-date
algorithms (strings.py) — pure integer ops, fully vectorizable on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import ColumnVector, ColumnarBatch
from .core import Expression, Schema, make_result, merged_validity
from .strings import _civil_from_days, _days_from_civil

_MICROS_PER_DAY = 86_400_000_000


def _to_days(c: ColumnVector):
    if isinstance(c.dtype, dt.TimestampType):
        return c.data // _MICROS_PER_DAY
    return c.data.astype(jnp.int64)


class _DateField(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        y, m, d = _civil_from_days(_to_days(c))
        return make_result(self._pick(y, m, d).astype(jnp.int32), c.validity, dt.INT32)

    def _pick(self, y, m, d):
        raise NotImplementedError


class Year(_DateField):
    def _pick(self, y, m, d):
        return y


class Month(_DateField):
    def _pick(self, y, m, d):
        return m


class DayOfMonth(_DateField):
    def _pick(self, y, m, d):
        return d


class Quarter(_DateField):
    def _pick(self, y, m, d):
        return (m - 1) // 3 + 1


class DayOfWeek(_DateField):
    """1 = Sunday … 7 = Saturday (Spark semantics)."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        days = _to_days(c)
        # 1970-01-01 was a Thursday (dow index 4 with Sunday=0)
        dow = (days + 4) % 7
        return make_result((dow + 1).astype(jnp.int32), c.validity, dt.INT32)


class WeekDay(_DateField):
    """0 = Monday … 6 = Sunday."""

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        days = _to_days(c)
        return make_result(((days + 3) % 7).astype(jnp.int32), c.validity, dt.INT32)


class DayOfYear(_DateField):
    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        days = _to_days(c)
        y, m, d = _civil_from_days(days)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return make_result((days - jan1 + 1).astype(jnp.int32), c.validity, dt.INT32)


class LastDay(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        days = _to_days(c)
        y, m, _ = _civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        nxt = _days_from_civil(ny, nm, jnp.ones_like(nm))
        return make_result((nxt - 1).astype(jnp.int32), c.validity, dt.DATE)


class _TimeField(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        us = c.data.astype(jnp.int64)
        sec_of_day = (us % _MICROS_PER_DAY) // 1_000_000
        sec_of_day = jnp.where(sec_of_day < 0, sec_of_day + 86_400, sec_of_day)
        return make_result(self._pick(sec_of_day).astype(jnp.int32), c.validity, dt.INT32)

    def _pick(self, s):
        raise NotImplementedError


class Hour(_TimeField):
    def _pick(self, s):
        return s // 3600


class Minute(_TimeField):
    def _pick(self, s):
        return (s % 3600) // 60


class Second(_TimeField):
    def _pick(self, s):
        return s % 60


class DateAdd(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = (a.data.astype(jnp.int64) + b.data.astype(jnp.int64)).astype(jnp.int32)
        return make_result(data, merged_validity(a, b), dt.DATE)


class DateSub(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = (a.data.astype(jnp.int64) - b.data.astype(jnp.int64)).astype(jnp.int32)
        return make_result(data, merged_validity(a, b), dt.DATE)


class DateDiff(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT32

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        b = self.children[1].eval(batch)
        data = (_to_days(a) - _to_days(b)).astype(jnp.int32)
        return make_result(data, merged_validity(a, b), dt.INT32)


class AddMonths(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        a = self.children[0].eval(batch)
        n = self.children[1].eval(batch)
        y, m, d = _civil_from_days(_to_days(a))
        months = y * 12 + (m - 1) + n.data.astype(jnp.int64)
        ny = months // 12
        nm = months % 12 + 1
        # clamp day to last day of target month
        ny2 = jnp.where(nm == 12, ny + 1, ny)
        nm2 = jnp.where(nm == 12, 1, nm + 1)
        last = _days_from_civil(ny2, nm2, jnp.ones_like(nm2)) - 1
        _, _, last_d = _civil_from_days(last)
        nd = jnp.minimum(d, last_d)
        data = _days_from_civil(ny, nm, nd).astype(jnp.int32)
        return make_result(data, merged_validity(a, n), dt.DATE)


class TruncDate(Expression):
    """trunc(date, fmt) for fmt in year/month/week/quarter."""

    def __init__(self, child: Expression, fmt: str):
        super().__init__(child)
        self.fmt = fmt.lower()

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        days = _to_days(c)
        y, m, d = _civil_from_days(days)
        if self.fmt in ("year", "yyyy", "yy"):
            out = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        elif self.fmt in ("month", "mon", "mm"):
            out = _days_from_civil(y, m, jnp.ones_like(d))
        elif self.fmt in ("quarter",):
            qm = ((m - 1) // 3) * 3 + 1
            out = _days_from_civil(y, qm, jnp.ones_like(d))
        elif self.fmt in ("week",):
            dow = (days + 3) % 7  # Monday=0
            out = days - dow
        else:
            raise TypeError(f"trunc format {self.fmt!r} unsupported")
        return make_result(out.astype(jnp.int32), c.validity, dt.DATE)


class UnixTimestampToSeconds(Expression):
    """unix_timestamp(ts) — seconds since epoch."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.INT64

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        if isinstance(c.dtype, dt.DateType):
            data = c.data.astype(jnp.int64) * 86_400
        else:
            data = c.data.astype(jnp.int64) // 1_000_000
        return make_result(data, c.validity, dt.INT64)


class FromUnixTime(Expression):
    """Seconds since epoch -> timestamp."""

    def data_type(self, schema: Schema) -> dt.DType:
        return dt.TIMESTAMP

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        c = self.children[0].eval(batch)
        return make_result(c.data.astype(jnp.int64) * 1_000_000, c.validity, dt.TIMESTAMP)


class MakeDate(Expression):
    def data_type(self, schema: Schema) -> dt.DType:
        return dt.DATE

    def eval(self, batch: ColumnarBatch) -> ColumnVector:
        y = self.children[0].eval(batch)
        m = self.children[1].eval(batch)
        d = self.children[2].eval(batch)
        validity = merged_validity(y, m, d)
        ok = (m.data >= 1) & (m.data <= 12) & (d.data >= 1) & (d.data <= 31)
        days = _days_from_civil(y.data.astype(jnp.int64), m.data.astype(jnp.int64),
                                d.data.astype(jnp.int64))
        return make_result(days.astype(jnp.int32), validity & ok, dt.DATE)
